"""Native (C++) component tests: xxhash parity, pickers, and the operator
binary reconciling against a fake Kubernetes API server."""

import asyncio
import json
import os
import shutil
import subprocess

import pytest
import xxhash
from aiohttp import web

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD_DIR = os.path.join(REPO, "native", "build")


@pytest.fixture(scope="session", autouse=True)
def build_native():
    if not shutil.which("cmake"):
        pytest.skip("cmake not available")
    subprocess.run(
        ["cmake", "-S", os.path.join(REPO, "native"), "-B", BUILD_DIR,
         "-G", "Ninja" if shutil.which("ninja") else "Unix Makefiles"],
        check=True, capture_output=True,
    )
    subprocess.run(
        ["cmake", "--build", BUILD_DIR], check=True, capture_output=True,
    )
    os.environ["TPU_STACK_NATIVE_LIB"] = BUILD_DIR
    # Force a re-probe after setting the env var.
    import production_stack_tpu.native as native

    native._load_attempted = False
    native._lib = None
    assert native.available()


def test_xxhash64_parity():
    from production_stack_tpu.native import xxhash64

    cases = [b"", b"a", b"abc", b"abcd", b"12345678", b"x" * 17,
             b"y" * 31, b"z" * 32, b"w" * 33, b"q" * 100, b"m" * 1000,
             "unicode ✓ text".encode()]
    for data in cases:
        assert xxhash64(data) == xxhash.xxh64_intdigest(data), data


def test_native_roundrobin():
    from production_stack_tpu.native import NativePicker

    p = NativePicker()
    p.set_endpoints(["http://b", "http://a", "http://c"])
    picks = [p.pick_roundrobin() for _ in range(6)]
    assert picks[:3] == ["http://a", "http://b", "http://c"]  # sorted order
    assert picks[3:] == picks[:3]


def test_native_prefix_stickiness():
    from production_stack_tpu.native import NativePicker

    p = NativePicker()
    p.set_endpoints(["http://e1", "http://e2", "http://e3", "http://e4"])
    prompt = "shared system prompt " * 20  # several 128-char chunks
    first = p.pick_prefix(prompt + "user A")
    # Same long prefix must route to the same endpoint.
    for suffix in ("user B", "user C", "user D"):
        assert p.pick_prefix(prompt + suffix) == first


def test_native_prefix_respects_endpoint_removal():
    from production_stack_tpu.native import NativePicker

    p = NativePicker()
    p.set_endpoints(["http://e1", "http://e2"])
    prompt = "p" * 300
    first = p.pick_prefix(prompt)
    p.remove_endpoint(first)
    remaining = [e for e in ("http://e1", "http://e2") if e != first]
    p.set_endpoints(remaining)
    assert p.pick_prefix(prompt) == remaining[0]


def test_native_kv_aware():
    from production_stack_tpu.native import NativePicker

    p = NativePicker()
    p.set_endpoints(["http://e1", "http://e2"])
    prompt = "k" * 400  # 4 chunks of 128 -> 3 full + remainder
    hashes = [
        xxhash.xxh64_intdigest(prompt[i:i + 128])
        for i in range(0, len(prompt), 128)
    ]
    endpoint, matched = p.pick_kv(prompt)
    assert endpoint is None and matched == 0
    p.kv_admit("http://e2", hashes)
    endpoint, matched = p.pick_kv(prompt)
    assert endpoint == "http://e2"
    assert matched == len(prompt)
    # Dead endpoints are filtered out.
    p.set_endpoints(["http://e1"])
    endpoint, _ = p.pick_kv(prompt)
    assert endpoint is None


# --------------------------------------------------------------------- #
# Operator binary against a fake K8s API server
# --------------------------------------------------------------------- #


class FakeK8s:
    """Tiny in-memory Kubernetes API server covering what the operator
    uses: CR lists + WATCH streams, deployments, services,
    serviceaccounts, pods, status subresources, and coordination.k8s.io
    Leases (with resourceVersion optimistic concurrency)."""

    def __init__(self):
        self.objects = {}  # path -> body dict
        self.crs = {}      # plural -> [cr dicts]
        self.pods = []
        self.status_updates = []
        self.leases = {}   # name -> lease dict
        self._lease_rv = 0
        self._watchers = []  # asyncio.Queue of event lines

    def emit_watch_event(self, event_type: str, obj: dict) -> None:
        line = json.dumps({"type": event_type, "object": obj})
        for q in list(self._watchers):
            q.put_nowait(line)

    def make_app(self):
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.handle)
        return app

    async def _serve_watch(self, request: web.Request):
        """Chunked watch stream: emits queued event lines until the
        client's timeoutSeconds elapses or it disconnects."""
        timeout = float(request.query.get("timeoutSeconds", "30"))
        resp = web.StreamResponse()
        resp.enable_chunked_encoding()
        resp.content_type = "application/json"
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append(q)
        deadline = asyncio.get_running_loop().time() + timeout
        try:
            while True:
                remain = deadline - asyncio.get_running_loop().time()
                if remain <= 0:
                    break
                try:
                    line = await asyncio.wait_for(q.get(), timeout=remain)
                except asyncio.TimeoutError:
                    break
                await resp.write(line.encode() + b"\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self._watchers.remove(q)
        try:
            await resp.write_eof()
        except ConnectionResetError:
            pass
        return resp

    def _handle_lease(self, request, path, method, body):
        name = path.rstrip("/").split("/")[-1]
        if method == "GET":
            if name in self.leases:
                return web.json_response(self.leases[name])
            return web.json_response({"reason": "NotFound"}, status=404)
        if method == "POST":
            lease_name = body["metadata"]["name"]
            if lease_name in self.leases:
                return web.json_response(
                    {"reason": "AlreadyExists"}, status=409)
            self._lease_rv += 1
            body["metadata"]["resourceVersion"] = str(self._lease_rv)
            self.leases[lease_name] = body
            return web.json_response(body, status=201)
        if method == "PUT":
            existing = self.leases.get(name)
            if existing is None:
                return web.json_response({"reason": "NotFound"}, status=404)
            sent_rv = body.get("metadata", {}).get("resourceVersion")
            if sent_rv != existing["metadata"]["resourceVersion"]:
                # Optimistic concurrency: stale writers lose.
                return web.json_response({"reason": "Conflict"}, status=409)
            self._lease_rv += 1
            body["metadata"]["resourceVersion"] = str(self._lease_rv)
            self.leases[name] = body
            return web.json_response(body)
        return web.json_response({}, status=405)

    async def handle(self, request: web.Request) -> web.Response:
        path = "/" + request.match_info["tail"]
        method = request.method
        if "/leases" in path:
            body = (json.loads(await request.text())
                    if method in ("POST", "PUT") else None)
            return self._handle_lease(request, path, method, body)
        if "/pods" in path and method == "GET":
            return web.json_response({"items": self.pods})
        if "production-stack.tpu" in path:
            if method == "GET" and request.query.get("watch") == "true":
                return await self._serve_watch(request)
            parts = path.rstrip("/").split("/")
            if path.endswith("/status") and method == "PUT":
                body = json.loads(await request.text())
                self.status_updates.append((path, body))
                return web.json_response(body)
            plural = parts[-1]
            if method == "GET" and plural in self.crs:
                return web.json_response({"items": self.crs[plural]})
            if method == "PUT" and parts[-2] in self.crs:
                # Update of an individual CR (finalizers, spec edits).
                body = json.loads(await request.text())
                items = self.crs[parts[-2]]
                for i, cr in enumerate(items):
                    if cr["metadata"]["name"] == parts[-1]:
                        items[i] = body
                        return web.json_response(body)
                return web.json_response({"reason": "NotFound"}, status=404)
            return web.json_response({"items": []})
        # Core objects (deployments/services/serviceaccounts).
        if method == "GET":
            if path in self.objects:
                return web.json_response(self.objects[path])
            return web.json_response({"reason": "NotFound"}, status=404)
        if method == "POST":
            body = json.loads(await request.text())
            name = body["metadata"]["name"]
            self.objects[path + "/" + name] = body
            return web.json_response(body, status=201)
        if method == "PUT":
            body = json.loads(await request.text())
            self.objects[path] = body
            return web.json_response(body)
        return web.json_response({}, status=405)


def _run_operator(api_url: str):
    binary = os.path.join(BUILD_DIR, "tpu-stack-operator")
    return subprocess.run(
        [binary, "--api-base", api_url, "--namespace", "default", "--once"],
        capture_output=True, timeout=60,
    )


def test_operator_reconciles_tpuruntime():
    fake = FakeK8s()
    fake.crs["tpuruntimes"] = [{
        "metadata": {"name": "llama8b", "uid": "uid-1"},
        "spec": {
            "model": "meta-llama/Llama-3-8B",
            "replicas": 2,
            "port": 8000,
            "tensorParallelSize": 8,
            "maxModelLen": 4096,
            "tpu": {"chips": 8, "accelerator": "tpu-v5-lite-podslice",
                    "topology": "2x4"},
        },
    }]

    async def run():
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}"
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, url)
        await runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr

    dep_key = "/apis/apps/v1/namespaces/default/deployments/llama8b-engine"
    assert dep_key in fake.objects, list(fake.objects)
    dep = fake.objects[dep_key]
    assert dep["spec"]["replicas"] == 2
    container = dep["spec"]["template"]["spec"]["containers"][0]
    cmd = container["command"]
    assert "production_stack_tpu.engine.server" in cmd
    assert "meta-llama/Llama-3-8B" in cmd
    assert "--tensor-parallel-size" in cmd and "8" in cmd
    # TPU resources, not nvidia.com/gpu.
    assert container["resources"]["limits"] == {"google.com/tpu": 8}
    sel = dep["spec"]["template"]["spec"]["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x4"
    assert sel["cloud.google.com/gke-tpu-accelerator"] == \
        "tpu-v5-lite-podslice"
    # Service + status update happened.
    svc_key = "/api/v1/namespaces/default/services/llama8b-engine-service"
    assert svc_key in fake.objects
    assert any("tpuruntimes/llama8b/status" in p
               for p, _ in fake.status_updates)


def test_operator_reconciles_router_and_cache():
    fake = FakeK8s()
    fake.crs["tpurouters"] = [{
        "metadata": {"name": "rt", "uid": "uid-2"},
        "spec": {"replicas": 1, "port": 8080, "routingLogic": "roundrobin",
                 "serviceDiscovery": "k8s"},
    }]
    fake.crs["cacheservers"] = [{
        "metadata": {"name": "kvc", "uid": "uid-3"},
        "spec": {"replicas": 1, "port": 8200, "capacityGb": 16},
    }]

    async def run():
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{port}")
        await runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr

    router_dep = fake.objects[
        "/apis/apps/v1/namespaces/default/deployments/rt-router"]
    cmd = router_dep["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "production_stack_tpu.router.app" in cmd
    assert "--routing-logic" in cmd and "roundrobin" in cmd
    assert "/api/v1/namespaces/default/serviceaccounts/rt-sa" in fake.objects

    cache_dep = fake.objects[
        "/apis/apps/v1/namespaces/default/deployments/kvc-cache"]
    ccmd = cache_dep["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "production_stack_tpu.kv.cache_server" in ccmd


def test_operator_detects_drift():
    fake = FakeK8s()
    fake.crs["tpuruntimes"] = [{
        "metadata": {"name": "m", "uid": "u"},
        "spec": {"model": "tiny-llama", "replicas": 3, "port": 8000},
    }]
    # Pre-existing deployment with stale replicas.
    dep_key = "/apis/apps/v1/namespaces/default/deployments/m-engine"
    fake.objects[dep_key] = {
        "metadata": {"name": "m-engine", "resourceVersion": "42"},
        "spec": {
            "replicas": 1,
            "template": {"spec": {"containers": [{
                "name": "engine", "image": "production-stack-tpu:latest",
                "command": ["stale"],
            }]}},
        },
    }

    async def run():
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{port}")
        await runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    dep = fake.objects[dep_key]
    assert dep["spec"]["replicas"] == 3  # drift corrected
    assert dep["metadata"]["resourceVersion"] == "42"  # carried over


def test_operator_detects_resource_drift():
    """A TPU-chips edit on the CR must reconcile even when replicas, image
    and command all match (the reference compares resources/env too,
    vllmruntime_controller.go:624-706)."""
    fake = FakeK8s()
    fake.crs["tpuruntimes"] = [{
        "metadata": {"name": "m", "uid": "u"},
        "spec": {"model": "tiny-llama", "replicas": 1, "port": 8000,
                 "tpu": {"chips": 8}},
    }]

    async def boot(expected_chips):
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{port}")
        await runner.cleanup()
        assert proc.returncode == 0, proc.stderr
        dep_key = "/apis/apps/v1/namespaces/default/deployments/m-engine"
        c = fake.objects[dep_key]["spec"]["template"]["spec"]["containers"][0]
        limits = c["resources"]["limits"]
        assert float(limits["google.com/tpu"]) == expected_chips

    asyncio.run(boot(8))
    # The API server normalizes quantities to strings; same value must NOT
    # count as drift (no infinite update loop) ...
    dep_key = "/apis/apps/v1/namespaces/default/deployments/m-engine"
    c = fake.objects[dep_key]["spec"]["template"]["spec"]["containers"][0]
    c["resources"] = {"requests": {"google.com/tpu": "8"},
                      "limits": {"google.com/tpu": "8"}}
    before = json.dumps(fake.objects[dep_key], sort_keys=True)
    asyncio.run(boot(8))
    assert json.dumps(fake.objects[dep_key], sort_keys=True) == before

    # ... but a chips edit is drift and must be corrected.
    fake.crs["tpuruntimes"][0]["spec"]["tpu"]["chips"] = 4
    asyncio.run(boot(4))


def test_operator_loads_lora_adapters():
    fake = FakeK8s()
    lora_calls = []

    engine_app = web.Application()

    async def load_lora(request):
        lora_calls.append(await request.json())
        return web.json_response({"status": "ok"})

    engine_app.router.add_post("/v1/load_lora_adapter", load_lora)

    async def run():
        eng_runner = web.AppRunner(engine_app)
        await eng_runner.setup()
        eng_site = web.TCPSite(eng_runner, "127.0.0.1", 0)
        await eng_site.start()
        eng_port = eng_site._server.sockets[0].getsockname()[1]

        fake.crs["loraadapters"] = [{
            "metadata": {"name": "ad1", "uid": "u-l"},
            "spec": {"adapterName": "sql-adapter", "runtimeName": "m",
                     "rank": 8, "port": eng_port},
        }]
        fake.pods = [{
            "metadata": {"name": "m-pod-1", "labels": {"app": "m"}},
            "status": {"podIP": "127.0.0.1", "phase": "Running"},
        }]

        api_runner = web.AppRunner(fake.make_app())
        await api_runner.setup()
        api_site = web.TCPSite(api_runner, "127.0.0.1", 0)
        await api_site.start()
        api_port = api_site._server.sockets[0].getsockname()[1]

        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{api_port}")
        await api_runner.cleanup()
        await eng_runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    assert lora_calls == [{"lora_name": "sql-adapter", "lora_rank": 8}]
    assert any("loraadapters/ad1/status" in p and
               b["status"]["phase"] == "Loaded"
               for p, b in fake.status_updates)
    # A finalizer was installed so deletion can unload first
    # (ref loraadapter_controller.go:94-110).
    assert fake.crs["loraadapters"][0]["metadata"]["finalizers"] == \
        ["loraadapter.production-stack.tpu/finalizer"]


class _FakeEnginePod:
    """In-process engine pod exposing the LoRA HTTP API the operator
    drives, pre-seeded with already-loaded adapters."""

    def __init__(self, preloaded=()):
        self.adapters = list(preloaded)
        self.loads = []
        self.unloads = []
        self.app = web.Application()
        self.app.router.add_post("/v1/load_lora_adapter", self._load)
        self.app.router.add_post("/v1/unload_lora_adapter", self._unload)
        self.app.router.add_get("/v1/lora_adapters", self._list)
        self.app.router.add_post("/model/download", self._download)
        self.downloads = []
        self.runner = None
        self.port = None

    async def _load(self, request):
        body = await request.json()
        self.loads.append(body)
        if body["lora_name"] not in self.adapters:
            self.adapters.append(body["lora_name"])
        return web.json_response({"status": "ok"})

    async def _unload(self, request):
        body = await request.json()
        self.unloads.append(body)
        if body["lora_name"] in self.adapters:
            self.adapters.remove(body["lora_name"])
        return web.json_response({"status": "ok"})

    async def _list(self, request):
        return web.json_response({"adapters": [
            {"lora_name": n, "slot": i}
            for i, n in enumerate(self.adapters)
        ]})

    async def _download(self, request):
        body = await request.json()
        self.downloads.append(body)
        return web.json_response(
            {"path": "/models/" + body["model_id"].replace("/", "-")})

    async def start(self):
        self.runner = web.AppRunner(self.app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self):
        await self.runner.cleanup()


def test_operator_lora_unload_on_delete_removes_finalizer():
    """A deleting CR (deletionTimestamp set) unloads the adapter from the
    pods that hold it, then drops the finalizer
    (ref loraadapter_controller.go:869-900)."""
    pod = _FakeEnginePod(preloaded=["sql-adapter", "other"])
    fake = FakeK8s()

    async def setup_and_run():
        await pod.start()
        fake.crs["loraadapters"] = [{
            "metadata": {
                "name": "ad1", "uid": "u-l",
                "deletionTimestamp": "2026-07-30T00:00:00Z",
                "finalizers": [
                    "loraadapter.production-stack.tpu/finalizer",
                    "someone-elses/finalizer",
                ],
            },
            "spec": {"adapterName": "sql-adapter", "runtimeName": "m",
                     "port": pod.port},
        }]
        fake.pods = [{
            "metadata": {"name": "m-pod-0", "labels": {"app": "m"}},
            "status": {"podIP": "127.0.0.1", "phase": "Running"},
        }]
        api_runner = web.AppRunner(fake.make_app())
        await api_runner.setup()
        api_site = web.TCPSite(api_runner, "127.0.0.1", 0)
        await api_site.start()
        api_port = api_site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{api_port}")
        await api_runner.cleanup()
        await pod.stop()
        return proc

    proc = asyncio.run(setup_and_run())
    assert proc.returncode == 0, proc.stderr
    assert pod.unloads == [{"lora_name": "sql-adapter"}]
    assert pod.adapters == ["other"]
    # Our finalizer gone, foreign finalizer untouched.
    assert fake.crs["loraadapters"][0]["metadata"]["finalizers"] == \
        ["someone-elses/finalizer"]
    assert pod.loads == []


def test_operator_lora_equalized_placement_and_unload():
    """algorithm=equalized with replicas=2 must target the two pods with
    the fewest other adapters and unload from a stale third pod
    (ref placement enum loraadapter_types.go:70-79 +
    reconcileToDesiredState :582-610)."""
    # pod0 is busy (2 other adapters), pod1 empty, pod2 holds a stale copy.
    pods = [
        _FakeEnginePod(preloaded=["a1", "a2"]),
        _FakeEnginePod(),
        _FakeEnginePod(preloaded=["x1", "x2", "x3", "sql-adapter"]),
    ]
    fake = FakeK8s()

    # The CR carries ONE port while pods differ by IP, so each fake pod
    # binds the same port on its own loopback alias (127.0.0.2/.3 bind on
    # Linux without setup).
    async def run():
        addrs = ["127.0.0.1", "127.0.0.2", "127.0.0.3"]
        runners = []
        port = None
        for addr, p in zip(addrs, pods):
            runner = web.AppRunner(p.app)
            await runner.setup()
            site = web.TCPSite(runner, addr, port or 0)
            await site.start()
            if port is None:
                port = site._server.sockets[0].getsockname()[1]
            p.port = port
            runners.append(runner)
        fake.crs["loraadapters"] = [{
            "metadata": {"name": "ad1", "uid": "u-l",
                         "finalizers": [
                             "loraadapter.production-stack.tpu/finalizer"]},
            "spec": {"adapterName": "sql-adapter", "runtimeName": "m",
                     "port": port,
                     "deploymentConfig": {"algorithm": "equalized",
                                          "replicas": 2}},
        }]
        fake.pods = [{
            "metadata": {"name": f"m-pod-{i}", "labels": {"app": "m"}},
            "status": {"podIP": addr, "phase": "Running"},
        } for i, addr in enumerate(addrs)]
        api_runner = web.AppRunner(fake.make_app())
        await api_runner.setup()
        api_site = web.TCPSite(api_runner, "127.0.0.1", 0)
        await api_site.start()
        api_port = api_site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{api_port}")
        await api_runner.cleanup()
        for r in runners:
            await r.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    # pod1 (0 adapters) and pod2 (3 other adapters but already holding the
    # adapter -> effective load 3) vs pod0 (2 others): equalized order is
    # pod1(0), pod0(2), pod2(3) -> desired = {pod1, pod0}.
    assert [c["lora_name"] for c in pods[1].loads] == ["sql-adapter"]
    assert [c["lora_name"] for c in pods[0].loads] == ["sql-adapter"]
    # Stale copy on pod2 dropped.
    assert pods[2].unloads == [{"lora_name": "sql-adapter"}]
    assert "sql-adapter" not in pods[2].adapters
    st = [b for p, b in fake.status_updates
          if "loraadapters/ad1/status" in p][-1]
    assert st["status"]["loadedOn"] == 2
    assert sorted(st["status"]["loadedAdapters"]) == ["m-pod-0", "m-pod-1"]


def test_operator_lora_ordered_placement_is_deterministic():
    """algorithm=ordered picks the lexicographically-first N pod names."""
    pods = [_FakeEnginePod(), _FakeEnginePod()]
    fake = FakeK8s()

    async def run():
        addrs = ["127.0.0.2", "127.0.0.1"]  # API order != name order
        runners = []
        port = None
        for addr, p in zip(addrs, pods):
            runner = web.AppRunner(p.app)
            await runner.setup()
            site = web.TCPSite(runner, addr, port or 0)
            await site.start()
            if port is None:
                port = site._server.sockets[0].getsockname()[1]
            runners.append(runner)
        fake.crs["loraadapters"] = [{
            "metadata": {"name": "ad1", "uid": "u-l",
                         "finalizers": [
                             "loraadapter.production-stack.tpu/finalizer"]},
            "spec": {"adapterName": "sql-adapter", "runtimeName": "m",
                     "port": port,
                     "deploymentConfig": {"algorithm": "ordered",
                                          "replicas": 1}},
        }]
        # API returns m-pod-9 first; ordered placement must pick m-pod-1.
        fake.pods = [
            {"metadata": {"name": "m-pod-9", "labels": {"app": "m"}},
             "status": {"podIP": addrs[0], "phase": "Running"}},
            {"metadata": {"name": "m-pod-1", "labels": {"app": "m"}},
             "status": {"podIP": addrs[1], "phase": "Running"}},
        ]
        api_runner = web.AppRunner(fake.make_app())
        await api_runner.setup()
        api_site = web.TCPSite(api_runner, "127.0.0.1", 0)
        await api_site.start()
        api_port = api_site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{api_port}")
        await api_runner.cleanup()
        for r in runners:
            await r.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    assert [c["lora_name"] for c in pods[1].loads] == ["sql-adapter"]
    assert pods[0].loads == []


def test_operator_lora_huggingface_download_flow():
    """source.type=huggingface drives the downloader sidecar and persists
    adapterPath on the CR spec (ref loraadapter_controller.go:334-390)."""
    pod = _FakeEnginePod()
    fake = FakeK8s()

    async def run():
        await pod.start()
        fake.crs["loraadapters"] = [{
            "metadata": {"name": "ad1", "uid": "u-l",
                         "finalizers": [
                             "loraadapter.production-stack.tpu/finalizer"]},
            "spec": {"adapterName": "sql-adapter", "runtimeName": "m",
                     "port": pod.port,
                     "source": {"type": "huggingface",
                                "repository": "org/sql-lora",
                                "sidecarPort": pod.port}},
        }]
        fake.pods = [{
            "metadata": {"name": "m-pod-0", "labels": {"app": "m"}},
            "status": {"podIP": "127.0.0.1", "phase": "Running"},
        }]
        api_runner = web.AppRunner(fake.make_app())
        await api_runner.setup()
        api_site = web.TCPSite(api_runner, "127.0.0.1", 0)
        await api_site.start()
        api_port = api_site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{api_port}")
        await api_runner.cleanup()
        await pod.stop()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    assert pod.downloads == [{"model_id": "org/sql-lora"}]
    # The discovered path is passed to the engine and persisted on the CR.
    assert pod.loads[0]["lora_path"] == "/models/org-sql-lora"
    assert fake.crs["loraadapters"][0]["spec"]["source"]["adapterPath"] == \
        "/models/org-sql-lora"


def test_operator_lora_hf_download_preserves_fresh_finalizer():
    """The adapterPath-persisting PUT must build on the CR as updated by
    the same pass's finalizer PUT — a stale copy would strip the finalizer
    just installed (regression: review finding on lora_resolve_path)."""
    pod = _FakeEnginePod()
    fake = FakeK8s()

    async def run():
        await pod.start()
        # CR starts with NO finalizer: the operator adds one, then the
        # download flow persists adapterPath; both must survive.
        fake.crs["loraadapters"] = [{
            "metadata": {"name": "ad1", "uid": "u-l"},
            "spec": {"adapterName": "sql-adapter", "runtimeName": "m",
                     "port": pod.port,
                     "source": {"type": "huggingface",
                                "repository": "org/sql-lora",
                                "sidecarPort": pod.port}},
        }]
        fake.pods = [{
            "metadata": {"name": "m-pod-0", "labels": {"app": "m"}},
            "status": {"podIP": "127.0.0.1", "phase": "Running"},
        }]
        api_runner = web.AppRunner(fake.make_app())
        await api_runner.setup()
        api_site = web.TCPSite(api_runner, "127.0.0.1", 0)
        await api_site.start()
        api_port = api_site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{api_port}")
        await api_runner.cleanup()
        await pod.stop()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    cr = fake.crs["loraadapters"][0]
    assert cr["metadata"]["finalizers"] == \
        ["loraadapter.production-stack.tpu/finalizer"]
    assert cr["spec"]["source"]["adapterPath"] == "/models/org-sql-lora"


def test_operator_lora_defers_finalizer_when_unload_fails():
    """A deleting CR whose engine pod is unreachable must KEEP the
    finalizer (unload-on-delete is the finalizer's whole guarantee);
    removal happens only once every unload provably succeeded."""
    fake = FakeK8s()

    async def run():
        fake.crs["loraadapters"] = [{
            "metadata": {
                "name": "ad1", "uid": "u-l",
                "deletionTimestamp": "2026-07-30T00:00:00Z",
                "finalizers": [
                    "loraadapter.production-stack.tpu/finalizer"],
            },
            # Port 1 is never listening -> unload cannot be confirmed.
            "spec": {"adapterName": "sql-adapter", "runtimeName": "m",
                     "port": 1},
        }]
        fake.pods = [{
            "metadata": {"name": "m-pod-0", "labels": {"app": "m"}},
            "status": {"podIP": "127.0.0.1", "phase": "Running"},
        }]
        api_runner = web.AppRunner(fake.make_app())
        await api_runner.setup()
        api_site = web.TCPSite(api_runner, "127.0.0.1", 0)
        await api_site.start()
        api_port = api_site._server.sockets[0].getsockname()[1]
        proc = await asyncio.get_running_loop().run_in_executor(
            None, _run_operator, f"http://127.0.0.1:{api_port}")
        await api_runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    assert fake.crs["loraadapters"][0]["metadata"]["finalizers"] == \
        ["loraadapter.production-stack.tpu/finalizer"]


# --------------------------------------------------------------------- #
# Operator transport hardening: bearer auth + TLS (round 3)
# --------------------------------------------------------------------- #


def _minimal_runtime_cr():
    return [{
        "metadata": {"name": "auth-rt", "uid": "uid-a", "generation": 1},
        "spec": {"model": "tiny-llama", "replicas": 1, "port": 8000},
    }]


def test_operator_sends_bearer_token(tmp_path):
    """Every API request carries Authorization: Bearer <token> when a
    token file is configured (ServiceAccount transport, ref
    operator/cmd/main.go in-cluster rest.Config)."""
    fake = FakeK8s()
    fake.crs["tpuruntimes"] = _minimal_runtime_cr()
    seen = []
    inner = fake.handle

    async def capture(request):
        seen.append(request.headers.get("Authorization"))
        return await inner(request)

    fake.handle = capture
    token_file = tmp_path / "token"
    token_file.write_text("sekret-rotating-token\n")

    async def run():
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        binary = os.path.join(BUILD_DIR, "tpu-stack-operator")
        proc = await asyncio.get_running_loop().run_in_executor(
            None, lambda: subprocess.run(
                [binary, "--api-base", f"http://127.0.0.1:{port}",
                 "--namespace", "default", "--once",
                 "--token-file", str(token_file)],
                capture_output=True, timeout=60))
        await runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    assert seen and all(h == "Bearer sekret-rotating-token" for h in seen)
    dep_key = "/apis/apps/v1/namespaces/default/deployments/auth-rt-engine"
    assert dep_key in fake.objects


def test_operator_https_verified(tmp_path):
    """The operator reconciles over TLS with server-cert verification
    against a CA file (direct apiserver transport, no proxy sidecar)."""
    import ssl

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "2",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True, timeout=60)

    fake = FakeK8s()
    fake.crs["tpuruntimes"] = _minimal_runtime_cr()
    token_file = tmp_path / "token"
    token_file.write_text("tls-token")
    seen = []
    inner = fake.handle

    async def capture(request):
        seen.append(request.headers.get("Authorization"))
        return await inner(request)

    fake.handle = capture

    async def run():
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(str(cert), str(key))
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0, ssl_context=ctx)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        binary = os.path.join(BUILD_DIR, "tpu-stack-operator")
        proc = await asyncio.get_running_loop().run_in_executor(
            None, lambda: subprocess.run(
                [binary, "--api-base", f"https://127.0.0.1:{port}",
                 "--namespace", "default", "--once",
                 "--token-file", str(token_file),
                 "--ca-file", str(cert)],
                capture_output=True, timeout=60))
        await runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0, proc.stderr
    dep_key = "/apis/apps/v1/namespaces/default/deployments/auth-rt-engine"
    assert dep_key in fake.objects, (proc.stderr, list(fake.objects))
    assert seen and all(h == "Bearer tls-token" for h in seen)


def test_operator_https_rejects_untrusted_ca(tmp_path):
    """Verification is real: a server whose cert is NOT in the CA bundle
    must get zero successful reconciliation writes."""
    import ssl

    for stem in ("good", "bad"):
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(tmp_path / f"{stem}.key"),
             "-out", str(tmp_path / f"{stem}.pem"), "-days", "2",
             "-subj", "/CN=127.0.0.1",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True, timeout=60)

    fake = FakeK8s()
    fake.crs["tpuruntimes"] = _minimal_runtime_cr()

    async def run():
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(str(tmp_path / "bad.pem"),
                            str(tmp_path / "bad.key"))
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0, ssl_context=ctx)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        binary = os.path.join(BUILD_DIR, "tpu-stack-operator")
        proc = await asyncio.get_running_loop().run_in_executor(
            None, lambda: subprocess.run(
                [binary, "--api-base", f"https://127.0.0.1:{port}",
                 "--namespace", "default", "--once",
                 "--ca-file", str(tmp_path / "good.pem")],
                capture_output=True, timeout=60))
        await runner.cleanup()
        return proc

    proc = asyncio.run(run())
    assert proc.returncode == 0
    assert not fake.objects  # handshake refused -> nothing written


def _start_operator(api_url: str, *extra):
    binary = os.path.join(BUILD_DIR, "tpu-stack-operator")
    return subprocess.Popen(
        [binary, "--api-base", api_url, "--namespace", "default",
         "--health-port", "0", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def test_operator_watch_event_reconciles_within_a_second():
    """The apiserver watch stream wakes the reconcile loop immediately:
    with an effectively-infinite poll interval, a CR added + watch event
    emitted must materialize its Deployment in well under the interval
    (ref: controller-runtime informers vs the old adaptive polling)."""
    fake = FakeK8s()
    fake.crs["tpuruntimes"] = [{
        "metadata": {"name": "first", "uid": "uid-1"},
        "spec": {"model": "tiny-llama", "replicas": 1, "port": 8000},
    }]

    async def run():
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}"
        proc = _start_operator(url, "--interval", "600",
                               "--max-interval", "600")
        try:
            # Initial pass (runs immediately at startup).
            for _ in range(100):
                if any(k.endswith("first-engine") for k in fake.objects):
                    break
                await asyncio.sleep(0.05)
            assert any(k.endswith("first-engine") for k in fake.objects)

            # Let the operator settle into its 600 s wait + its watch
            # streams connect.
            await asyncio.sleep(1.0)

            new_cr = {
                "metadata": {"name": "second", "uid": "uid-2",
                             "resourceVersion": "7"},
                "spec": {"model": "tiny-llama", "replicas": 1,
                         "port": 8000},
            }
            fake.crs["tpuruntimes"].append(new_cr)
            t0 = asyncio.get_running_loop().time()
            fake.emit_watch_event("ADDED", new_cr)
            deadline = t0 + 2.0
            while asyncio.get_running_loop().time() < deadline:
                if any(k.endswith("second-engine") for k in fake.objects):
                    break
                await asyncio.sleep(0.02)
            latency = asyncio.get_running_loop().time() - t0
            assert any(k.endswith("second-engine") for k in fake.objects), \
                "watch event did not trigger a reconcile"
            assert latency < 1.0, f"event->reconcile took {latency:.2f}s"
        finally:
            proc.kill()
            proc.wait(timeout=10)
            await runner.cleanup()

    asyncio.run(run())


def test_operator_leader_election_standby_and_failover():
    """With --leader-elect only the lease holder reconciles; a standby
    replica takes over once the holder's lease expires (ref
    operator/cmd/main.go EnableLeaderElection)."""
    fake = FakeK8s()
    fake.crs["tpuruntimes"] = [{
        "metadata": {"name": "m", "uid": "uid-1"},
        "spec": {"model": "tiny-llama", "replicas": 1, "port": 8000},
    }]

    async def run():
        runner = web.AppRunner(fake.make_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}"
        flags = ("--leader-elect", "--lease-duration", "2",
                 "--interval", "1", "--max-interval", "1")
        a = _start_operator(url, "--identity", "op-a", *flags)
        b = None
        try:
            # A acquires the lease and reconciles.
            for _ in range(200):
                if (fake.leases.get("tpu-stack-operator", {}).get(
                        "spec", {}).get("holderIdentity") == "op-a"
                        and any(k.endswith("m-engine")
                                for k in fake.objects)):
                    break
                await asyncio.sleep(0.05)
            assert fake.leases["tpu-stack-operator"]["spec"][
                "holderIdentity"] == "op-a"

            # B starts as standby: with the deployment deleted it must
            # NOT recreate it while A holds the lease.
            b = _start_operator(url, "--identity", "op-b", *flags)
            await asyncio.sleep(1.0)  # B is up and observing
            fake.objects = {k: v for k, v in fake.objects.items()
                            if not k.endswith("m-engine")}
            await asyncio.sleep(1.0)
            # A (the leader) recreates it; kill A and delete again to
            # isolate B's standby behavior.
            a.kill()
            a.wait(timeout=10)
            fake.objects = {k: v for k, v in fake.objects.items()
                            if not k.endswith("m-engine")}
            await asyncio.sleep(0.8)  # < lease duration: B still standby
            assert not any(k.endswith("m-engine") for k in fake.objects), \
                "standby replica acted while the lease was live"

            # Lease expires -> B acquires and reconciles.
            for _ in range(200):
                if any(k.endswith("m-engine") for k in fake.objects):
                    break
                await asyncio.sleep(0.05)
            assert any(k.endswith("m-engine") for k in fake.objects), \
                "standby never took over after lease expiry"
            assert fake.leases["tpu-stack-operator"]["spec"][
                "holderIdentity"] == "op-b"
        finally:
            if b is not None:
                b.kill()
                b.wait(timeout=10)
            if a.poll() is None:
                a.kill()
                a.wait(timeout=10)
            await runner.cleanup()

    asyncio.run(run())
