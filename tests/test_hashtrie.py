"""HashTrie unit tests (cf. reference src/vllm_router/prefix/hashtrie.py)."""

from production_stack_tpu.router.hashtrie import HashTrie


async def test_insert_and_match():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcdefgh", "e1")
    matched, eps = await trie.longest_prefix_match("abcdefgh", {"e1", "e2"})
    assert matched == 2
    assert eps == {"e1"}


async def test_no_match_returns_all_available():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcdefgh", "e1")
    matched, eps = await trie.longest_prefix_match("zzzz", {"e1", "e2"})
    assert matched == 0
    assert eps == {"e1", "e2"}


async def test_partial_prefix_match():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcd1234", "e1")
    await trie.insert("abcdXXXX", "e2")
    matched, eps = await trie.longest_prefix_match("abcd1234", {"e1", "e2"})
    assert matched == 2 and eps == {"e1"}
    matched, eps = await trie.longest_prefix_match("abcdZZZZ", {"e1", "e2"})
    assert matched == 1 and eps == {"e1", "e2"}


async def test_dead_endpoint_excluded():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcd", "dead")
    matched, eps = await trie.longest_prefix_match("abcd", {"live"})
    assert matched == 0
    assert eps == {"live"}


async def test_remove_endpoint():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcd", "e1")
    await trie.remove_endpoint("e1")
    matched, eps = await trie.longest_prefix_match("abcd", {"e1"})
    assert matched == 0


async def test_eviction_bounds_nodes():
    trie = HashTrie(chunk_size=4, max_nodes=50)
    for i in range(100):
        await trie.insert(f"pref{i:04d}suffix{i:04d}", "e1")
    assert trie.node_count <= 60
