"""HashTrie unit tests (cf. reference src/vllm_router/prefix/hashtrie.py)."""

from production_stack_tpu.router.hashtrie import HashTrie


async def test_insert_and_match():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcdefgh", "e1")
    matched, eps = await trie.longest_prefix_match("abcdefgh", {"e1", "e2"})
    assert matched == 2
    assert eps == {"e1"}


async def test_no_match_returns_all_available():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcdefgh", "e1")
    matched, eps = await trie.longest_prefix_match("zzzz", {"e1", "e2"})
    assert matched == 0
    assert eps == {"e1", "e2"}


async def test_partial_prefix_match():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcd1234", "e1")
    await trie.insert("abcdXXXX", "e2")
    matched, eps = await trie.longest_prefix_match("abcd1234", {"e1", "e2"})
    assert matched == 2 and eps == {"e1"}
    matched, eps = await trie.longest_prefix_match("abcdZZZZ", {"e1", "e2"})
    assert matched == 1 and eps == {"e1", "e2"}


async def test_dead_endpoint_excluded():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcd", "dead")
    matched, eps = await trie.longest_prefix_match("abcd", {"live"})
    assert matched == 0
    assert eps == {"live"}


async def test_remove_endpoint():
    trie = HashTrie(chunk_size=4)
    await trie.insert("abcd", "e1")
    await trie.remove_endpoint("e1")
    matched, eps = await trie.longest_prefix_match("abcd", {"e1"})
    assert matched == 0


async def test_eviction_bounds_nodes():
    trie = HashTrie(chunk_size=4, max_nodes=50)
    for i in range(100):
        await trie.insert(f"pref{i:04d}suffix{i:04d}", "e1")
    assert trie.node_count <= 60


def _reachable_nodes(trie: HashTrie) -> int:
    total = 0
    stack = list(trie.root.children.values())
    while stack:
        n = stack.pop()
        total += 1
        stack.extend(n.children.values())
    return total


async def test_eviction_never_detaches_active_insert_path():
    """Regression: mid-insert eviction must not evict the subtree the
    insert is walking. Previously a long insert that crossed the
    max_nodes threshold partway down could have its own top-level
    subtree evicted (it is the oldest once fresher inserts exist),
    attaching all later chunks to a detached node: node_count counted
    unreachable nodes and drifted up forever."""
    trie = HashTrie(chunk_size=2, max_nodes=12)
    # One long (old) chain, then fresher short chains, so the long
    # chain's top-level subtree is the LRU eviction candidate.
    await trie.insert("aa" * 6, "e1")
    await trie.insert("bb", "e1")
    await trie.insert("cc", "e1")
    # 8 nodes so far. This 8-chunk insert shares the "aa" top-level
    # child and crosses max_nodes mid-walk, triggering eviction while
    # standing inside the "aa" subtree.
    await trie.insert("aa" * 8, "e1")
    assert trie.node_count == _reachable_nodes(trie)
    # The just-inserted path must be fully reachable.
    matched, eps = await trie.longest_prefix_match("aa" * 8, {"e1"})
    assert matched == 8 and eps == {"e1"}
    # And repeated pressure keeps the invariant.
    for i in range(50):
        await trie.insert(f"zz{i:02d}" * 4, "e2")
        assert trie.node_count == _reachable_nodes(trie)
    assert trie.node_count <= 12 + 8  # bounded: threshold + one path
