"""SLO engine e2e + units (hermetic): outcome classification through the
real router against fake engines, flag-off parity, the canary prober,
the fleet event journal (ring bound, privileged /debug/events, Grafana
annotations export), and a toy run of the saturation harness proving
the classifier reconciles.

Outcome taxonomy under test (router/slo.py): every request that reaches
the handler terminates as exactly one of ok / slow / shed / failed /
client_abort, and with --slo-config off none of that code runs.
"""

import argparse
import asyncio
import time

import aiohttp
import pytest
import yaml
from aiohttp import web

from production_stack_tpu.obs.events import EventJournal
from production_stack_tpu.router import metrics as router_metrics
from production_stack_tpu.router import routing_logic as rl
from production_stack_tpu.router.app import build_app
from production_stack_tpu.router.engine_stats import EngineStatsScraper
from production_stack_tpu.router.request_stats import RequestStatsMonitor
from production_stack_tpu.router.slo import (
    OUTCOMES,
    CanaryProber,
    SLOEngine,
)
from production_stack_tpu.testing.fake_engine import FakeEngine
from production_stack_tpu.utils.misc import SingletonABCMeta, SingletonMeta


# ---------------------------------------------------------------------------
# Unit: SLOEngine objective resolution + accounting
# ---------------------------------------------------------------------------


def test_objectives_precedence_tenant_beats_model_beats_default():
    eng = SLOEngine({
        "default": {"ttft_p99_s": 2.0, "inter_token_p99_s": 0.5},
        "models": {"big": {"ttft_p99_s": 5.0}},
        "tenants": {"premium": {"ttft_p99_s": 1.0}},
    })
    assert eng.objectives()["ttft_p99_s"] == 2.0
    assert eng.objectives(model="big")["ttft_p99_s"] == 5.0
    # Tenant override wins even when the model also overrides.
    assert eng.objectives(tenant="premium", model="big")["ttft_p99_s"] == 1.0
    # Non-overridden keys fall through to the default.
    assert eng.objectives(model="big")["inter_token_p99_s"] == 0.5


def test_objectives_adapter_entry_beats_base_model_entry():
    eng = SLOEngine({
        "default": {"ttft_p99_s": 2.0, "inter_token_p99_s": 0.5},
        "models": {"base-8b": {"ttft_p99_s": 5.0},
                   "sql-adapter": {"ttft_p99_s": 1.5}},
        "tenants": {"premium": {"ttft_p99_s": 1.0}},
    })
    # Adapter traffic names the adapter as ``model``; its own entry
    # wins over the base model's.
    obj = eng.objectives(model="sql-adapter", base_model="base-8b")
    assert obj["ttft_p99_s"] == 1.5
    # An adapter WITHOUT its own entry inherits the base model's
    # objectives instead of the default.
    obj = eng.objectives(model="other-adapter", base_model="base-8b")
    assert obj["ttft_p99_s"] == 5.0
    # Non-overridden keys still fall through to the default.
    assert obj["inter_token_p99_s"] == 0.5
    # Tenant override beats both.
    obj = eng.objectives(tenant="premium", model="sql-adapter",
                         base_model="base-8b")
    assert obj["ttft_p99_s"] == 1.0
    # Non-LoRA traffic: base_model is None (or equals model) — exactly
    # the old resolution.
    assert eng.objectives(model="base-8b")["ttft_p99_s"] == 5.0
    assert eng.objectives(
        model="base-8b", base_model="base-8b")["ttft_p99_s"] == 5.0


def test_latency_outcome_uses_adapter_resolution():
    eng = SLOEngine({
        "default": {"ttft_p99_s": 2.0},
        "models": {"base-8b": {"ttft_p99_s": 5.0},
                   "sql-adapter": {"ttft_p99_s": 0.5}},
    })
    # 1s TTFT: fine for the base model, a violation for the adapter.
    assert eng.latency_outcome(
        None, "other-adapter", ttft_s=1.0, base_model="base-8b") == "ok"
    assert eng.latency_outcome(
        None, "sql-adapter", ttft_s=1.0, base_model="base-8b") == "slow"


def test_objectives_config_junk_is_ignored_not_fatal():
    eng = SLOEngine({
        "default": {"ttft_p99_s": "fast", "unknown_knob": 3,
                    "inter_token_p99_s": True},
        "tenants": {"t": None},
    })
    # Junk values fall back to the built-in defaults; classification
    # still works (never a crash on the request path).
    assert eng.objectives()["ttft_p99_s"] == 2.0
    assert eng.objectives()["inter_token_p99_s"] == 0.5
    assert eng.latency_outcome("t", None, ttft_s=0.1) == "ok"


def test_latency_outcome_boundaries():
    eng = SLOEngine({"default": {"ttft_p99_s": 1.0,
                                 "inter_token_p99_s": 0.2}})
    assert eng.latency_outcome(None, None, ttft_s=0.99) == "ok"
    assert eng.latency_outcome(None, None, ttft_s=1.01) == "slow"
    assert eng.latency_outcome(None, None, inter_token_s=0.3) == "slow"
    # Unknown timings never violate (a proxy that saw no chunks cannot
    # judge inter-token latency).
    assert eng.latency_outcome(None, None) == "ok"


def test_observe_counts_and_goodput_window():
    eng = SLOEngine()
    for outcome in ("ok", "ok", "ok", "slow"):
        eng.observe(outcome, tenant="t1", model="m")
    # Unknown outcome strings are folded into failed, never raised.
    eng.observe("exploded", tenant="t1", model="m")
    counts = eng.counts()
    assert counts["ok"] == 3 and counts["slow"] == 1
    assert counts["failed"] == 1
    assert sum(counts.values()) == 5
    assert eng.goodput(300.0) == pytest.approx(3 / 5)
    # An empty window is None (unknown), not 0 or 1.
    assert SLOEngine().goodput(300.0) is None
    assert set(counts) == set(OUTCOMES)


def test_from_file_rejects_non_mapping(tmp_path):
    p = tmp_path / "slo.yaml"
    p.write_text("- not\n- a\n- mapping\n")
    with pytest.raises(ValueError, match="YAML mapping"):
        SLOEngine.from_file(str(p))
    p.write_text("")  # empty file -> all defaults
    eng = SLOEngine.from_file(str(p))
    assert eng.objectives()["availability"] == 0.999


# ---------------------------------------------------------------------------
# Unit: EventJournal ring
# ---------------------------------------------------------------------------


def test_event_journal_ring_is_bounded():
    j = EventJournal("test", capacity=4)
    for i in range(10):
        j.record("failover", endpoint=f"http://e{i}")
    assert len(j.snapshot(limit=100)) == 4
    # Totals survive eviction.
    assert j.recorded_total == 10
    assert j.kind_counts() == {"failover": 10}
    # Newest first.
    assert j.snapshot(limit=1)[0]["endpoint"] == "http://e9"
    s = j.summary()
    assert s["buffered"] == 4 and s["recorded_total"] == 10


def test_event_journal_kind_filter_and_grafana_shape():
    j = EventJournal("test")
    j.record("breaker_open", endpoint="http://a", failures=3)
    j.record("lease_sweep", endpoint="http://b", swept=2)
    assert [e["kind"] for e in j.snapshot(kind="lease_sweep")] == [
        "lease_sweep"]
    annotations = j.to_grafana(kind="breaker_open")
    assert len(annotations) == 1
    a = annotations[0]
    assert isinstance(a["time"], int)  # epoch millis
    assert a["time"] >= int(time.time() * 1000) - 60_000
    assert a["tags"] == ["breaker_open", "http://a"]
    assert a["text"] == "breaker_open: failures=3"


# ---------------------------------------------------------------------------
# E2E: router + fake engine
# ---------------------------------------------------------------------------


def _args(**overrides) -> argparse.Namespace:
    from production_stack_tpu.router.parser import build_parser

    args = build_parser().parse_args([])
    for k, v in overrides.items():
        setattr(args, k, v)
    return args


async def _start(app: web.Application):
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


@pytest.fixture(autouse=True)
def _reset_singletons():
    def _reset():
        for cls in (
            rl.RoundRobinRouter, rl.SessionRouter, rl.PrefixAwareRouter,
            rl.KvawareRouter, rl.DisaggregatedPrefillRouter,
        ):
            SingletonABCMeta._reset_instance(cls)
        SingletonMeta._reset_instance(RequestStatsMonitor)
        SingletonMeta._reset_instance(EngineStatsScraper)

    _reset()
    yield
    _reset()


def _slo_file(tmp_path, config) -> str:
    p = tmp_path / "slo.yaml"
    p.write_text(yaml.safe_dump(config))
    return str(p)


async def _router_one_engine(engine=None, **argover):
    engine = engine or FakeEngine(model="test-model", ttft=0.01,
                                  tokens_per_sec=500.0)
    erunner, eurl = await _start(engine.make_app())
    args = _args(
        static_backends=eurl,
        static_models="test-model",
        routing_logic="roundrobin",
        engine_stats_interval=60,
        **argover,
    )
    app = build_app(args)
    rrunner, rurl = await _start(app)
    return engine, eurl, app, rurl, [erunner, rrunner]


async def _cleanup(runners):
    for r in reversed(runners):
        await r.cleanup()


async def _complete(s, rurl, **extra):
    body = {"model": "test-model", "prompt": "hi", "max_tokens": 4,
            "stream": True, **extra}
    async with s.post(f"{rurl}/v1/completions", json=body) as resp:
        status = resp.status
        async for _ in resp.content:
            pass
        return status


async def _wait_counts(state, total, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if sum(state.slo.counts().values()) >= total:
            return state.slo.counts()
        await asyncio.sleep(0.02)
    return state.slo.counts()


async def test_outcome_classification_ok_slow_failed(tmp_path):
    """One request per latency outcome plus an unroutable model, each
    classified exactly once (counts sum to requests seen)."""
    path = _slo_file(tmp_path, {
        "default": {"ttft_p99_s": 30.0, "inter_token_p99_s": 30.0},
        # The slow tenant's TTFT bound is unmeetable, so its (successful)
        # request classifies slow.
        "models": {"test-model": {"ttft_p99_s": 30.0}},
    })
    engine, eurl, app, rurl, runners = await _router_one_engine(
        slo_config=path)
    state = app["state"]
    assert state.slo is not None and state.slo.source == path
    try:
        async with aiohttp.ClientSession() as s:
            assert await _complete(s, rurl) == 200            # -> ok
            state.slo.models["test-model"]["ttft_p99_s"] = 1e-9
            assert await _complete(s, rurl) == 200            # -> slow
            assert await _complete(s, rurl, model="nope") == 400  # -> failed
            counts = await _wait_counts(state, 3)

            # Goodput gauge refreshes at scrape time with the 2/3 ratio
            # (the failed request burns budget; nothing is excluded here
            # because no client aborted).
            async with s.get(f"{rurl}/metrics") as resp:
                text = await resp.text()
    finally:
        await _cleanup(runners)
    assert counts["ok"] == 1 and counts["slow"] == 1
    assert counts["failed"] == 1 and counts["client_abort"] == 0
    assert sum(counts.values()) == 3
    assert 'vllm_router:goodput_ratio{window="5m"}' in text
    assert ('vllm_router:request_outcomes_total{'
            'model="test-model",outcome="ok",tenant="default"} 1.0') in text


async def test_outcome_classification_client_abort(tmp_path):
    """A client that hangs up mid-stream classifies client_abort — not
    failed (the engine did nothing wrong) and not ok."""
    engine = FakeEngine(model="test-model", ttft=0.01, tokens_per_sec=5.0)
    _, eurl, app, rurl, runners = await _router_one_engine(
        engine=engine,
        slo_config=_slo_file(tmp_path, {"default": {"ttft_p99_s": 30.0}}))
    state = app["state"]
    try:
        async with aiohttp.ClientSession() as s:
            resp = await s.post(
                f"{rurl}/v1/completions",
                json={"model": "test-model", "prompt": "hi",
                      "max_tokens": 200, "stream": True})
            assert resp.status == 200
            await resp.content.readany()  # first chunk arrived...
            resp.close()                  # ...then the client vanishes
        counts = await _wait_counts(state, 1)
    finally:
        await _cleanup(runners)
    assert counts["client_abort"] == 1
    assert sum(counts.values()) == 1


def _outcome_sample_count() -> int:
    return sum(len(m.samples)
               for m in router_metrics.request_outcomes.collect())


def _canary_sample_count() -> int:
    return sum(len(m.samples)
               for m in router_metrics.canary_probes.collect())


async def test_flag_off_no_slo_state_and_no_series():
    """Without --slo-config / --canary-interval nothing is constructed
    and no outcome/canary series ever appears: the deltas across a
    served request are zero (the global registry may carry series from
    other tests, so deltas — not absolutes — are the invariant)."""
    before_outcomes = _outcome_sample_count()
    before_canary = _canary_sample_count()
    engine, eurl, app, rurl, runners = await _router_one_engine()
    state = app["state"]
    try:
        assert state.slo is None
        assert state.canary is None
        async with aiohttp.ClientSession() as s:
            assert await _complete(s, rurl) == 200
    finally:
        await _cleanup(runners)
    assert _outcome_sample_count() == before_outcomes
    assert _canary_sample_count() == before_canary


async def test_debug_events_served_and_privileged(tmp_path):
    """/debug/events serves the journal (newest first + Grafana shape)
    and sits behind the API key like the other debug surfaces."""
    engine, eurl, app, rurl, runners = await _router_one_engine(
        api_key="sekret")
    state = app["state"]
    state.events.record("failover", endpoint="http://old:1",
                        attempt=2)
    state.events.record("breaker_open", endpoint="http://old:1")
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{rurl}/debug/events") as resp:
                assert resp.status == 401  # privileged, no bearer
            hdr = {"Authorization": "Bearer sekret"}
            async with s.get(f"{rurl}/debug/events", headers=hdr) as resp:
                assert resp.status == 200
                payload = await resp.json()
            async with s.get(f"{rurl}/debug/events?format=grafana",
                             headers=hdr) as resp:
                assert resp.status == 200
                annotations = await resp.json()
            async with s.get(f"{rurl}/debug/events?kind=failover",
                             headers=hdr) as resp:
                only = await resp.json()
    finally:
        await _cleanup(runners)
    kinds = [e["kind"] for e in payload["events"]]
    assert kinds[:2] == ["breaker_open", "failover"]  # newest first
    assert payload["recorded_total"] >= 2
    assert {a["tags"][0] for a in annotations} >= {"failover",
                                                   "breaker_open"}
    assert all(e["kind"] == "failover" for e in only["events"])
    assert only["events"]


async def test_canary_probe_measures_ttft_and_records_failures(tmp_path):
    """The prober hits replicas directly: a healthy engine yields a TTFT
    sample; a torn-down one records a connect failure (the signal the
    TPUStackCanaryFailing alert consumes)."""
    engine, eurl, app, rurl, runners = await _router_one_engine(
        slo_config=_slo_file(tmp_path, {}))
    state = app["state"]
    prober = CanaryProber(state, interval_s=60.0, prompt_tokens=4,
                          max_tokens=2, events=state.events)
    try:
        eps = state.service_discovery.get_endpoint_info()
        assert len(eps) == 1
        ttft = await prober.probe(eps[0])
        assert ttft is not None and 0 < ttft < 10
        assert prober.probes_run == 1 and prober.failures == 0
        # Probes bypass the request path: nothing was classified.
        assert sum(state.slo.counts().values()) == 0

        await runners[0].cleanup()  # tear the engine down
        assert await prober.probe(eps[0]) is None
        assert prober.failures == 1
        fails = state.events.snapshot(kind="canary_failure")
        assert fails and fails[0]["endpoint"] == eps[0].url
        assert fails[0]["attributes"]["reason"] == "connect"
    finally:
        await _cleanup(runners[1:])


def test_saturation_toy_run_reconciles():
    """The harness at toy scale: every offered request reaches the
    router and gets exactly one outcome (the 10k-user artifact run is
    bench.py's BENCH_SATURATION=1; this keeps the machinery honest in
    the tier-1 suite)."""
    from production_stack_tpu.testing.saturation import run_saturation

    result = asyncio.run(run_saturation(
        steps=(10, 25), requests_per_user=2, replicas=2,
        collapse_threshold=0.9))
    assert result["outcomes_reconcile_all"] is True
    assert result["total_requests"] == 70
    for rung in result["rungs"]:
        assert rung["unreached"] == 0
        assert rung["outcomes_classified"] == rung["requests"]
        assert rung["goodput"] is not None
    assert sum(result["engine_requests"]) == 70
