"""Multi-tenant QoS: token buckets, weighted-fair queue, tenant
registry, scheduler priority admission/preemption, and router-level
429 / shed / header behavior (hermetic, fake engines).

The flag-off contract is load-bearing: without --qos-tenants-file the
router must behave byte-identically to a stack without the subsystem
(state.qos is None, no qos headers, no X-Priority toward the engine).
"""

import argparse
import asyncio
import json
import os

import aiohttp
import pytest
from aiohttp import web

from production_stack_tpu.engine.kvcache import KVCacheManager
from production_stack_tpu.engine.sampling import SamplingParams
from production_stack_tpu.engine.scheduler import (
    EngineRequest,
    RequestStatus,
    Scheduler,
    parse_priority,
    priority_label,
)
from production_stack_tpu.qos import QoSGate, ShedError
from production_stack_tpu.qos.fair_queue import (
    FairDispatchQueue,
    priority_class,
)
from production_stack_tpu.qos.gate import estimate_tokens
from production_stack_tpu.qos.tenants import TenantRegistry, TenantSpec
from production_stack_tpu.qos.token_bucket import TokenBucket
from production_stack_tpu.router import routing_logic as rl
from production_stack_tpu.router.app import build_app
from production_stack_tpu.router.engine_stats import EngineStatsScraper
from production_stack_tpu.router.request_stats import RequestStatsMonitor
from production_stack_tpu.testing.fake_engine import FakeEngine
from production_stack_tpu.utils.misc import SingletonABCMeta, SingletonMeta

# ---------------------------------------------------------------------------
# Token bucket
# ---------------------------------------------------------------------------


def test_token_bucket_drain_refill_and_retry_after():
    b = TokenBucket(rate=10, burst=10)
    t0 = b._last
    ok, retry = b.try_acquire(10, now=t0)
    assert ok and retry == 0.0
    ok, retry = b.try_acquire(1, now=t0)
    assert not ok
    assert retry == pytest.approx(0.1)
    # A denied acquire leaves the bucket untouched; refill clears it.
    ok, _ = b.try_acquire(1, now=t0 + 0.2)
    assert ok
    # Refill caps at burst no matter how long the idle gap.
    assert b.remaining(now=t0 + 1000) == pytest.approx(10)


def test_token_bucket_unlimited_and_oversized():
    assert TokenBucket(rate=0, burst=0).try_acquire(10**9) == (True, 0.0)
    b = TokenBucket(rate=1, burst=5)
    t0 = b._last
    # amount > burst can never clear: quote time-to-full, don't spin.
    ok, retry = b.try_acquire(50, now=t0)
    assert not ok and retry == pytest.approx(0.0)
    b.try_acquire(5, now=t0)
    ok, retry = b.try_acquire(50, now=t0)
    assert not ok and retry == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# Fair dispatch queue
# ---------------------------------------------------------------------------


async def _drain(q, leases, order, n):
    """Release leases one at a time, recording dispatch order."""
    for _ in range(n):
        lease = await asyncio.wait_for(leases.get(), 5)
        lease.release()
    await asyncio.sleep(0)
    return order


async def test_drr_weighted_share():
    q = FairDispatchQueue(max_concurrency=1, shed_queue_depth=0,
                          quantum=256.0)
    order, leases = [], asyncio.Queue()

    async def worker(name, weight):
        lease = await q.acquire(name, weight=weight, priority="batch",
                                cost=256.0)
        order.append(name)
        await leases.put(lease)

    tasks = [asyncio.create_task(worker("heavy", 4.0)) for _ in range(12)]
    await asyncio.sleep(0)  # heavy queues first (one dispatches)
    tasks += [asyncio.create_task(worker("light", 1.0)) for _ in range(12)]
    await asyncio.sleep(0)
    await _drain(q, leases, order, 24)
    await asyncio.gather(*tasks)
    assert len(order) == 24
    # weight 4 drains ~4x the token volume per DRR round (the steady
    # pattern is 4 heavy dispatches per light one), so the heavy tenant
    # is nearly drained before the light one gets its third slot.
    assert order[:5] == ["heavy"] * 5
    assert order[:16].count("heavy") >= 11


async def test_interactive_not_starved_by_batch_flood():
    q = FairDispatchQueue(max_concurrency=1, shed_queue_depth=64)
    batch_lease = await q.acquire("crawler", priority="batch")
    # Total in-flight == max_concurrency, but interactive rides on top.
    inter_lease = await asyncio.wait_for(
        q.acquire("acme", priority="interactive"), 1)
    assert q.inflight == 2
    # A second interactive waits for an interactive slot, not the batch.
    second = asyncio.ensure_future(q.acquire("acme", priority="interactive"))
    await asyncio.sleep(0.01)
    assert not second.done()
    inter_lease.release()
    (await asyncio.wait_for(second, 1)).release()
    batch_lease.release()
    assert q.inflight == 0


async def test_batch_shed_at_queue_depth():
    q = FairDispatchQueue(max_concurrency=1, shed_queue_depth=1)
    lease = await q.acquire("crawler", priority="batch")
    queued = asyncio.ensure_future(q.acquire("crawler", priority="batch"))
    await asyncio.sleep(0)
    assert q.queued("batch") == 1
    with pytest.raises(ShedError) as ei:
        await q.acquire("crawler", priority="batch")
    assert ei.value.retry_after > 0
    # Interactive is never shed.
    (await q.acquire("acme", priority="interactive")).release()
    lease.release()
    (await asyncio.wait_for(queued, 1)).release()


async def test_cancelled_waiter_releases_cleanly():
    q = FairDispatchQueue(max_concurrency=1)
    lease = await q.acquire("a", priority="batch")
    waiter = asyncio.ensure_future(q.acquire("a", priority="batch"))
    await asyncio.sleep(0)
    waiter.cancel()
    with pytest.raises(asyncio.CancelledError):
        await waiter
    lease.release()
    assert q.inflight == 0
    # Queue still functional after the cancellation.
    (await asyncio.wait_for(q.acquire("a", priority="batch"), 1)).release()


async def test_cancel_vs_pump_race_does_not_leak_slots():
    """Task.cancel() marks the waiter's future cancelled immediately, but
    acquire()'s cleanup only runs when the cancelled task is next
    scheduled.  A lease release in that window runs _pump(), which must
    skip the dead waiter without consuming a dispatch slot — repeated
    client disconnects used to leak max_concurrency slots this way."""
    q = FairDispatchQueue(max_concurrency=1)
    for _ in range(3):  # a leak compounds; three rounds would deadlock
        lease = await asyncio.wait_for(
            q.acquire("a", priority="batch"), 1)
        waiter = asyncio.ensure_future(q.acquire("a", priority="batch"))
        await asyncio.sleep(0)  # waiter is enqueued
        waiter.cancel()   # fut cancelled synchronously...
        lease.release()   # ...and _pump() runs before acquire()'s cleanup
        with pytest.raises(asyncio.CancelledError):
            await waiter
        assert q.inflight == 0
        assert q.queued() == 0
    (await asyncio.wait_for(q.acquire("a", priority="batch"), 1)).release()
    assert q.inflight == 0


# ---------------------------------------------------------------------------
# Tenant registry + gate
# ---------------------------------------------------------------------------

_TENANTS = {
    "tenants": [
        {"name": "acme", "api_keys": ["sk-acme"], "weight": 4,
         "priority": "interactive", "requests_per_second": 2,
         "burst_seconds": 1.0},
        {"name": "crawler", "api_key": "sk-c1, sk-c2", "weight": 1,
         "priority": "batch"},
    ],
    "max_concurrency": 3,
    "shed_queue_depth": 5,
}


def test_registry_resolves_keys_and_defaults():
    reg = TenantRegistry.from_dict(_TENANTS)
    assert reg.resolve("Bearer sk-acme").name == "acme"
    # api_key accepts a comma-separated string too.
    assert reg.resolve("Bearer sk-c2").name == "crawler"
    assert reg.resolve("Bearer sk-c2").priority == "batch"
    assert reg.resolve("Bearer unknown").name == "default"
    assert reg.resolve(None).name == "default"
    assert reg.max_concurrency == 3 and reg.shed_queue_depth == 5


def test_registry_rejects_bad_config():
    with pytest.raises(ValueError):
        TenantSpec.from_dict({"name": "x", "priority": "vip"})
    with pytest.raises(ValueError):
        TenantSpec.from_dict({"name": "x", "weight": 0})
    with pytest.raises(ValueError):
        TenantSpec.from_dict({"priority": "batch"})
    with pytest.raises(ValueError):
        TenantRegistry.from_dict(
            {"tenants": [{"name": "x"}, {"name": "x"}]})


def test_request_priority_upgrade_gated(tmp_path):
    """X-Priority only downgrades: a batch-classed tenant cannot stamp
    its flood `interactive` to bypass shedding / slot yielding /
    preemption ordering, unless allow_priority_upgrade is set."""
    path = tmp_path / "tenants.json"
    data = dict(_TENANTS)
    data["tenants"] = list(_TENANTS["tenants"]) + [
        {"name": "bulk-vip", "api_keys": ["sk-vip"], "priority": "batch",
         "allow_priority_upgrade": True}]
    path.write_text(json.dumps(data))
    gate = QoSGate(str(path))
    crawler = gate.resolve("Bearer sk-c1")
    assert crawler.priority == "batch"
    assert gate.request_priority(crawler, None) == "batch"
    assert gate.request_priority(crawler, "interactive") == "batch"
    # Opt-in flag restores the upgrade path for trusted tenants.
    vip = gate.resolve("Bearer sk-vip")
    assert gate.request_priority(vip, None) == "batch"
    assert gate.request_priority(vip, "interactive") == "interactive"
    # Downgrades stay honored either way.
    acme = gate.resolve("Bearer sk-acme")
    assert gate.request_priority(acme, "batch") == "batch"


def test_estimate_tokens_scales_with_request():
    small = estimate_tokens({"messages": [
        {"role": "user", "content": "hi"}], "max_tokens": 5})
    big = estimate_tokens({"messages": [
        {"role": "user", "content": "x" * 4000}], "max_tokens": 5})
    assert big - small == pytest.approx(1000, abs=2)
    # No max_tokens -> default completion estimate, not zero.
    assert estimate_tokens({"prompt": "hello"}) > 60


def test_gate_admit_429_headers_and_hot_reload(tmp_path):
    path = tmp_path / "tenants.json"
    path.write_text(json.dumps(_TENANTS))
    gate = QoSGate(str(path), reload_interval_s=0.0)
    acme = gate.resolve("Bearer sk-acme")
    # burst = 2 req/s * 1 s: two immediate admits, the third 429s.
    assert gate.admit(acme, {}).admitted
    r2 = gate.admit(acme, {})
    assert r2.admitted
    assert r2.headers["x-ratelimit-remaining-requests"] == "0"
    r3 = gate.admit(acme, {})
    assert not r3.admitted and r3.reason == "requests"
    assert r3.retry_after > 0
    assert r3.headers["x-ratelimit-reset-requests"].endswith("s")
    # X-Priority header may downgrade the tenant default class.
    assert gate.request_priority(acme, None) == "interactive"
    assert gate.request_priority(acme, "batch") == "batch"
    assert gate.request_priority(acme, "bogus") == "interactive"
    # Hot reload: rewrite the file, force mtime change, pick up new spec.
    data = dict(_TENANTS)
    data["tenants"] = [dict(_TENANTS["tenants"][0], name="acme2")]
    path.write_text(json.dumps(data))
    os.utime(path, (1, 1))
    assert gate.maybe_reload(force=True)
    assert gate.resolve("Bearer sk-acme").name == "acme2"
    # A broken rewrite keeps the previous config.
    path.write_text("{not json")
    os.utime(path, (2, 2))
    assert not gate.maybe_reload(force=True)
    assert gate.resolve("Bearer sk-acme").name == "acme2"


# ---------------------------------------------------------------------------
# Scheduler: priority admission + preemption victims
# ---------------------------------------------------------------------------


def test_parse_priority_and_label():
    assert parse_priority(None) == 0
    assert parse_priority("interactive") == 0
    assert parse_priority("batch") == 1
    assert parse_priority(" Batch ") == 1
    assert parse_priority("junk") == 0
    assert priority_label(0) == "interactive"
    assert priority_label(1) == "batch"
    assert priority_class("BATCH") == "batch"
    assert priority_class(None) == "interactive"


def _mk_req(rid, n_prompt, priority=0, arrival=None):
    req = EngineRequest(
        request_id=rid,
        prompt_token_ids=list(range(1, n_prompt + 1)),
        sampling=SamplingParams(max_tokens=4, temperature=0.0),
        on_token=lambda token, finish: None,
        priority=priority,
    )
    if arrival is not None:
        req.arrival_time = arrival
    return req


def test_waiting_queue_admits_by_priority_then_arrival():
    kv = KVCacheManager(64, 4, enable_prefix_caching=False)
    sched = Scheduler(kv, max_num_seqs=4, max_model_len=512)
    b1 = _mk_req("b1", 8, priority=1, arrival=1.0)
    b2 = _mk_req("b2", 8, priority=1, arrival=2.0)
    i1 = _mk_req("i1", 8, priority=0, arrival=3.0)
    for r in (b1, b2, i1):
        sched.add(r)
    # The interactive arrival jumps the queued batch requests...
    assert sched.peek_waiting() is i1
    sched._pop_waiting(i1)
    # ...and batch requests drain in arrival order afterwards.
    assert sched.peek_waiting() is b1
    sched._pop_waiting(b1)
    assert sched.peek_waiting() is b2


def test_default_priority_keeps_fifo_order():
    kv = KVCacheManager(64, 4, enable_prefix_caching=False)
    sched = Scheduler(kv, max_num_seqs=4, max_model_len=512)
    reqs = [_mk_req(f"r{i}", 8, arrival=float(i)) for i in range(3)]
    for r in reqs:
        sched.add(r)
    for r in reqs:
        assert sched.peek_waiting() is r
        sched._pop_waiting(r)


def test_preempt_victim_prefers_batch_over_older_interactive():
    kv = KVCacheManager(64, 4, enable_prefix_caching=False)
    sched = Scheduler(kv, max_num_seqs=4, max_model_len=512)
    # Batch request is OLDER: priority still dominates arrival time.
    batch = _mk_req("batch", 8, priority=1, arrival=1.0)
    inter = _mk_req("inter", 8, priority=0, arrival=2.0)
    for req, slot in ((batch, 0), (inter, 1)):
        sched.add(req)
        sched._pop_waiting(req)
        kv.allocate_prompt(req.request_id, req.all_token_ids)
        sched.start_running(req, slot)
    seq = sched.preempt_victim()
    assert seq is not None and seq.req is batch
    assert batch.status is RequestStatus.PREEMPTED
    assert sched.preempted_by_priority == {"interactive": 0, "batch": 1}
    # Among equals, youngest-first (the pre-QoS rule) still holds.
    seq2 = sched.preempt_victim()
    assert seq2.req is inter
    assert sched.preempted_by_priority["interactive"] == 1


def test_preempt_victim_mid_chunked_prefill_batch():
    """A batch request mid-chunked-prefill is the victim even while an
    interactive request is decoding, and resumes from token 0."""
    kv = KVCacheManager(64, 4, enable_prefix_caching=False)
    sched = Scheduler(kv, max_num_seqs=4, max_model_len=512,
                      chunked_prefill=True, chunk_tokens=16,
                      token_budget=16, max_consecutive_prefills=2)
    inter = _mk_req("inter", 8, priority=0, arrival=1.0)
    sched.add(inter)
    action, plan = sched.next_action()
    assert action == "prefill_step"
    kv.allocate_prompt("inter", inter.all_token_ids)
    inter.num_computed_tokens = 8
    sched.prefilling.remove(inter)
    sched.start_running(inter, sched._free_slot())
    batch = _mk_req("batch", 64, priority=1, arrival=2.0)
    sched.add(batch)
    while batch.num_computed_tokens < 32:
        action, plan = sched.next_action()
        if action != "prefill_step":
            continue
        for pc in plan:
            if pc.start == 0:
                kv.allocate_prompt(pc.req.request_id,
                                   pc.req.all_token_ids, limit=pc.end)
            else:
                kv.extend_tokens(pc.req.request_id,
                                 pc.req.all_token_ids, pc.end)
            pc.req.num_computed_tokens = pc.end
    free_before = kv.allocator.num_free
    seq = sched.preempt_victim()
    assert seq.req is batch and seq.slot == -1
    assert batch.num_computed_tokens == 0
    assert kv.allocator.num_free > free_before
    assert sched.preempted_by_priority["batch"] == 1
    # Requeued at the head of its class; interactive still outranks it.
    assert sched.peek_waiting() is batch
    sched.add(_mk_req("i2", 8, priority=0, arrival=3.0))
    assert sched.peek_waiting().request_id == "i2"


# ---------------------------------------------------------------------------
# Router end-to-end (fake engines)
# ---------------------------------------------------------------------------


def _args(**overrides) -> argparse.Namespace:
    from production_stack_tpu.router.parser import build_parser

    args = build_parser().parse_args([])
    for k, v in overrides.items():
        setattr(args, k, v)
    return args


async def _start(app: web.Application):
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


@pytest.fixture(autouse=True)
def _reset_singletons():
    def _reset():
        for cls in (
            rl.RoundRobinRouter, rl.SessionRouter, rl.PrefixAwareRouter,
            rl.KvawareRouter, rl.DisaggregatedPrefillRouter,
        ):
            SingletonABCMeta._reset_instance(cls)
        SingletonMeta._reset_instance(RequestStatsMonitor)
        SingletonMeta._reset_instance(EngineStatsScraper)

    _reset()
    yield
    _reset()


async def _qos_router(tmp_path, tenants=None, engine_kwargs=None,
                      **argover):
    tenants_file = None
    if tenants is not None:
        tenants_file = str(tmp_path / "tenants.json")
        with open(tenants_file, "w") as f:
            json.dump(tenants, f)
    engine = FakeEngine(model="test-model", **(engine_kwargs or {}))
    eng_runner, eng_url = await _start(engine.make_app())
    args = _args(
        static_backends=eng_url,
        static_models="test-model",
        engine_stats_interval=60,
        qos_tenants_file=tenants_file,
        **argover,
    )
    app = build_app(args)
    router_runner, router_url = await _start(app)
    return engine, app, router_url, [eng_runner, router_runner]


async def _cleanup(runners):
    for r in reversed(runners):
        await r.cleanup()


def _chat(max_tokens=2):
    return {"model": "test-model", "max_tokens": max_tokens,
            "messages": [{"role": "user", "content": "hi"}]}


async def test_router_429_with_ratelimit_headers(tmp_path):
    tenants = {"tenants": [
        {"name": "acme", "api_keys": ["sk-acme"], "weight": 1,
         "priority": "interactive", "requests_per_second": 1,
         "burst_seconds": 1.0}]}
    engine, app, url, runners = await _qos_router(tmp_path, tenants)
    try:
        hdrs = {"Authorization": "Bearer sk-acme"}
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/chat/completions",
                              json=_chat(), headers=hdrs) as resp:
                assert resp.status == 200
                assert resp.headers["x-tenant"] == "acme"
                assert "x-ratelimit-remaining-requests" in resp.headers
            async with s.post(f"{url}/v1/chat/completions",
                              json=_chat(), headers=hdrs) as resp:
                assert resp.status == 429
                body = await resp.json()
                assert body["error"]["type"] == "RateLimitError"
                assert int(resp.headers["Retry-After"]) >= 1
                assert resp.headers[
                    "x-ratelimit-remaining-requests"] == "0"
            # Another tenant (default) is not rate limited.
            async with s.post(f"{url}/v1/chat/completions",
                              json=_chat()) as resp:
                assert resp.status == 200
                assert resp.headers["x-tenant"] == "default"
            await asyncio.sleep(0)
            async with s.get(f"{url}/metrics") as resp:
                text = await resp.text()
        assert 'vllm_router:tenant_admitted_total{tenant="acme"} 1.0' in text
        assert ('vllm_router:tenant_rejected_total'
                '{reason="requests",tenant="acme"} 1.0') in text
    finally:
        await _cleanup(runners)


async def test_router_sheds_batch_under_concurrency(tmp_path):
    tenants = {
        "tenants": [{"name": "crawler", "api_keys": ["sk-c"],
                     "weight": 1, "priority": "batch"}],
        "max_concurrency": 1, "shed_queue_depth": 1,
    }
    engine, app, url, runners = await _qos_router(
        tmp_path, tenants, engine_kwargs={"ttft": 0.4})
    try:
        hdrs = {"Authorization": "Bearer sk-c"}

        async def one(s):
            async with s.post(f"{url}/v1/chat/completions",
                              json=_chat(), headers=hdrs) as resp:
                await resp.read()
                return resp.status, dict(resp.headers)

        async with aiohttp.ClientSession() as s:
            # Stagger so arrival order is deterministic: 1 in flight,
            # 1 queued, the third is shed with 503 + Retry-After.
            t1 = asyncio.ensure_future(one(s))
            await asyncio.sleep(0.1)
            t2 = asyncio.ensure_future(one(s))
            await asyncio.sleep(0.1)
            t3 = asyncio.ensure_future(one(s))
            results = await asyncio.gather(t1, t2, t3)
            statuses = [r[0] for r in results]
            assert statuses.count(200) == 2
            assert statuses.count(503) == 1
            shed_headers = results[statuses.index(503)][1]
            assert int(shed_headers["Retry-After"]) >= 1
            async with s.get(f"{url}/metrics") as resp:
                text = await resp.text()
        assert ('vllm_router:tenant_shed_total{tenant="crawler"} 1.0'
                in text)
        assert engine.priority_requests["batch"] == 2
        assert engine.tenant_requests == {"crawler": 2}
    finally:
        await _cleanup(runners)


async def test_priority_header_overrides_tenant_class(tmp_path):
    tenants = {"tenants": [
        {"name": "acme", "api_keys": ["sk-acme"], "weight": 1,
         "priority": "interactive"}]}
    engine, app, url, runners = await _qos_router(tmp_path, tenants)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{url}/v1/chat/completions", json=_chat(),
                headers={"Authorization": "Bearer sk-acme",
                         "X-Priority": "batch"}) as resp:
                assert resp.status == 200
        assert engine.priority_requests == {"interactive": 0, "batch": 1}
        assert engine.tenant_requests == {"acme": 1}
    finally:
        await _cleanup(runners)


async def test_no_tenants_file_leaves_request_path_untouched(tmp_path):
    """Flag-off parity: state.qos is None, responses carry no qos
    headers, and the engine sees no X-Priority / X-Tenant."""
    engine, app, url, runners = await _qos_router(tmp_path, tenants=None)
    try:
        assert app["state"].qos is None
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/chat/completions",
                              json=_chat(max_tokens=3)) as resp:
                assert resp.status == 200
                body = await resp.json()
                for h in resp.headers:
                    assert not h.lower().startswith("x-ratelimit")
                assert "x-tenant" not in resp.headers
        # Priority defaulted from the ABSENCE of the header, and no
        # tenant header reached the engine.
        assert engine.priority_requests == {"interactive": 1, "batch": 0}
        assert engine.tenant_requests == {}
        assert "Hello" in body["choices"][0]["message"]["content"]

        # Same request through a QoS-enabled router: identical body.
        tenants = {"tenants": [{"name": "acme", "api_keys": ["sk-acme"]}]}
        self_runners = []
        try:
            for cls in (rl.RoundRobinRouter,):
                SingletonABCMeta._reset_instance(cls)
            SingletonMeta._reset_instance(RequestStatsMonitor)
            SingletonMeta._reset_instance(EngineStatsScraper)
            engine2, app2, url2, self_runners = await _qos_router(
                tmp_path, tenants)
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"{url2}/v1/chat/completions",
                    json=_chat(max_tokens=3),
                    headers={"Authorization": "Bearer sk-acme"}) as resp:
                    assert resp.status == 200
                    body2 = await resp.json()
            assert body2["choices"] == body["choices"]
        finally:
            await _cleanup(self_runners)
    finally:
        await _cleanup(runners)


async def test_spoofed_qos_headers_stripped_when_qos_off(tmp_path):
    """Security regression: client-supplied X-Tenant / X-Priority are
    router-asserted headers — with QoS off they must be stripped at the
    proxy boundary, not forwarded to the engine."""
    engine, app, url, runners = await _qos_router(tmp_path, tenants=None)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{url}/v1/chat/completions", json=_chat(),
                headers={"X-Tenant": "victim-tenant",
                         "X-Priority": "batch"}) as resp:
                assert resp.status == 200
        # Neither spoofed header reached the engine: no tenant recorded,
        # and priority defaulted from the ABSENCE of the header.
        assert engine.tenant_requests == {}
        assert engine.priority_requests == {"interactive": 1, "batch": 0}
    finally:
        await _cleanup(runners)


async def test_spoofed_tenant_header_overwritten_when_qos_on(tmp_path):
    """With QoS on, the forwarded X-Tenant is the AUTHENTICATED tenant —
    a client claiming someone else's identity in the header can't bill
    or prioritize as them."""
    tenants = {"tenants": [
        {"name": "acme", "api_keys": ["sk-acme"], "weight": 1,
         "priority": "interactive"}]}
    engine, app, url, runners = await _qos_router(tmp_path, tenants)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{url}/v1/chat/completions", json=_chat(),
                headers={"Authorization": "Bearer sk-acme",
                         "X-Tenant": "victim-tenant"}) as resp:
                assert resp.status == 200
                assert resp.headers["x-tenant"] == "acme"
        assert engine.tenant_requests == {"acme": 1}
        assert "victim-tenant" not in engine.tenant_requests
    finally:
        await _cleanup(runners)


async def test_health_reports_qos_state(tmp_path):
    tenants = {"tenants": [{"name": "acme", "api_keys": ["sk-acme"]}],
               "max_concurrency": 7}
    engine, app, url, runners = await _qos_router(tmp_path, tenants)
    try:
        qos = app["state"].qos
        assert qos is not None
        health = qos.health()
        assert health["tenants"] == ["acme", "default"]
        assert health["max_concurrency"] == 7
        assert health["inflight"] == 0
    finally:
        await _cleanup(runners)
