"""Relay pump tier (router/relay.py, --relay-off-loop): flag-on vs
flag-off client-visible byte identity for streamed responses (SSE and
plain JSON), pump-side fault semantics (client disconnect ->
client_abort + QoS slot released, upstream inter-chunk deadline ->
failed + truncated stream), QoS usage-reconciliation parity for a
gamed ``max_tokens`` stream, flag-off registry sample-delta parity (no
relay series without the flag), and a 2-worker pre-fork leg asserting
pump metrics come back worker-stamped through the federation plane."""

import argparse
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import aiohttp
import pytest
import yaml
from aiohttp import web

from production_stack_tpu.router import metrics as router_metrics
from production_stack_tpu.router import routing_logic as rl
from production_stack_tpu.router.app import build_app
from production_stack_tpu.router.engine_stats import EngineStatsScraper
from production_stack_tpu.router.request_stats import RequestStatsMonitor
from production_stack_tpu.testing.fake_engine import FakeEngine
from production_stack_tpu.utils.misc import SingletonABCMeta, SingletonMeta

MODEL = "test-model"


@pytest.fixture(autouse=True)
def _reset_singletons():
    def _reset():
        for cls in (
            rl.RoundRobinRouter, rl.SessionRouter, rl.PrefixAwareRouter,
            rl.KvawareRouter, rl.DisaggregatedPrefillRouter,
        ):
            SingletonABCMeta._reset_instance(cls)
        SingletonMeta._reset_instance(RequestStatsMonitor)
        SingletonMeta._reset_instance(EngineStatsScraper)

    _reset()
    yield
    _reset()


def _args(**overrides) -> argparse.Namespace:
    from production_stack_tpu.router.parser import build_parser

    args = build_parser().parse_args([])
    for k, v in overrides.items():
        setattr(args, k, v)
    return args


async def _start(app: web.Application):
    runner = web.AppRunner(app)
    await runner.setup()
    # Short shutdown grace: a deliberately hung fake-engine handler
    # (hang_mid_stream) must not hold teardown for aiohttp's default
    # 60 s drain.
    site = web.TCPSite(runner, "127.0.0.1", 0, shutdown_timeout=0.5)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def _router(engine=None, **argover):
    engine = engine or FakeEngine(model=MODEL, ttft=0.01,
                                  tokens_per_sec=500.0)
    erunner, eurl = await _start(engine.make_app())
    args = _args(
        static_backends=eurl,
        static_models=MODEL,
        routing_logic="roundrobin",
        engine_stats_interval=60,
        **argover,
    )
    app = build_app(args)
    rrunner, rurl = await _start(app)
    return engine, eurl, app, rurl, [erunner, rrunner]


async def _cleanup(runners):
    for r in reversed(runners):
        await r.cleanup()


def _counter_total(counter) -> float:
    return sum(s.value for m in counter.collect() for s in m.samples
               if s.name.endswith("_total"))


def _relay_sample_counts() -> dict:
    return {
        name: sum(len(m.samples) for m in metric.collect())
        for name, metric in (
            ("bytes", router_metrics.relay_bytes),
            ("chunks", router_metrics.relay_chunks),
            ("handoff_failures", router_metrics.relay_handoff_failures),
            ("active_pumps", router_metrics.relay_active_pumps),
            ("queue_depth", router_metrics.relay_queue_depth),
        )
    }


async def _stream_body(s, rurl, *, stream=True, max_tokens=8,
                       headers=None, **extra) -> tuple:
    body = {"model": MODEL, "prompt": "ping", "max_tokens": max_tokens,
            "stream": stream, **extra}
    async with s.post(f"{rurl}/v1/completions", json=body,
                      headers=headers or {}) as resp:
        return resp.status, await resp.content.read()


async def _stream_chat(s, rurl, *, max_tokens=8, headers=None) -> tuple:
    # Fault injection and SSE usage frames only exist on the fake
    # engine's chat endpoint. A truncated chunked body (mid-stream
    # fault) surfaces as a ClientError while reading — keep the bytes.
    body = {"model": MODEL, "max_tokens": max_tokens, "stream": True,
            "messages": [{"role": "user", "content": "ping"}]}
    async with s.post(f"{rurl}/v1/chat/completions", json=body,
                      headers=headers or {}) as resp:
        raw = b""
        try:
            async for chunk in resp.content.iter_any():
                raw += chunk
        except aiohttp.ClientError:
            pass
        return resp.status, raw


# ---------------------------------------------------------------------------
# Byte identity: flag-on output == flag-off output
# ---------------------------------------------------------------------------


def _normalize(raw: bytes) -> bytes:
    """Zero out the per-request fields (id, created) the engine stamps
    into every frame so two runs of the same request compare equal."""
    import re

    raw = re.sub(rb'"id": "[^"]*"', b'"id": "X"', raw)
    return re.sub(rb'"created": \d+', b'"created": 0', raw)


async def test_stream_bytes_identical_flag_on_vs_off():
    """The same SSE completion and the same non-streamed JSON body must
    reach the client byte-for-byte equal (modulo the engine's random
    request id / timestamp) whether the pump moved them or the event
    loop did — and the flag-on leg must actually have pumped (relay
    chunk counter advanced, so this is not two on-loop runs)."""
    results = {}
    for leg in ("off", "on"):
        engine, _, app, rurl, runners = await _router(
            relay_off_loop=(leg == "on"))
        try:
            assert (app["state"].relay is not None) == (leg == "on")
            async with aiohttp.ClientSession() as s:
                status, sse = await _stream_body(s, rurl, stream=True)
                assert status == 200
                status, body = await _stream_body(s, rurl, stream=False)
                assert status == 200
            results[leg] = (_normalize(sse), _normalize(body))
        finally:
            await _cleanup(runners)

    assert results["on"][0] == results["off"][0]  # SSE stream
    assert results["on"][1] == results["off"][1]  # buffered JSON
    sse = results["on"][0]
    assert sse.count(b"data: ") >= 8 and b"data: [DONE]" in sse


async def test_flag_on_pumps_and_counts():
    """Flag-on: the handoff engages (no fallback reasons except the
    benign ones), and the per-server relay byte/chunk counters settle to
    exactly what streamed."""
    chunks_before = _counter_total(router_metrics.relay_chunks)
    bytes_before = _counter_total(router_metrics.relay_bytes)
    engine, eurl, app, rurl, runners = await _router(relay_off_loop=True)
    try:
        async with aiohttp.ClientSession() as s:
            status, sse = await _stream_body(s, rurl, stream=True)
            assert status == 200
    finally:
        await _cleanup(runners)
    pumped_chunks = _counter_total(router_metrics.relay_chunks) \
        - chunks_before
    pumped_bytes = _counter_total(router_metrics.relay_bytes) \
        - bytes_before
    # The first chunk goes out on-loop (commit), the rest through the
    # pump; upstream chunk coalescing makes the exact count variable.
    assert pumped_chunks >= 1
    assert 0 < pumped_bytes < len(sse)


# ---------------------------------------------------------------------------
# Fault semantics through the pump
# ---------------------------------------------------------------------------


def _slo_file(tmp_path, config) -> str:
    p = tmp_path / "slo.yaml"
    p.write_text(yaml.safe_dump(config))
    return str(p)


async def _wait_counts(state, total, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if sum(state.slo.counts().values()) >= total:
            return state.slo.counts()
        await asyncio.sleep(0.02)
    return state.slo.counts()


async def test_pump_client_disconnect_client_abort_and_slot_release(
        tmp_path):
    """A client that hangs up while the pump owns its socket must
    classify client_abort (not failed), and the QoS concurrency slot
    must come back — the finally-path the flag-off build runs is the
    same one the pump feeds."""
    tenants_file = str(tmp_path / "tenants.json")
    with open(tenants_file, "w") as f:
        json.dump({"tenants": [], "max_concurrency": 1}, f)
    engine = FakeEngine(model=MODEL, ttft=0.01, tokens_per_sec=5.0)
    _, _, app, rurl, runners = await _router(
        engine=engine, relay_off_loop=True,
        qos_tenants_file=tenants_file,
        slo_config=_slo_file(tmp_path, {"default": {"ttft_p99_s": 30.0}}))
    state = app["state"]
    try:
        async with aiohttp.ClientSession() as s:
            resp = await s.post(
                f"{rurl}/v1/completions",
                json={"model": MODEL, "prompt": "hi",
                      "max_tokens": 200, "stream": True})
            assert resp.status == 200
            await resp.content.readany()  # committed (handoff window)
            resp.close()                  # client vanishes mid-pump
        counts = await _wait_counts(state, 1)
        # The slot freed: with max_concurrency=1 a leaked lease would
        # park this next request behind a dead one.
        deadline = time.monotonic() + 10.0
        while state.qos.queue.inflight and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        assert state.qos.queue.inflight == 0
        async with aiohttp.ClientSession() as s:
            status, _ = await _stream_body(s, rurl, max_tokens=2)
            assert status == 200
    finally:
        await _cleanup(runners)
    assert counts["client_abort"] == 1
    assert counts["failed"] == 0


async def test_pump_inter_chunk_deadline_still_fires(tmp_path):
    """The inter-chunk deadline is enforced loop-side on the upstream
    read, so a replica that hangs mid-stream while the pump owns the
    client socket must still classify failed, truncate the stream, and
    abort the pump job (no terminal chunk, connection torn down)."""
    engine = FakeEngine(model=MODEL, ttft=0.01, tokens_per_sec=500.0)
    _, eurl, app, rurl, runners = await _router(
        engine=engine, relay_off_loop=True,
        fault_tolerance=True, ft_inter_chunk_deadline=0.4,
        slo_config=_slo_file(tmp_path, {"default": {"ttft_p99_s": 30.0}}))
    state = app["state"]
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{eurl}/fault", json={
                    "mode": "hang_mid_stream", "after_chunks": 2,
                    "times": -1}) as resp:
                assert resp.status == 200
            t0 = time.perf_counter()
            status, body = await _stream_chat(s, rurl, max_tokens=200)
            wall = time.perf_counter() - t0
        assert status == 200
        assert b"data: [DONE]" not in body  # truncated, not completed
        assert wall < 5.0                   # deadline, not a hang
        counts = await _wait_counts(state, 1)
    finally:
        await _cleanup(runners)
    assert counts["failed"] == 1
    assert counts["client_abort"] == 0


async def test_usage_reconciliation_parity_gamed_max_tokens(tmp_path):
    """A tenant gaming the admission estimator (string max_tokens) is
    debited from what actually streamed; the pump buffers the same
    full_response, so the reconciled overage must match the flag-off
    leg exactly."""
    debits = {}
    for leg in ("off", "on"):
        tenants_file = str(tmp_path / f"tenants-{leg}.json")
        with open(tenants_file, "w") as f:
            json.dump({"tenants": [
                {"name": "gamer", "api_keys": ["sk-g"], "weight": 1,
                 "tokens_per_second": 100, "burst_seconds": 2.0}]}, f)
        before = _counter_total(router_metrics.qos_usage_reconciled)
        _, _, app, rurl, runners = await _router(
            relay_off_loop=(leg == "on"), qos_tenants_file=tenants_file)
        try:
            async with aiohttp.ClientSession() as s:
                # Gamed: a string max_tokens is invisible to the
                # admission estimator but honored by the engine, so
                # reconciliation must debit the overage post-stream.
                status, _ = await _stream_chat(
                    s, rurl, max_tokens="400",
                    headers={"Authorization": "Bearer sk-g"})
                assert status == 200
        finally:
            await _cleanup(runners)
        debits[leg] = _counter_total(
            router_metrics.qos_usage_reconciled) - before
    assert debits["off"] > 0
    assert debits["on"] == debits["off"]


# ---------------------------------------------------------------------------
# Flag-off parity: no relay series, no relay state
# ---------------------------------------------------------------------------


async def test_flag_off_no_relay_state_and_no_series():
    """Without --relay-off-loop nothing is constructed and no relay
    series ever appears: sample-count deltas across a served streamed
    request and a scrape are zero (the registry is shared across tests,
    so deltas — not absolutes — are the invariant)."""
    before = _relay_sample_counts()
    _, _, app, rurl, runners = await _router()
    try:
        assert app["state"].relay is None
        async with aiohttp.ClientSession() as s:
            status, _ = await _stream_body(s, rurl, stream=True)
            assert status == 200
            async with s.get(f"{rurl}/metrics") as resp:
                assert resp.status == 200
    finally:
        await _cleanup(runners)
    assert _relay_sample_counts() == before


# ---------------------------------------------------------------------------
# 2-worker federation leg: pump metrics worker-stamped
# ---------------------------------------------------------------------------


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _post_stream(url: str, timeout: float = 10.0) -> int:
    req = urllib.request.Request(
        url + "/v1/completions",
        data=json.dumps({"model": MODEL, "prompt": "hi",
                         "max_tokens": 8, "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp.read()
        return resp.status


async def test_two_worker_relay_metrics_worker_stamped():
    """``--router-workers 2 --relay-off-loop``: every worker runs its
    own pump pool; the aggregated scrape must carry the pool gauges
    per-worker (``worker="0"``/``worker="1"``) and the relay counters
    summed fleet-wide without a worker label. The engine paces its
    token frames so chunks keep arriving after the handoff commit point
    — an unpaced body lands whole in the first read and leaves the pump
    nothing to count."""
    engine = FakeEngine(model=MODEL, ttft=0.0, tokens_per_sec=200)
    erunner, eurl = await _start(engine.make_app())
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    rurl = f"http://127.0.0.1:{port}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "production_stack_tpu.router.app",
         "--host", "127.0.0.1", "--port", str(port),
         "--router-workers", "2",
         "--relay-off-loop", "--relay-pump-threads", "1",
         "--static-backends", eurl, "--static-models", MODEL,
         "--routing-logic", "roundrobin",
         "--engine-stats-interval", "60",
         "--log-level", "warning"],
        env=dict(os.environ, TPU_STACK_LOG_LEVEL="warning"))
    try:
        for _ in range(150):
            try:
                await asyncio.to_thread(_get, rurl + "/health", 2.0)
                break
            except OSError:
                await asyncio.sleep(0.2)
        else:
            raise RuntimeError("2-worker relay router never became healthy")

        for _ in range(4):
            assert await asyncio.to_thread(_post_stream, rurl) == 200

        exposition = (await asyncio.to_thread(
            _get, rurl + "/metrics")).decode()
        pump_lines = [l for l in exposition.splitlines()
                      if l.startswith("vllm_router:relay_active_pumps{")]
        assert any('worker="0"' in l for l in pump_lines), pump_lines
        assert any('worker="1"' in l for l in pump_lines), pump_lines
        assert all(float(l.split()[-1]) == 1.0 for l in pump_lines)
        chunk_lines = [l for l in exposition.splitlines()
                       if l.startswith("vllm_router:relay_chunks_total{")]
        assert chunk_lines and all(
            "worker=" not in l for l in chunk_lines), chunk_lines
        assert sum(float(l.split()[-1]) for l in chunk_lines) >= 1
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
        await erunner.cleanup()
