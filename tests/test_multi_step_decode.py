"""Fused multi-step decode: burst generation must match step-by-step
generation exactly (greedy), and finish conditions mid-burst must trim."""

import threading

import numpy as np

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.core import EngineCore
from production_stack_tpu.engine.sampling import SamplingParams


def _run(core, prompt_ids, max_tokens=16, rid="r", ignore_eos=True):
    done = threading.Event()
    out = []

    def on_token(tok, finish):
        if tok is not None:
            out.append(tok)
        if finish is not None:
            out.append(("finish", finish))
            done.set()

    core.add_request(
        rid, list(prompt_ids),
        SamplingParams(temperature=0.0, max_tokens=max_tokens,
                       ignore_eos=ignore_eos),
        on_token,
    )
    assert done.wait(timeout=180), "generation timed out"
    return out


def _config(**kw):
    base = dict(
        model="tiny-llama", max_model_len=256, max_num_seqs=4,
        block_size=8, num_blocks=128, max_loras=0,
    )
    base.update(kw)
    return EngineConfig(**base)


def test_burst_matches_single_step():
    rng = np.random.default_rng(11)
    prompt = [int(t) for t in rng.integers(0, 500, size=30)]

    single = EngineCore(_config(decode_steps=1))
    single.start()
    try:
        out_single = _run(single, prompt, max_tokens=17)
    finally:
        single.stop()

    burst = EngineCore(_config(decode_steps=8))
    burst.start()
    try:
        out_burst = _run(burst, prompt, max_tokens=17)
    finally:
        burst.stop()

    assert out_burst == out_single


def test_burst_respects_max_tokens():
    core = EngineCore(_config(decode_steps=8))
    core.start()
    try:
        out = _run(core, list(range(20)), max_tokens=5)
        tokens = [t for t in out if not isinstance(t, tuple)]
        assert len(tokens) == 5
        assert out[-1] == ("finish", "length")
    finally:
        core.stop()


def test_burst_concurrent_sequences():
    core = EngineCore(_config(decode_steps=8))
    core.start()
    try:
        outs = {}
        events = {}

        def make_cb(key):
            ev = threading.Event()
            events[key] = ev
            outs[key] = []

            def cb(tok, finish):
                if tok is not None:
                    outs[key].append(tok)
                if finish is not None:
                    ev.set()
            return cb

        rng = np.random.default_rng(13)
        prompts = {
            f"s{i}": [int(t) for t in rng.integers(0, 500, size=10 + i)]
            for i in range(4)
        }
        for i, (key, prompt) in enumerate(prompts.items()):
            core.add_request(
                key, prompt,
                SamplingParams(temperature=0.0, max_tokens=9 + i,
                               ignore_eos=True),
                make_cb(key),
            )
        for key, ev in events.items():
            assert ev.wait(timeout=180), f"{key} timed out"
        # Every sequence got exactly its max_tokens — budgets differ per
        # sequence, so per-seq burst-width clamping (allow masking) is
        # actually exercised within shared bursts.
        for i in range(4):
            assert len(outs[f"s{i}"]) == 9 + i
    finally:
        core.stop()
