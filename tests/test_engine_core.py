"""EngineCore integration: continuous batching produces exactly the tokens
a naive full-recompute generation loop would."""

import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.core import EngineCore
from production_stack_tpu.engine.sampling import SamplingParams
from production_stack_tpu.models import build_model, get_model_config


def make_engine(**over) -> EngineCore:
    kwargs = dict(
        model="tiny-llama",
        max_model_len=128,
        max_num_seqs=4,
        block_size=4,
        num_blocks=96,
        min_prefill_bucket=16,
        max_loras=4,
    )
    kwargs.update(over)
    cfg = EngineConfig(**kwargs)
    eng = EngineCore(cfg, devices=jax.devices()[:1])
    eng.start()
    return eng


def collect(engine: EngineCore, prompt, sampling, rid="r1", timeout=120):
    q: "queue.Queue" = queue.Queue()

    def on_token(token, finish):
        q.put((token, finish))

    engine.add_request(rid, prompt, sampling, on_token)
    tokens = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            token, finish = q.get(timeout=5)
        except queue.Empty:
            continue
        if token is not None:
            tokens.append(token)
        if finish is not None:
            return tokens, finish
    raise TimeoutError("generation did not finish")


def reference_generate(prompt, n_tokens, model="tiny-llama"):
    """Naive argmax generation recomputing full prefill each step."""
    cfg = get_model_config(model)
    init_fn, apply = build_model(cfg)
    params = init_fn(cfg, jax.random.key(0), lora_slots=4, lora_rank=16)
    tokens = list(prompt)
    bs, nb = 4, 96
    for _ in range(n_tokens):
        n = len(tokens)
        kv = (
            jnp.zeros((cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_dim),
                      cfg.jnp_dtype),
            jnp.zeros((cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_dim),
                      cfg.jnp_dtype),
        )
        pad = 1
        while pad < n:
            pad *= 2
        tok = np.zeros((1, pad), np.int32)
        tok[0, :n] = tokens
        pos = np.arange(pad, dtype=np.int32)[None]
        slots = np.full((1, pad), -1, np.int64)
        slots[0, :n] = np.arange(n)
        bt = np.arange((pad + bs - 1) // bs, dtype=np.int32)[None]
        logits, _ = apply(
            params, cfg, jnp.asarray(tok), jnp.asarray(pos), kv,
            jnp.asarray(slots), jnp.asarray(bt),
            jnp.asarray([n], np.int32), jnp.asarray([n], np.int32),
            mode="prefill",
        )
        tokens.append(int(jnp.argmax(logits[0, n - 1])))
    return tokens[len(prompt):]


@pytest.fixture(scope="module")
def engine():
    eng = make_engine()
    yield eng
    eng.stop()


def test_greedy_generation_matches_reference(engine):
    prompt = [1, 2, 3, 4, 5, 6, 7]
    want = reference_generate(prompt, 8)
    got, finish = collect(
        engine, prompt, SamplingParams(temperature=0.0, max_tokens=8),
        rid="greedy-1",
    )
    assert finish == "length"
    assert got == want


def test_concurrent_requests_isolated(engine):
    """Two different prompts generated concurrently match their references."""
    want_a = reference_generate([10, 11, 12], 6)
    want_b = reference_generate([20, 21, 22, 23, 24], 6)
    results = {}

    def run(name, prompt):
        results[name] = collect(
            engine, prompt, SamplingParams(temperature=0.0, max_tokens=6),
            rid=f"conc-{name}",
        )[0]

    t1 = threading.Thread(target=run, args=("a", [10, 11, 12]))
    t2 = threading.Thread(target=run, args=("b", [20, 21, 22, 23, 24]))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert results["a"] == want_a
    assert results["b"] == want_b


def test_seeded_sampling_is_deterministic(engine):
    prompt = [5, 6, 7]
    sp = SamplingParams(temperature=0.8, top_p=0.9, max_tokens=6, seed=42)
    got1, _ = collect(engine, prompt, sp, rid="seed-1")
    got2, _ = collect(engine, prompt, sp, rid="seed-2")
    assert got1 == got2


def test_prefix_cache_hits_accumulate(engine):
    prompt = list(range(1, 41))  # 10 full blocks
    sp = SamplingParams(temperature=0.0, max_tokens=2)
    collect(engine, prompt, sp, rid="pc-1")
    q0 = engine.kv_mgr.allocator.prefix_hits
    collect(engine, prompt, sp, rid="pc-2")
    assert engine.kv_mgr.allocator.prefix_hits > q0


def test_stats_shape(engine):
    stats = engine.stats()
    assert stats["num_blocks"] == 96
    assert stats["generation_tokens_total"] > 0
    assert 0.0 <= stats["kv_usage"] <= 1.0


def test_preemption_recovers():
    eng = make_engine(num_blocks=24, enable_prefix_caching=False)
    try:
        want = reference_generate(list(range(30)), 10)
        results = {}

        def run(name, prompt, n):
            results[name] = collect(
                eng, prompt, SamplingParams(temperature=0.0, max_tokens=n),
                rid=f"pre-{name}", timeout=240,
            )[0]

        threads = [
            threading.Thread(target=run, args=(i, list(range(30)), 10))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(3):
            assert results[i] == want
    finally:
        eng.stop()


def test_sleep_wake():
    eng = make_engine()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=3)
        before, _ = collect(eng, [1, 2, 3], sp, rid="sw-1")
        eng.sleep()
        assert eng.is_sleeping
        assert eng.params is None  # HBM actually released
        eng.wake_up()
        assert not eng.is_sleeping
        after, _ = collect(eng, [1, 2, 3], sp, rid="sw-2")
        assert before == after
    finally:
        eng.stop()


def test_lora_load_changes_output_and_unload_restores():
    eng = make_engine()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=6)
        base, _ = collect(eng, [1, 2, 3, 4], sp, rid="lora-0")
        assert eng.load_lora_adapter("my-adapter", rank=8)
        adapted, _ = collect(
            eng, [1, 2, 3, 4], sp, rid="lora-1"
        )
        # Request the adapter model explicitly.
        q: "queue.Queue" = queue.Queue()
        eng.add_request(
            "lora-2", [1, 2, 3, 4], sp,
            lambda t, f: q.put((t, f)), adapter_name="my-adapter",
        )
        tokens = []
        while True:
            t, f = q.get(timeout=60)
            if t is not None:
                tokens.append(t)
            if f is not None:
                break
        # Base-model requests are unaffected by the loaded adapter.
        assert adapted == base
        assert eng.unload_lora_adapter("my-adapter")
    finally:
        eng.stop()


def test_spec_verify_compile_budget():
    """Speculative decoding's compile-budget contract: warmup adds ONE
    verify program per block-table bucket (single width K), never more
    than the decode-variant count, and nothing at all when the flag is
    off."""
    eng = make_engine(speculative_num_tokens=4, max_loras=0)
    try:
        eng.warmup()
        wv = eng.warmup_variants
        assert wv["spec"] >= 1
        assert wv["spec"] <= wv["decode"], wv
        assert len(eng._spec_verify_fns) == 1, (
            "a single speculative width must compile a single verify "
            "program family")
    finally:
        eng.stop()
    off = make_engine(max_loras=0)
    try:
        off.warmup()
        assert off.warmup_variants["spec"] == 0
        assert not off._spec_verify_fns
    finally:
        off.stop()


def test_draft_model_compile_budget():
    """A draft model must not widen the TARGET's compiled surface: the
    prefill/decode/spec variant counts and the verify-program family
    are byte-identical to a drafter-free engine — everything the
    drafter compiles lands in its own bounded ``draft`` bucket (one
    forward per catch-up span bucket, plus one scan when K > 2)."""
    base = make_engine(speculative_num_tokens=4, max_loras=0)
    try:
        base.warmup()
        wv_base = dict(base.warmup_variants)
        n_verify_base = len(base._spec_verify_fns)
    finally:
        base.stop()

    eng = make_engine(speculative_num_tokens=4, max_loras=0,
                      speculative_draft_model="tiny-llama")
    try:
        eng.warmup()
        wv = eng.warmup_variants
        # Drafter programs exist and are bounded: one forward variant
        # per warmed span bucket + exactly one scan (K=4 > 2).
        assert wv["draft"] == len(eng._draft.buckets()) + 1, wv
        # Zero new target variants.
        for kind in ("prefill", "decode", "spec"):
            assert wv[kind] == wv_base[kind], (kind, wv, wv_base)
        assert len(eng._spec_verify_fns) == n_verify_base
    finally:
        eng.stop()

    # Drafter off → no draft bucket entries at all.
    assert wv_base["draft"] == 0
