"""RequestStatsMonitor tests (cf. reference src/vllm_router/stats/request_stats.py)."""

from production_stack_tpu.router.request_stats import (
    MovingAverageMonitor,
    RequestStatsMonitor,
)
from production_stack_tpu.utils.misc import SingletonMeta


def fresh_monitor(window=10.0) -> RequestStatsMonitor:
    SingletonMeta._reset_instance(RequestStatsMonitor)
    return RequestStatsMonitor(window)


def test_moving_average_window_expiry():
    mon = MovingAverageMonitor(10.0)
    mon.update(0.0, 1.0)
    mon.update(5.0, 3.0)
    assert mon.get_average() == 2.0
    mon.update(12.0, 5.0)  # t=0 sample expires
    assert mon.get_average() == 4.0
    assert mon.get_count() == 2


def test_request_lifecycle_stats():
    m = fresh_monitor(window=60.0)
    url = "http://e1:8000"
    m.on_new_request(url, "r1", 100.0)
    stats = m.get_request_stats(current_time=100.5)
    assert stats[url].in_prefill_requests == 1
    m.on_request_response(url, "r1", 100.8)  # TTFT = 0.8
    stats = m.get_request_stats(current_time=101.0)
    assert stats[url].in_prefill_requests == 0
    assert stats[url].in_decoding_requests == 1
    assert abs(stats[url].ttft - 0.8) < 1e-9
    m.on_request_complete(url, "r1", 102.0)
    stats = m.get_request_stats(current_time=102.0)
    assert stats[url].finished_requests == 1
    assert stats[url].in_decoding_requests == 0
    assert abs(stats[url].avg_latency - 2.0) < 1e-9


def test_qps_counts_requests_in_window():
    m = fresh_monitor(window=10.0)
    url = "http://e1:8000"
    for i in range(5):
        m.on_new_request(url, f"r{i}", 100.0 + i)
    stats = m.get_request_stats(current_time=105.0)
    assert abs(stats[url].qps - 0.5) < 1e-9  # 5 requests / 10 s window


def test_swapped_counter():
    m = fresh_monitor()
    m.on_request_swapped("http://e1:8000", "r1", 1.0)
    m.on_new_request("http://e1:8000", "r1", 1.0)
    stats = m.get_request_stats(current_time=2.0)
    assert stats["http://e1:8000"].num_swapped_requests == 1


def test_itl_tracking():
    m = fresh_monitor()
    url = "http://e1:8000"
    m.on_new_request(url, "r1", 0.0)
    m.on_request_response(url, "r1", 1.0)
    m.on_token(url, "r1", 1.1)
    m.on_token(url, "r1", 1.3)
    stats = m.get_request_stats(current_time=2.0)
    assert 0.1 < stats[url].avg_itl < 0.2
