"""Experimental, feature-gated router features (reference src/vllm_router/experimental/)."""
