"""PII detection: block requests containing detected PII.

Rebuild of reference ``src/vllm_router/experimental/pii/`` (~600 LoC):
``check_pii`` middleware semantics (``pii/middleware.py:101-154``) with a
regex analyzer (``pii/analyzers/regex.py``). The Presidio analyzer variant is
not shipped (presidio is not in this image); the analyzer interface mirrors
it so one can be plugged in.
"""

from __future__ import annotations

import re
from typing import List, Optional

from prometheus_client import Counter

from production_stack_tpu.router.metrics import REGISTRY
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

pii_requests_blocked = Counter(
    "vllm_router:pii_requests_blocked_total",
    "Requests blocked due to detected PII",
    ["entity_type"],
    registry=REGISTRY,
)

PII_PATTERNS = {
    "EMAIL_ADDRESS": re.compile(
        r"[a-zA-Z0-9._%+-]+@[a-zA-Z0-9.-]+\.[a-zA-Z]{2,}"
    ),
    "US_SSN": re.compile(r"\b\d{3}-\d{2}-\d{4}\b"),
    "CREDIT_CARD": re.compile(r"\b(?:\d[ -]*?){13,16}\b"),
    "PHONE_NUMBER": re.compile(
        r"\b(?:\+?1[-.\s]?)?\(?\d{3}\)?[-.\s]\d{3}[-.\s]\d{4}\b"
    ),
    "IP_ADDRESS": re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),
    "API_KEY": re.compile(r"\b(?:sk|pk|api|key)[-_][a-zA-Z0-9]{16,}\b"),
    "IBAN": re.compile(r"\b[A-Z]{2}\d{2}[A-Z0-9]{11,30}\b"),
}


def _luhn_ok(digits: str) -> bool:
    total, alt = 0, False
    for d in reversed(digits):
        n = int(d)
        if alt:
            n *= 2
            if n > 9:
                n -= 9
        total += n
        alt = not alt
    return total % 10 == 0


class RegexPIIAnalyzer:
    def analyze(self, text: str) -> List[str]:
        found = []
        for entity, pattern in PII_PATTERNS.items():
            m = pattern.search(text)
            if not m:
                continue
            if entity == "CREDIT_CARD":
                digits = re.sub(r"\D", "", m.group())
                if len(digits) < 13 or not _luhn_ok(digits):
                    continue
            found.append(entity)
        return found


class PIIDetector:
    """Checks request prompts/messages for PII before routing."""

    def __init__(self, analyzer=None):
        self.analyzer = analyzer or RegexPIIAnalyzer()

    async def check_request(self, request_json: dict) -> Optional[str]:
        texts = []
        if isinstance(request_json.get("prompt"), str):
            texts.append(request_json["prompt"])
        for m in request_json.get("messages", []) or []:
            if isinstance(m.get("content"), str):
                texts.append(m["content"])
        for text in texts:
            entities = self.analyzer.analyze(text)
            if entities:
                for e in entities:
                    pii_requests_blocked.labels(entity_type=e).inc()
                logger.warning("Blocked request containing PII: %s", entities)
                return ",".join(entities)
        return None
