"""Semantic cache: serve chat completions from similar cached requests.

Rebuild of reference ``src/vllm_router/experimental/semantic_cache*`` (~1100
LoC): embed the chat messages, search a vector store for a similar past
request, and serve the cached response on a hit; store new responses after
completion.

The reference uses sentence-transformers + FAISS. FAISS is not in this image
and model downloads require egress, so the store is a numpy matrix with exact
cosine search (fine for cache sizes this layer sees) and the embedder is
pluggable: a deterministic hashed bag-of-ngrams embedder by default (no
downloads), sentence-transformers if a local model path is supplied.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

import numpy as np

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class HashedNgramEmbedder:
    """Deterministic text embedding via hashed character n-grams.

    No model download, no heavy deps; cosine-similar texts share n-grams.
    """

    def __init__(self, dim: int = 512, ngram: int = 3):
        self.dim = dim
        self.ngram = ngram

    def encode(self, texts: List[str]) -> np.ndarray:
        import xxhash

        out = np.zeros((len(texts), self.dim), dtype=np.float32)
        for i, text in enumerate(texts):
            t = text.lower()
            for j in range(max(len(t) - self.ngram + 1, 1)):
                h = xxhash.xxh64_intdigest(t[j : j + self.ngram])
                out[i, h % self.dim] += 1.0
            norm = np.linalg.norm(out[i])
            if norm > 0:
                out[i] /= norm
        return out


class SentenceTransformerEmbedder:
    def __init__(self, model_path: str):
        from sentence_transformers import SentenceTransformer

        self.model = SentenceTransformer(model_path)

    def encode(self, texts: List[str]) -> np.ndarray:
        vecs = self.model.encode(texts, normalize_embeddings=True)
        return np.asarray(vecs, dtype=np.float32)


class VectorStore:
    """Exact cosine-similarity store (FAISS flat-IP equivalent)."""

    def __init__(self, dim: int):
        self.dim = dim
        self._vecs = np.zeros((0, dim), dtype=np.float32)
        self._payloads: List[dict] = []
        self._lock = threading.Lock()

    def add(self, vec: np.ndarray, payload: dict) -> None:
        with self._lock:
            self._vecs = np.vstack([self._vecs, vec.reshape(1, -1)])
            self._payloads.append(payload)

    def search(self, vec: np.ndarray, threshold: float) -> Optional[dict]:
        with self._lock:
            if len(self._payloads) == 0:
                return None
            sims = self._vecs @ vec.reshape(-1)
            best = int(np.argmax(sims))
            if sims[best] >= threshold:
                return self._payloads[best]
            return None

    def __len__(self) -> int:
        return len(self._payloads)


class SemanticCache:
    """Reference semantic_cache.py:77-150 semantics: search before routing,
    store after completion; per-model partitions."""

    def __init__(
        self,
        model_name: str = "hashed-ngram",
        cache_dir: Optional[str] = None,
        threshold: float = 0.95,
        dim: int = 512,
    ):
        if model_name and os.path.isdir(model_name):
            self.embedder = SentenceTransformerEmbedder(model_name)
            probe = self.embedder.encode(["probe"])
            dim = probe.shape[1]
        else:
            self.embedder = HashedNgramEmbedder(dim=dim)
        self.threshold = threshold
        self._stores: Dict[str, VectorStore] = {}
        self._dim = dim
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _render(request_json: dict) -> str:
        parts = []
        for m in request_json.get("messages", []) or []:
            c = m.get("content")
            if isinstance(c, str):
                parts.append(f"{m.get('role')}: {c}")
        return "\n".join(parts)

    def _store_for(self, model: str) -> VectorStore:
        if model not in self._stores:
            self._stores[model] = VectorStore(self._dim)
        return self._stores[model]

    async def check(self, request_json: dict) -> Optional[dict]:
        """Return a cached chat completion response dict on a hit."""
        if request_json.get("stream"):
            return None
        text = self._render(request_json)
        if not text:
            return None
        vec = self.embedder.encode([text])[0]
        hit = self._store_for(request_json.get("model", "")).search(
            vec, self.threshold
        )
        if hit is not None:
            self.hits += 1
            logger.info("Semantic cache hit (%d total)", self.hits)
            response = dict(hit["response"])
            response["cached"] = True
            return response
        self.misses += 1
        return None

    async def maybe_store(self, request_json: dict, response_body: bytes) -> None:
        if request_json.get("stream"):
            return
        try:
            response = json.loads(response_body)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return
        if "choices" not in response:
            return
        text = self._render(request_json)
        if not text:
            return
        vec = self.embedder.encode([text])[0]
        self._store_for(request_json.get("model", "")).add(
            vec, {"request": request_json, "response": response}
        )
