"""KV controller: tracks which engine holds which token-prefix.

Replaces the LMCache controller the reference embeds in its router for
KV-aware routing (reference ``src/vllm_router/routers/routing_logic.py:238-344``;
engine workers register via ``LMCACHE_ENABLE_CONTROLLER`` env,
``helm/templates/deployment-vllm-multi.yaml:324-339``).

Design: engines report *chunk hashes* of the prefixes they admit to (and
evict from) their KV caches. The controller keeps a trie of chunk hashes →
set of instance ids, answering "which live engine holds the longest stored
prefix of this prompt". Chunk hashing matches the router's prefix trie
(xxhash64 over fixed-size character chunks) so router and engines agree on
granularity without sharing a tokenizer.

Runs in-process in the router (as the reference does) and is also exposed
over HTTP by the router app (``/kv/register``, ``/kv/admit``, ``/kv/evict``,
``/kv/lookup``) so out-of-process engines can report — the reference's
controller↔worker TCP channel equivalent.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Set, Tuple

import xxhash

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_global_kv_controller: Optional["KVController"] = None

CHUNK_SIZE = 128  # characters per hash chunk; matches router.hashtrie default

# Reserved instance id for the shared L3 cache server: engines that spill
# evicted prefixes to the remote tier report the eviction with
# ``spilled=true`` and the controller re-attributes the claim to this
# pseudo-instance instead of dropping it, so the fleet pull path can try
# peer → L3 → recompute.
L3_INSTANCE = "__l3__"


def chunk_hashes(text: str, chunk_size: int = CHUNK_SIZE,
                 salt: Optional[str] = None) -> List[int]:
    """Chunk-hash a prompt. ``salt`` partitions the hash space (used for
    LoRA adapters, whose k/v projections differ from the base model's):
    a salted chunk never collides with the unsalted one, so prefix reuse
    and cross-replica pulls cannot cross adapter boundaries. Chunk
    boundaries are unchanged; ``salt=None``/"" yields today's exact
    hashes, keeping the base-model path byte-identical."""
    if salt:
        prefix = f"{salt}\x00"
        return [
            xxhash.xxh64_intdigest(prefix + text[i : i + chunk_size])
            for i in range(0, len(text), chunk_size)
        ]
    return [
        xxhash.xxh64_intdigest(text[i : i + chunk_size])
        for i in range(0, len(text), chunk_size)
    ]


def path_key(parent_key: int, chunk_hash: int) -> int:
    """Stable identifier of one trie node: hash of the root-anchored
    chunk-hash path down to it. Engines compute the same keys from their
    admitted prefixes, so controller and engine can compare claim sets
    without shipping the trie (anti-entropy resync digests)."""
    return xxhash.xxh64_intdigest(f"{parent_key}:{chunk_hash}")


def path_keys(hashes: List[int], root_key: int = 0) -> List[int]:
    """Node keys for every prefix of a root-anchored chunk-hash path."""
    keys = []
    k = root_key
    for h in hashes:
        k = path_key(k, h)
        keys.append(k)
    return keys


def claim_digest(keys: "Set[int]") -> Tuple[int, int]:
    """Compact (count, xor-of-keys) digest of a claim set. Order-free,
    incremental on both sides; a mismatch in either field triggers a
    full-state resync."""
    x = 0
    for k in keys:
        x ^= k
    return len(keys), x


class _Node:
    __slots__ = ("children", "instances", "hits")

    def __init__(self):
        self.children: Dict[int, "_Node"] = {}
        # instance id -> timestamp of its most recent admission report for
        # this chunk. Engines cannot report block-level evictions exactly
        # (their caches are token-chain keyed), so staleness is bounded by
        # a TTL instead: claims older than ``admit_ttl`` are ignored at
        # lookup. Live prefixes stay fresh because engines re-admit on
        # every served request.
        self.instances: Dict[str, float] = {}
        # Reuse count: how many lookups terminated at this node as their
        # deepest live match (GET /debug/kv/trie hottest-prefix ranking).
        self.hits = 0


class KVController:
    """In-process KV index. All methods are coroutine-safe via one lock.

    ``admit_ttl``: seconds an admission claim stays routable without being
    re-reported (0 disables expiry).
    """

    def __init__(self, chunk_size: int = CHUNK_SIZE,
                 admit_ttl: float = 600.0,
                 lease_misses: int = 3,
                 heartbeat_interval: float = 10.0):
        self.chunk_size = chunk_size
        self.admit_ttl = admit_ttl
        # Lease policy: an instance that registered with a generation id
        # (i.e. opted into heartbeating) expires after missing
        # ``lease_misses`` beats of its reported interval (or the
        # controller default when it didn't report one). Legacy
        # registrations without a generation never lease-expire — their
        # staleness stays bounded by admit_ttl alone, exactly as before.
        self.lease_misses = max(1, int(lease_misses))
        self.heartbeat_interval = heartbeat_interval
        self._root = _Node()
        # id -> {url, last_seen, generation, state, last_beat,
        #        heartbeat_interval}; generation/last_beat are None for
        # legacy (non-heartbeating) registrations.
        self._instances: Dict[str, dict] = {}
        self._l3_url: Optional[str] = None
        self._lock = asyncio.Lock()
        # Claims removed by the crash-consistency machinery, by reason
        # (expired lease / superseded generation / anti-entropy resync).
        # Exported as vllm_router:kv_claims_swept_total by the router.
        self.swept_totals: Dict[str, int] = {
            "expired": 0, "regenerated": 0, "resync": 0}

    def attach_l3(self, url: Optional[str]) -> None:
        """Attach (or detach) the shared L3 cache server. While set,
        spilled evictions keep their trie claims under ``L3_INSTANCE``.
        Sync on purpose: called at router init, before serving starts."""
        self._l3_url = url
        if url:
            self._instances[L3_INSTANCE] = {
                "url": url, "last_seen": time.time()}
        else:
            self._instances.pop(L3_INSTANCE, None)

    def _fresh(self, ts: float, now: float) -> bool:
        return self.admit_ttl <= 0 or (now - ts) <= self.admit_ttl

    # -- claim walks (shared by dereg, lease expiry, resync) ---------------
    def _sweep_claims_locked(self, instance_id: str,
                             keep_keys: Optional[Set[int]] = None) -> int:
        """Pop every trie claim of ``instance_id``; returns how many were
        removed. ``keep_keys`` (resync replace) counts only nodes whose
        path key is NOT about to be re-claimed, so the swept counter
        reflects actual drift, not the full claim set. Lock held."""
        removed = 0
        stack = [(self._root, 0)]
        while stack:
            node, key = stack.pop()
            if node.instances.pop(instance_id, None) is not None:
                if keep_keys is None or key not in keep_keys:
                    removed += 1
            for h, child in node.children.items():
                stack.append((child, path_key(key, h)))
        return removed

    def _claim_keys_locked(self, instance_id: str) -> Set[int]:
        """Path keys of every trie node claimed by ``instance_id``."""
        keys: Set[int] = set()
        stack = [(self._root, 0)]
        while stack:
            node, key = stack.pop()
            if instance_id in node.instances:
                keys.add(key)
            for h, child in node.children.items():
                stack.append((child, path_key(key, h)))
        return keys

    # -- instance registry (reference QueryInstMsg / instance-id→URL map) --
    async def register_instance(self, instance_id: str, url: str,
                                generation: Optional[str] = None,
                                heartbeat_interval: Optional[float] = None,
                                ) -> dict:
        """Register (or re-register) an engine incarnation.

        With a ``generation`` id, registration is crash-consistent: any
        prior incarnation at the same instance id OR the same URL whose
        generation differs (including legacy generation-less records) is
        swept atomically — a kill -9'd replica's restart replaces the
        corpse's claims in one step instead of waiting out the lease or
        the admit TTL. Returns ``{"swept": N, "superseded": [ids]}``."""
        now = time.time()
        swept = 0
        superseded: List[str] = []
        async with self._lock:
            if generation is not None:
                stale = [
                    other_id for other_id, info in self._instances.items()
                    if other_id != L3_INSTANCE
                    and info.get("generation") != generation
                    and (other_id == instance_id or info.get("url") == url)
                ]
                for other_id in stale:
                    swept += self._sweep_claims_locked(other_id)
                    if other_id != instance_id:
                        self._instances.pop(other_id, None)
                        superseded.append(other_id)
                self.swept_totals["regenerated"] += swept
            self._instances[instance_id] = {
                "url": url, "last_seen": now,
                "generation": generation,
                "state": "live",
                # Only heartbeat-capable registrations carry a lease: a
                # generation-less legacy engine, or one that disabled
                # heartbeating (interval 0/None), must never be expired
                # for beats it was never going to send.
                "last_beat": (
                    now if generation is not None and heartbeat_interval
                    else None),
                "heartbeat_interval": heartbeat_interval,
            }
        if swept:
            logger.info(
                "KV controller: register %s gen=%s swept %d stale claims "
                "(superseded: %s)", instance_id, generation, swept,
                superseded or [instance_id])
        return {"swept": swept, "superseded": superseded}

    async def heartbeat(self, instance_id: str,
                        generation: Optional[str] = None,
                        heartbeat_interval: Optional[float] = None) -> dict:
        """Lease renewal. ``known=False`` tells the engine to re-register
        (controller restarted, instance expired+superseded, or the
        generation doesn't match the registered incarnation).
        ``revived=True`` flags a beat from an instance the lease sweeper
        had expired — its claims were swept, so the engine should resync
        to restore them."""
        now = time.time()
        async with self._lock:
            info = self._instances.get(instance_id)
            if info is None or (
                    generation is not None
                    and info.get("generation") is not None
                    and info["generation"] != generation):
                return {"known": False, "revived": False}
            revived = info.get("state") == "expired"
            info["last_beat"] = now
            info["last_seen"] = now
            info["state"] = "live"
            if heartbeat_interval:
                info["heartbeat_interval"] = heartbeat_interval
            if generation is not None and info.get("generation") is None:
                info["generation"] = generation
        return {"known": True, "revived": revived}

    async def expire_stale_leases(self, now: Optional[float] = None
                                  ) -> List[dict]:
        """Expire instances whose lease lapsed (``lease_misses`` missed
        heartbeats): sweep their claims (anything spilled to the L3 is
        already attributed to ``__l3__`` and survives; the rest is gone
        with the process) and mark them ``expired`` so service discovery
        and the EPP health view exclude their URLs. The record is kept —
        a late beat from a paused-not-dead process revives it (and
        triggers a resync)."""
        now = time.time() if now is None else now
        expired: List[dict] = []
        async with self._lock:
            for instance_id, info in self._instances.items():
                if instance_id == L3_INSTANCE:
                    continue
                last_beat = info.get("last_beat")
                if last_beat is None or info.get("state") == "expired":
                    continue
                interval = (info.get("heartbeat_interval")
                            or self.heartbeat_interval)
                if now - last_beat <= self.lease_misses * interval:
                    continue
                swept = self._sweep_claims_locked(instance_id)
                info["state"] = "expired"
                self.swept_totals["expired"] += swept
                expired.append({"instance_id": instance_id,
                                "url": info.get("url"),
                                "swept": swept})
        for item in expired:
            logger.warning(
                "KV controller: lease expired for %s (%s) — swept %d "
                "claims", item["instance_id"], item["url"], item["swept"])
        return expired

    # -- anti-entropy resync (heals timeout-swallowed admit/evict) ---------
    async def resync_check(self, instance_id: str, count: int,
                           xor: int) -> dict:
        """Compare an engine's claim digest against the controller's view
        of that instance. ``match=False`` asks the engine to follow up
        with its full state (:meth:`resync_replace`)."""
        async with self._lock:
            if instance_id not in self._instances:
                return {"known": False, "match": False}
            have_count, have_xor = claim_digest(
                self._claim_keys_locked(instance_id))
        return {"known": True,
                "match": have_count == count and have_xor == xor}

    async def resync_replace(self, instance_id: str,
                             paths: List[List[int]]) -> dict:
        """Replace an instance's claims with the engine's authoritative
        state: ``paths`` are root-anchored chunk-hash lists (one per
        admitted prefix). Claims the controller held that the engine no
        longer does are swept (reason ``resync``); missing ones are
        re-admitted. Heals silent drift from swallowed reports."""
        now = time.time()
        keep: Set[int] = set()
        for path in paths:
            keep.update(path_keys(path))
        async with self._lock:
            if instance_id not in self._instances:
                return {"known": False, "swept": 0, "claims": 0}
            swept = self._sweep_claims_locked(instance_id, keep_keys=keep)
            self.swept_totals["resync"] += swept
            for path in paths:
                node = self._root
                for h in path:
                    nxt = node.children.get(h)
                    if nxt is None:
                        nxt = _Node()
                        node.children[h] = nxt
                    nxt.instances[instance_id] = now
                    node = nxt
            info = self._instances[instance_id]
            info["last_seen"] = now
        if swept:
            logger.info(
                "KV controller: resync for %s swept %d drifted claims "
                "(%d paths reasserted)", instance_id, swept, len(paths))
        return {"known": True, "swept": swept, "claims": len(keep)}

    async def instances_snapshot(self) -> List[dict]:
        """Operator/EPP view of the instance table (GET /kv/instances)."""
        now = time.time()
        async with self._lock:
            out = []
            for instance_id, info in self._instances.items():
                last_beat = info.get("last_beat")
                out.append({
                    "instance_id": instance_id,
                    "url": info.get("url"),
                    "generation": info.get("generation"),
                    "state": ("l3" if instance_id == L3_INSTANCE
                              else info.get("state", "live")),
                    "last_beat_age_s": (
                        round(now - last_beat, 3)
                        if last_beat is not None else None),
                    "claims": len(self._claim_keys_locked(instance_id)),
                })
            return out

    async def trie_snapshot(self, top: int = 10) -> dict:
        """Operator view of the chunk-hash trie (GET /debug/kv/trie):
        per-instance claim counts (incl. ``__l3__``), node-depth
        distribution, an approximate in-memory footprint, and the top-N
        hottest prefixes by lookup reuse count. One locked walk; sized
        for a debug endpoint, not the request path."""
        import sys

        async with self._lock:
            node_count = 0
            claim_count = 0
            approx_bytes = 0
            max_depth = 0
            depth_distribution: Dict[int, int] = {}
            claims_by_instance: Dict[str, int] = {}
            hot: List[Tuple[int, int, tuple, "_Node"]] = []
            stack: List[Tuple["_Node", int, tuple]] = [
                (self._root, 0, ())]
            while stack:
                node, depth, path = stack.pop()
                node_count += 1
                approx_bytes += (sys.getsizeof(node)
                                 + sys.getsizeof(node.children)
                                 + sys.getsizeof(node.instances))
                if depth > 0:
                    depth_distribution[depth] = \
                        depth_distribution.get(depth, 0) + 1
                    max_depth = max(max_depth, depth)
                for instance_id in node.instances:
                    claim_count += 1
                    claims_by_instance[instance_id] = \
                        claims_by_instance.get(instance_id, 0) + 1
                if node.hits > 0:
                    hot.append((node.hits, depth, path, node))
                for h, child in node.children.items():
                    stack.append((child, depth + 1, path + (h,)))
            hot.sort(key=lambda item: (-item[0], item[1]))
            now = time.time()
            hottest = [{
                "hits": hits,
                "depth": depth,
                "approx_chars": depth * self.chunk_size,
                # The trie stores chunk hashes, not text: the path is the
                # prefix's identity (matches path_keys/claim digests).
                "chunk_hashes": [format(h, "016x") for h in path],
                "holders": sorted(
                    i for i, ts in node.instances.items()
                    if i in self._instances and self._fresh(ts, now)),
            } for hits, depth, path, node in hot[:max(int(top), 0)]]
            return {
                "chunk_size": self.chunk_size,
                "nodes": node_count,
                "claims": claim_count,
                "max_depth": max_depth,
                "approx_memory_bytes": approx_bytes,
                # JSON object keys are strings; keep depths sorted.
                "depth_distribution": {
                    str(d): depth_distribution[d]
                    for d in sorted(depth_distribution)},
                "claims_by_instance": dict(
                    sorted(claims_by_instance.items())),
                "hottest_prefixes": hottest,
            }

    async def fed_digest(self) -> dict:
        """Whole-trie digest for cross-worker divergence comparison
        (``obs/federation.py``). Each multi-worker router process keeps
        its own controller, fed only by the register/admit reports that
        happened to land on its socket — so tries WILL diverge. The
        digest xors a deterministic hash of every (instance, path-key)
        claim pair (``hash()`` is per-process salted; xxhash is not), so
        equal digests mean identical claim sets regardless of report
        order, and the claim/instance counts show how lopsided the
        fragmentation is."""
        async with self._lock:
            instance_ids = sorted(self._instances)
            claims = 0
            xor = 0
            for instance_id in instance_ids:
                for key in self._claim_keys_locked(instance_id):
                    claims += 1
                    xor ^= xxhash.xxh64_intdigest(
                        f"{instance_id}:{key:016x}")
            return {
                "instances": len(instance_ids),
                "claims": claims,
                "xor": format(xor, "016x"),
            }

    async def deregister_instance(self, instance_id: str) -> None:
        async with self._lock:
            self._instances.pop(instance_id, None)
            stack = [self._root]
            while stack:
                node = stack.pop()
                node.instances.pop(instance_id, None)
                stack.extend(node.children.values())

    async def deregister_url(self, url: str) -> List[str]:
        """Deregister every instance advertising ``url`` (breaker-open
        mirror: the router only knows the failing endpoint's URL)."""
        async with self._lock:
            gone = [i for i, info in self._instances.items()
                    if info["url"] == url and i != L3_INSTANCE]
        for instance_id in gone:
            await self.deregister_instance(instance_id)
        if gone:
            logger.info("KV controller: deregistered %s for %s", gone, url)
        return gone

    async def instance_url(self, instance_id: str) -> Optional[str]:
        async with self._lock:
            info = self._instances.get(instance_id)
            return info["url"] if info else None

    async def instances(self) -> Dict[str, str]:
        async with self._lock:
            return {k: v["url"] for k, v in self._instances.items()}

    # -- admission/eviction reports from engines ---------------------------
    async def admit(self, instance_id: str, hashes: List[int]) -> None:
        now = time.time()
        async with self._lock:
            if instance_id in self._instances:
                self._instances[instance_id]["last_seen"] = now
            node = self._root
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None:
                    nxt = _Node()
                    node.children[h] = nxt
                nxt.instances[instance_id] = now
                node = nxt

    async def admit_text(self, instance_id: str, text: str,
                         salt: Optional[str] = None) -> None:
        await self.admit(
            instance_id, chunk_hashes(text, self.chunk_size, salt=salt))

    async def evict(self, instance_id: str, hashes: List[int],
                    spilled: bool = False) -> None:
        """Evict a prefix: the instance no longer holds `hashes` nor anything
        below it. With ``spilled=True`` (engine pushed the evicted blocks to
        the remote tier) and an attached L3, the vacated claims transfer to
        ``L3_INSTANCE`` so the prefix stays routable via the shared cache."""
        async with self._lock:
            node = self._root
            path = []
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None:
                    return
                path.append(nxt)
                node = nxt
            now = time.time()
            mark_l3 = spilled and self._l3_url is not None
            stack = [node]
            while stack:
                n = stack.pop()
                if n.instances.pop(instance_id, None) is not None and mark_l3:
                    n.instances[L3_INSTANCE] = now
                stack.extend(n.children.values())
            if mark_l3 and L3_INSTANCE in self._instances:
                self._instances[L3_INSTANCE]["last_seen"] = now

    # -- lookup (reference LookupMsg) --------------------------------------
    async def lookup(self, text: str,
                     salt: Optional[str] = None) -> Optional[Tuple[int, str]]:
        """Longest stored prefix of ``text`` → (matched_chars, instance_id).

        Live engine holders win over the L3 pseudo-instance at equal match
        depth; a strictly deeper L3 match wins so the fleet pull path can
        restore the longer prefix from the shared cache. ``salt`` scopes
        the match to one adapter's claims (see ``chunk_hashes``)."""
        hashes = chunk_hashes(text, self.chunk_size, salt=salt)
        now = time.time()
        async with self._lock:
            node = self._root
            matched = 0
            best_engines: Optional[Set[str]] = None
            engine_matched = 0
            l3_matched = 0
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None or not nxt.instances:
                    break
                live = {
                    i for i, ts in nxt.instances.items()
                    if i in self._instances and self._fresh(ts, now)
                    # Lease-expired instances are never routable holders,
                    # even if a paused-not-dead process kept admitting.
                    and self._instances[i].get("state", "live") != "expired"
                }
                if not live:
                    break
                matched += 1
                engines = live - {L3_INSTANCE}
                if engines:
                    best_engines = engines
                    engine_matched = matched
                if L3_INSTANCE in live:
                    l3_matched = matched
                node = nxt
            if matched > 0:
                # ``node`` is the deepest chunk with a live claim — this
                # lookup reused the prefix ending there.
                node.hits += 1
            if best_engines and engine_matched >= l3_matched:
                matched_chars = min(engine_matched * self.chunk_size,
                                    len(text))
                # Deterministic tiebreak: most-recently-seen instance.
                inst = max(
                    best_engines,
                    key=lambda i: self._instances.get(i, {}).get(
                        "last_seen", 0),
                )
                return matched_chars, inst
            if l3_matched:
                return min(l3_matched * self.chunk_size, len(text)), \
                    L3_INSTANCE
            return None


def initialize_kv_controller(chunk_size: int = CHUNK_SIZE,
                             admit_ttl: float = 600.0,
                             lease_misses: int = 3,
                             heartbeat_interval: float = 10.0,
                             ) -> KVController:
    global _global_kv_controller
    _global_kv_controller = KVController(
        chunk_size, admit_ttl=admit_ttl, lease_misses=lease_misses,
        heartbeat_interval=heartbeat_interval)
    return _global_kv_controller


def get_kv_controller() -> Optional[KVController]:
    return _global_kv_controller
