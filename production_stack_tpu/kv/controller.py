"""KV controller: tracks which engine holds which token-prefix.

Replaces the LMCache controller the reference embeds in its router for
KV-aware routing (reference ``src/vllm_router/routers/routing_logic.py:238-344``;
engine workers register via ``LMCACHE_ENABLE_CONTROLLER`` env,
``helm/templates/deployment-vllm-multi.yaml:324-339``).

Design: engines report *chunk hashes* of the prefixes they admit to (and
evict from) their KV caches. The controller keeps a trie of chunk hashes →
set of instance ids, answering "which live engine holds the longest stored
prefix of this prompt". Chunk hashing matches the router's prefix trie
(xxhash64 over fixed-size character chunks) so router and engines agree on
granularity without sharing a tokenizer.

Runs in-process in the router (as the reference does) and is also exposed
over HTTP by the router app (``/kv/register``, ``/kv/admit``, ``/kv/evict``,
``/kv/lookup``) so out-of-process engines can report — the reference's
controller↔worker TCP channel equivalent.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Set, Tuple

import xxhash

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_global_kv_controller: Optional["KVController"] = None

CHUNK_SIZE = 128  # characters per hash chunk; matches router.hashtrie default

# Reserved instance id for the shared L3 cache server: engines that spill
# evicted prefixes to the remote tier report the eviction with
# ``spilled=true`` and the controller re-attributes the claim to this
# pseudo-instance instead of dropping it, so the fleet pull path can try
# peer → L3 → recompute.
L3_INSTANCE = "__l3__"


def chunk_hashes(text: str, chunk_size: int = CHUNK_SIZE) -> List[int]:
    return [
        xxhash.xxh64_intdigest(text[i : i + chunk_size])
        for i in range(0, len(text), chunk_size)
    ]


class _Node:
    __slots__ = ("children", "instances")

    def __init__(self):
        self.children: Dict[int, "_Node"] = {}
        # instance id -> timestamp of its most recent admission report for
        # this chunk. Engines cannot report block-level evictions exactly
        # (their caches are token-chain keyed), so staleness is bounded by
        # a TTL instead: claims older than ``admit_ttl`` are ignored at
        # lookup. Live prefixes stay fresh because engines re-admit on
        # every served request.
        self.instances: Dict[str, float] = {}


class KVController:
    """In-process KV index. All methods are coroutine-safe via one lock.

    ``admit_ttl``: seconds an admission claim stays routable without being
    re-reported (0 disables expiry).
    """

    def __init__(self, chunk_size: int = CHUNK_SIZE,
                 admit_ttl: float = 600.0):
        self.chunk_size = chunk_size
        self.admit_ttl = admit_ttl
        self._root = _Node()
        self._instances: Dict[str, dict] = {}  # id -> {url, last_seen}
        self._l3_url: Optional[str] = None
        self._lock = asyncio.Lock()

    def attach_l3(self, url: Optional[str]) -> None:
        """Attach (or detach) the shared L3 cache server. While set,
        spilled evictions keep their trie claims under ``L3_INSTANCE``.
        Sync on purpose: called at router init, before serving starts."""
        self._l3_url = url
        if url:
            self._instances[L3_INSTANCE] = {
                "url": url, "last_seen": time.time()}
        else:
            self._instances.pop(L3_INSTANCE, None)

    def _fresh(self, ts: float, now: float) -> bool:
        return self.admit_ttl <= 0 or (now - ts) <= self.admit_ttl

    # -- instance registry (reference QueryInstMsg / instance-id→URL map) --
    async def register_instance(self, instance_id: str, url: str) -> None:
        async with self._lock:
            self._instances[instance_id] = {"url": url, "last_seen": time.time()}

    async def deregister_instance(self, instance_id: str) -> None:
        async with self._lock:
            self._instances.pop(instance_id, None)
            stack = [self._root]
            while stack:
                node = stack.pop()
                node.instances.pop(instance_id, None)
                stack.extend(node.children.values())

    async def deregister_url(self, url: str) -> List[str]:
        """Deregister every instance advertising ``url`` (breaker-open
        mirror: the router only knows the failing endpoint's URL)."""
        async with self._lock:
            gone = [i for i, info in self._instances.items()
                    if info["url"] == url and i != L3_INSTANCE]
        for instance_id in gone:
            await self.deregister_instance(instance_id)
        if gone:
            logger.info("KV controller: deregistered %s for %s", gone, url)
        return gone

    async def instance_url(self, instance_id: str) -> Optional[str]:
        async with self._lock:
            info = self._instances.get(instance_id)
            return info["url"] if info else None

    async def instances(self) -> Dict[str, str]:
        async with self._lock:
            return {k: v["url"] for k, v in self._instances.items()}

    # -- admission/eviction reports from engines ---------------------------
    async def admit(self, instance_id: str, hashes: List[int]) -> None:
        now = time.time()
        async with self._lock:
            if instance_id in self._instances:
                self._instances[instance_id]["last_seen"] = now
            node = self._root
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None:
                    nxt = _Node()
                    node.children[h] = nxt
                nxt.instances[instance_id] = now
                node = nxt

    async def admit_text(self, instance_id: str, text: str) -> None:
        await self.admit(instance_id, chunk_hashes(text, self.chunk_size))

    async def evict(self, instance_id: str, hashes: List[int],
                    spilled: bool = False) -> None:
        """Evict a prefix: the instance no longer holds `hashes` nor anything
        below it. With ``spilled=True`` (engine pushed the evicted blocks to
        the remote tier) and an attached L3, the vacated claims transfer to
        ``L3_INSTANCE`` so the prefix stays routable via the shared cache."""
        async with self._lock:
            node = self._root
            path = []
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None:
                    return
                path.append(nxt)
                node = nxt
            now = time.time()
            mark_l3 = spilled and self._l3_url is not None
            stack = [node]
            while stack:
                n = stack.pop()
                if n.instances.pop(instance_id, None) is not None and mark_l3:
                    n.instances[L3_INSTANCE] = now
                stack.extend(n.children.values())
            if mark_l3 and L3_INSTANCE in self._instances:
                self._instances[L3_INSTANCE]["last_seen"] = now

    # -- lookup (reference LookupMsg) --------------------------------------
    async def lookup(self, text: str) -> Optional[Tuple[int, str]]:
        """Longest stored prefix of ``text`` → (matched_chars, instance_id).

        Live engine holders win over the L3 pseudo-instance at equal match
        depth; a strictly deeper L3 match wins so the fleet pull path can
        restore the longer prefix from the shared cache."""
        hashes = chunk_hashes(text, self.chunk_size)
        now = time.time()
        async with self._lock:
            node = self._root
            matched = 0
            best_engines: Optional[Set[str]] = None
            engine_matched = 0
            l3_matched = 0
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None or not nxt.instances:
                    break
                live = {
                    i for i, ts in nxt.instances.items()
                    if i in self._instances and self._fresh(ts, now)
                }
                if not live:
                    break
                matched += 1
                engines = live - {L3_INSTANCE}
                if engines:
                    best_engines = engines
                    engine_matched = matched
                if L3_INSTANCE in live:
                    l3_matched = matched
                node = nxt
            if best_engines and engine_matched >= l3_matched:
                matched_chars = min(engine_matched * self.chunk_size,
                                    len(text))
                # Deterministic tiebreak: most-recently-seen instance.
                inst = max(
                    best_engines,
                    key=lambda i: self._instances.get(i, {}).get(
                        "last_seen", 0),
                )
                return matched_chars, inst
            if l3_matched:
                return min(l3_matched * self.chunk_size, len(text)), \
                    L3_INSTANCE
            return None


def initialize_kv_controller(chunk_size: int = CHUNK_SIZE,
                             admit_ttl: float = 600.0) -> KVController:
    global _global_kv_controller
    _global_kv_controller = KVController(chunk_size, admit_ttl=admit_ttl)
    return _global_kv_controller


def get_kv_controller() -> Optional[KVController]:
    return _global_kv_controller
