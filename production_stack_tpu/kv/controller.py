"""KV controller: tracks which engine holds which token-prefix.

Replaces the LMCache controller the reference embeds in its router for
KV-aware routing (reference ``src/vllm_router/routers/routing_logic.py:238-344``;
engine workers register via ``LMCACHE_ENABLE_CONTROLLER`` env,
``helm/templates/deployment-vllm-multi.yaml:324-339``).

Design: engines report *chunk hashes* of the prefixes they admit to (and
evict from) their KV caches. The controller keeps a trie of chunk hashes →
set of instance ids, answering "which live engine holds the longest stored
prefix of this prompt". Chunk hashing matches the router's prefix trie
(xxhash64 over fixed-size character chunks) so router and engines agree on
granularity without sharing a tokenizer.

Runs in-process in the router (as the reference does) and is also exposed
over HTTP by the router app (``/kv/register``, ``/kv/admit``, ``/kv/evict``,
``/kv/lookup``) so out-of-process engines can report — the reference's
controller↔worker TCP channel equivalent.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Set, Tuple

import xxhash

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_global_kv_controller: Optional["KVController"] = None

CHUNK_SIZE = 128  # characters per hash chunk; matches router.hashtrie default


def chunk_hashes(text: str, chunk_size: int = CHUNK_SIZE) -> List[int]:
    return [
        xxhash.xxh64_intdigest(text[i : i + chunk_size])
        for i in range(0, len(text), chunk_size)
    ]


class _Node:
    __slots__ = ("children", "instances")

    def __init__(self):
        self.children: Dict[int, "_Node"] = {}
        # instance id -> timestamp of its most recent admission report for
        # this chunk. Engines cannot report block-level evictions exactly
        # (their caches are token-chain keyed), so staleness is bounded by
        # a TTL instead: claims older than ``admit_ttl`` are ignored at
        # lookup. Live prefixes stay fresh because engines re-admit on
        # every served request.
        self.instances: Dict[str, float] = {}


class KVController:
    """In-process KV index. All methods are coroutine-safe via one lock.

    ``admit_ttl``: seconds an admission claim stays routable without being
    re-reported (0 disables expiry).
    """

    def __init__(self, chunk_size: int = CHUNK_SIZE,
                 admit_ttl: float = 600.0):
        self.chunk_size = chunk_size
        self.admit_ttl = admit_ttl
        self._root = _Node()
        self._instances: Dict[str, dict] = {}  # id -> {url, last_seen}
        self._lock = asyncio.Lock()

    def _fresh(self, ts: float, now: float) -> bool:
        return self.admit_ttl <= 0 or (now - ts) <= self.admit_ttl

    # -- instance registry (reference QueryInstMsg / instance-id→URL map) --
    async def register_instance(self, instance_id: str, url: str) -> None:
        async with self._lock:
            self._instances[instance_id] = {"url": url, "last_seen": time.time()}

    async def deregister_instance(self, instance_id: str) -> None:
        async with self._lock:
            self._instances.pop(instance_id, None)
            stack = [self._root]
            while stack:
                node = stack.pop()
                node.instances.pop(instance_id, None)
                stack.extend(node.children.values())

    async def instance_url(self, instance_id: str) -> Optional[str]:
        async with self._lock:
            info = self._instances.get(instance_id)
            return info["url"] if info else None

    async def instances(self) -> Dict[str, str]:
        async with self._lock:
            return {k: v["url"] for k, v in self._instances.items()}

    # -- admission/eviction reports from engines ---------------------------
    async def admit(self, instance_id: str, hashes: List[int]) -> None:
        now = time.time()
        async with self._lock:
            if instance_id in self._instances:
                self._instances[instance_id]["last_seen"] = now
            node = self._root
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None:
                    nxt = _Node()
                    node.children[h] = nxt
                nxt.instances[instance_id] = now
                node = nxt

    async def admit_text(self, instance_id: str, text: str) -> None:
        await self.admit(instance_id, chunk_hashes(text, self.chunk_size))

    async def evict(self, instance_id: str, hashes: List[int]) -> None:
        """Evict a prefix: the instance no longer holds `hashes` nor anything
        below it."""
        async with self._lock:
            node = self._root
            path = []
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None:
                    return
                path.append(nxt)
                node = nxt
            stack = [node]
            while stack:
                n = stack.pop()
                n.instances.pop(instance_id, None)
                stack.extend(n.children.values())

    # -- lookup (reference LookupMsg) --------------------------------------
    async def lookup(self, text: str) -> Optional[Tuple[int, str]]:
        """Longest stored prefix of ``text`` → (matched_chars, instance_id)."""
        hashes = chunk_hashes(text, self.chunk_size)
        now = time.time()
        async with self._lock:
            node = self._root
            matched = 0
            best: Optional[Set[str]] = None
            for h in hashes:
                nxt = node.children.get(h)
                if nxt is None or not nxt.instances:
                    break
                live = {
                    i for i, ts in nxt.instances.items()
                    if i in self._instances and self._fresh(ts, now)
                }
                if not live:
                    break
                matched += 1
                best = live
                node = nxt
            if not best:
                return None
            matched_chars = min(matched * self.chunk_size, len(text))
            # Deterministic tiebreak: most-recently-seen instance.
            inst = max(
                best, key=lambda i: self._instances.get(i, {}).get("last_seen", 0)
            )
            return matched_chars, inst


def initialize_kv_controller(chunk_size: int = CHUNK_SIZE,
                             admit_ttl: float = 600.0) -> KVController:
    global _global_kv_controller
    _global_kv_controller = KVController(chunk_size, admit_ttl=admit_ttl)
    return _global_kv_controller


def get_kv_controller() -> Optional[KVController]:
    return _global_kv_controller
