"""Standalone KV cache server: the remote offload tier.

TPU-native equivalent of the reference's ``lmcache_experimental_server``
process (deployed by the CacheServer CRD,
``operator/internal/controller/cacheserver_controller.go:135-206``, and the
helm ``deployment-cache-server.yaml``). Engines spill evicted KV blocks here
(via :class:`production_stack_tpu.kv.offload.RemoteKVClient`) and pull them
back on prefix-cache misses, which also gives cross-engine KV sharing: an
engine can reuse a prefix another engine computed
(``docs/source/use_cases/sharing-kv-cache.rst``).

API (block payloads are opaque bytes — the .npz format of
``offload.pack_block``):

- ``PUT  /v1/blocks/{hash}``  store a block
- ``GET  /v1/blocks/{hash}``  fetch a block (404 on miss)
- ``HEAD /v1/blocks/{hash}``  existence probe
- ``GET  /health``, ``GET /metrics``
"""

from __future__ import annotations

import argparse
import asyncio
from collections import OrderedDict
from typing import Optional

from aiohttp import web

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class CacheServer:
    def __init__(self, capacity_bytes: int = 4 << 30):
        self.capacity_bytes = capacity_bytes
        self._store: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evicted = 0

    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 30)
        app.router.add_put("/v1/blocks/{hash}", self.handle_put)
        # add_get also serves HEAD (existence probe) via the same handler.
        app.router.add_get("/v1/blocks/{hash}", self.handle_get)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        return app

    async def handle_put(self, request: web.Request) -> web.Response:
        key = request.match_info["hash"]
        data = await request.read()
        if key in self._store:
            self._bytes -= len(self._store.pop(key))
        while self._bytes + len(data) > self.capacity_bytes and self._store:
            _, old = self._store.popitem(last=False)
            self._bytes -= len(old)
            self.evicted += 1
        if self._bytes + len(data) > self.capacity_bytes:
            return web.json_response({"error": "block exceeds capacity"},
                                     status=413)
        self._store[key] = data
        self._bytes += len(data)
        return web.json_response({"status": "ok", "bytes": len(data)})

    async def handle_get(self, request: web.Request) -> web.Response:
        key = request.match_info["hash"]
        if request.method == "HEAD":  # existence probe: no LRU/stat churn
            status = 200 if key in self._store else 404
            return web.Response(status=status)
        data = self._store.get(key)
        if data is None:
            self.misses += 1
            return web.Response(status=404)
        self._store.move_to_end(key)
        self.hits += 1
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        text = (
            "# TYPE kvcache:blocks gauge\n"
            f"kvcache:blocks {len(self._store)}\n"
            "# TYPE kvcache:bytes gauge\n"
            f"kvcache:bytes {self._bytes}\n"
            "# TYPE kvcache:capacity_bytes gauge\n"
            f"kvcache:capacity_bytes {self.capacity_bytes}\n"
            "# TYPE kvcache:hits counter\n"
            f"kvcache:hits_total {self.hits}\n"
            "# TYPE kvcache:misses counter\n"
            f"kvcache:misses_total {self.misses}\n"
            "# TYPE kvcache:evicted counter\n"
            f"kvcache:evicted_total {self.evicted}\n"
        )
        return web.Response(text=text, content_type="text/plain")


async def run_cache_server(server: CacheServer, host: str, port: int) -> web.AppRunner:
    runner = web.AppRunner(server.make_app())
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("KV cache server on %s:%d (capacity %.1f GiB)",
                host, port, server.capacity_bytes / (1 << 30))
    return runner


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(description="Standalone KV cache server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--capacity-gb", type=float, default=4.0)
    args = p.parse_args(argv)
    server = CacheServer(capacity_bytes=int(args.capacity_gb * (1 << 30)))

    async def _run():
        await run_cache_server(server, args.host, args.port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_run())


if __name__ == "__main__":
    main()
