"""KV cache layer: controller, offload, cache server, transfer.

The LMCache-equivalent subsystem of the stack (reference integrates LMCache
via env config — ``helm/templates/deployment-vllm-multi.yaml:182-195`` — and
embeds its controller in the router for KV-aware routing,
``src/vllm_router/routers/routing_logic.py:238-255``). Here the layer is
native to the stack:

- :mod:`controller`  -- tracks which engine holds which token-prefix.
- :mod:`offload`     -- TPU HBM -> host RAM KV block offload.
- :mod:`cache_server` -- standalone remote KV cache tier.
- :mod:`transfer`    -- engine-to-engine KV movement (disaggregated prefill).
"""
