"""Fleet-scale serving: global prefix cache + load-predictive autoscaling.

Turns the per-replica pieces that already exist in-tree — the KV
controller's chunk-hash trie (:mod:`production_stack_tpu.kv.controller`),
the disaggregated-prefill ``/kv/pull`` path
(:mod:`production_stack_tpu.engine.server`), and the remote
:mod:`production_stack_tpu.kv.cache_server` — into one cluster-wide cache
hierarchy:

- **L1** (HBM prefix cache, per replica) and **L2** (host offload tier)
  are unchanged.
- **Cross-replica pulls**: when the controller says the longest stored
  prefix of a prompt lives on a *different* replica than the routing
  pick, :class:`FleetCache` asks the picked replica to ``/kv/pull`` the
  prefix from the holder before the request is proxied. A pull that
  misses, times out, or targets a breaker-open holder degrades to plain
  recompute — never to request failure.
- **L3**: engines with ``--kv-remote-url`` spill evicted blocks to the
  shared cache server; the controller re-attributes those claims to the
  ``__l3__`` pseudo-instance (``spilled=true`` eviction reports), so a
  prefix that left every replica is still pullable fleet-wide.

:class:`AutoscaleRecommender` closes the loop: it folds the signals the
stack already exports — per-replica queue depth, HBM KV pressure, and
the QoS batch backlog — into a recommended replica count (served at
``GET /autoscale/recommendation`` and as
``vllm_router:autoscale_*_replicas`` gauges for KEDA/HPA), plus a
scale-in orchestration that drains the chosen replica via the engine's
``/drain`` hook and evicts it from the controller so no request is ever
routed to — or told to pull from — a disappearing holder.
"""

from __future__ import annotations

import asyncio
import math
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from production_stack_tpu.kv.controller import L3_INSTANCE, KVController
from production_stack_tpu.kv.economics import (
    DEFAULT_CHARS_PER_TOKEN, DEFAULT_PREFILL_TPS_FLOOR, PullLedger)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Clamp range for --fleet-auto-min-match applications: the advisor's raw
# break-even can collapse to ~0 (free transfers) or explode (slow link);
# neither extreme is a sane routing threshold.
AUTO_MIN_MATCH_FLOOR = 64
AUTO_MIN_MATCH_CAP = 1_000_000


@dataclass
class FleetCacheConfig:
    pull_timeout_s: float = 15.0
    # Minimum controller match (characters) worth a pull round-trip; a
    # shorter prefix recomputes faster than it transfers.
    min_match_chars: int = 256
    l3_url: Optional[str] = None
    api_key: Optional[str] = None
    # Stampede control: router-side cap on concurrent pull orchestrations
    # against ONE holder replica (the holder additionally self-protects
    # with its own /kv/pull admission semaphore → 503 + Retry-After).
    pull_max_concurrency: int = 8
    # Pull-economics ledger (kv/economics.py): recompute-cost floor used
    # when no measured prefill throughput is wired, and the chars/token
    # conversion for the advisor's recommended min-match.
    prefill_tokens_per_s_floor: float = DEFAULT_PREFILL_TPS_FLOOR
    chars_per_token: float = DEFAULT_CHARS_PER_TOKEN
    ledger_capacity: int = 512
    # --fleet-auto-min-match: apply the advisor's recommendation to
    # min_match_chars on a damped interval (new = old + damping*(rec-old)).
    auto_min_match: bool = False
    auto_min_match_interval_s: float = 30.0
    auto_min_match_damping: float = 0.3


class FleetCache:
    """Router-side orchestrator of cross-replica KV pulls.

    One instance per router process, created only when ``--fleet-cache``
    is set — with the flag off the request path never reaches this
    module (parity convention, see tests/test_fleet.py).
    """

    def __init__(self, config: FleetCacheConfig,
                 kv_controller: KVController,
                 fault_tolerance=None):
        self.config = config
        self.kv_controller = kv_controller
        self.fault_tolerance = fault_tolerance
        self.pulls_attempted = 0
        self.pulls_succeeded = 0
        self.pulls_failed = 0
        self.pulls_rejected = 0
        self.pulls_coalesced = 0
        self.l3_pulls = 0
        # Stampede control state. _single_flight dedups identical-prefix
        # pulls to the same target (followers await the leader's
        # transfer); _inflight_by_holder enforces the per-holder cap;
        # last_attempt_by_holder lets the chaos harness assert that
        # transfers against a dead holder stop within one lease interval.
        self._single_flight: Dict[tuple, "asyncio.Task"] = {}
        self._inflight_by_holder: Dict[str, int] = {}
        self.last_attempt_by_holder: Dict[str, float] = {}
        # Pull economics: every orchestrated pull (including rejected and
        # failed ones) lands one classified record here; the crossover
        # advisor reads the measured transfer model back out.
        self.ledger = PullLedger(
            capacity=config.ledger_capacity,
            prefill_tokens_per_s_floor=config.prefill_tokens_per_s_floor,
            chars_per_token=config.chars_per_token)
        # --fleet-auto-min-match bookkeeping (apply_auto_min_match).
        self.auto_min_match_applied = 0
        self.auto_min_match_last: Optional[dict] = None

    def _record_economics(self, server_url: str, holder: str,
                          holder_url: str, matched_chars: int, outcome: str,
                          bytes_moved: int = 0, tokens_saved: int = 0,
                          pull_seconds: float = 0.0) -> dict:
        """Land a pull in the ledger and export its classification."""
        from production_stack_tpu.router import metrics as router_metrics

        rec = self.ledger.record(
            server_url=server_url, holder=holder, holder_url=holder_url,
            matched_chars=matched_chars, outcome=outcome,
            bytes_moved=bytes_moved, tokens_saved=tokens_saved,
            pull_seconds=pull_seconds)
        if rec["classification"] == "win":
            router_metrics.kv_pull_wins.labels(server=server_url).inc()
        else:
            router_metrics.kv_pull_losses.labels(server=server_url).inc()
        router_metrics.kv_pull_net_seconds_saved.labels(
            server=server_url).inc(rec["net_seconds_saved"])
        return rec

    def apply_auto_min_match(self) -> dict:
        """One --fleet-auto-min-match application step: move
        ``min_match_chars`` toward the advisor's recommendation, damped
        (``new = old + damping*(recommended-old)``) and clamped to
        [AUTO_MIN_MATCH_FLOOR, AUTO_MIN_MATCH_CAP]. A no-data or
        pull-never-wins advisory applies nothing. Called by the router's
        background applier; public so tests can drive one step."""
        old = self.config.min_match_chars
        advice = self.ledger.advise(current_min_match_chars=old)
        recommended = advice.get("recommended_min_match_chars")
        state = {"applied": False, "old": old, "new": old,
                 "recommended": recommended,
                 "pull_never_wins": advice.get("pull_never_wins", False),
                 "reason": advice.get("reason")}
        if recommended is not None:
            target = min(max(int(recommended), AUTO_MIN_MATCH_FLOOR),
                         AUTO_MIN_MATCH_CAP)
            new = int(round(
                old + self.config.auto_min_match_damping * (target - old)))
            new = min(max(new, AUTO_MIN_MATCH_FLOOR), AUTO_MIN_MATCH_CAP)
            if new != old:
                self.config.min_match_chars = new
                logger.info(
                    "fleet: auto-min-match %d -> %d (advisor recommends "
                    "%d from %d measured pulls)", old, new, recommended,
                    advice.get("samples", 0))
            state.update({"applied": True, "new": new})
            self.auto_min_match_applied += 1
        self.auto_min_match_last = state
        return state

    def _headers(self, request_id: str) -> Dict[str, str]:
        headers = {"X-Request-Id": request_id}
        if self.config.api_key:
            headers["Authorization"] = f"Bearer {self.config.api_key}"
        return headers

    async def maybe_pull(self, server_url: str, prompt: str,
                         request_json: dict, request_id: str,
                         salt: Optional[str] = None) -> Optional[dict]:
        """If a different replica (or the L3) holds a long-enough prefix
        of ``prompt``, ask ``server_url`` to pull it before prefill.

        Returns a summary dict (for tracing/tests) or None when no pull
        applied. Never raises: every failure mode means "recompute",
        which the engine does anyway. ``salt`` scopes the lookup to one
        LoRA adapter's claims — a pull never crosses adapter boundaries.
        """
        if not prompt or len(prompt) < self.config.min_match_chars:
            return None
        try:
            match = await self.kv_controller.lookup(prompt, salt=salt)
        except Exception as e:  # noqa: BLE001 - lookup is best-effort
            logger.warning("fleet lookup failed: %s", e)
            return None
        if match is None:
            return None
        matched_chars, holder = match
        if matched_chars < self.config.min_match_chars:
            return None
        holder_url = await self.kv_controller.instance_url(holder)
        if not holder_url:
            return None
        if holder_url.rstrip("/") == server_url.rstrip("/"):
            return None  # the pick already holds it — plain L1 hit
        ft = self.fault_tolerance
        if ft is not None and holder_url in ft.breaker.blocked_urls():
            # Breaker-open holder: don't burn the pull timeout against a
            # replica that is already failing — recompute instead.
            logger.info("fleet: skipping pull from breaker-open holder %s",
                        holder_url)
            return None

        from production_stack_tpu.router import metrics as router_metrics

        holder_key = holder_url.rstrip("/")
        flight_key = (server_url.rstrip("/"), holder_key, salt or "",
                      hash(prompt[:matched_chars]))
        task = self._single_flight.get(flight_key)
        coalesced = task is not None
        if task is None:
            if (self._inflight_by_holder.get(holder_key, 0)
                    >= self.config.pull_max_concurrency):
                # The holder is already serving the cap's worth of
                # transfers for the router — recompute is cheaper than
                # queueing behind a stampede.
                self.pulls_rejected += 1
                router_metrics.kv_pull_rejected.labels(
                    server=server_url).inc()
                self._record_economics(server_url, holder, holder_url,
                                       matched_chars, "rejected")
                logger.info(
                    "fleet: pull %s <- %s rejected (holder at "
                    "max concurrency %d)", server_url, holder_url,
                    self.config.pull_max_concurrency)
                return {"holder": holder, "holder_url": holder_url,
                        "matched_chars": matched_chars,
                        "outcome": "rejected", "injected_blocks": 0,
                        "seconds": 0.0}
            task = asyncio.ensure_future(self._do_pull(
                server_url, holder_url, holder, matched_chars,
                request_json, request_id))
            self._single_flight[flight_key] = task
            task.add_done_callback(
                lambda _t: self._single_flight.pop(flight_key, None))
        else:
            self.pulls_coalesced += 1
        try:
            # Awaiting a shared Task is cancellation-safe: a cancelled
            # follower abandons its await without killing the transfer.
            result = await task
        except Exception as e:  # noqa: BLE001 - pull is best-effort
            logger.warning("fleet pull task failed: %s", e)
            return None
        if result is None:
            return None
        if coalesced:
            return {**result, "coalesced": True}
        return result

    async def _do_pull(self, server_url: str, holder_url: str, holder: str,
                       matched_chars: int, request_json: dict,
                       request_id: str) -> dict:
        """One actual /kv/pull round-trip (single-flight leader)."""
        from production_stack_tpu.router import metrics as router_metrics

        holder_key = holder_url.rstrip("/")
        self._inflight_by_holder[holder_key] = (
            self._inflight_by_holder.get(holder_key, 0) + 1)
        self.last_attempt_by_holder[holder_key] = time.monotonic()
        self.pulls_attempted += 1
        router_metrics.kv_pull_attempts.labels(server=server_url).inc()
        if holder == L3_INSTANCE:
            self.l3_pulls += 1
            router_metrics.fleet_l3_pulls.inc()
        t0 = time.monotonic()
        outcome = "ok"
        injected = 0
        pulled_bytes = 0
        tokens_saved = 0
        try:
            import aiohttp

            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"{server_url.rstrip('/')}/kv/pull",
                    json={"source_url": holder_url,
                          "request": request_json},
                    headers=self._headers(request_id),
                    timeout=aiohttp.ClientTimeout(
                        total=self.config.pull_timeout_s),
                ) as resp:
                    if resp.status == 503:
                        # The target's pull-admission semaphore is full
                        # (engine-side --kv-pull-max-concurrency): it
                        # told us to back off, and prefill recomputes.
                        outcome = "rejected"
                    elif resp.status != 200:
                        outcome = f"http_{resp.status}"
                    else:
                        body = await resp.json()
                        status = body.get("status")
                        injected = int(body.get("injected_blocks", 0) or 0)
                        tokens_saved = int(body.get("num_tokens", 0) or 0)
                        pulled_bytes = int(
                            (body.get("transfer") or {}).get("bytes", 0)
                            or 0)
                        if status == "ok" and injected > 0:
                            outcome = "ok"
                        elif status == "l3":
                            # The target found the prefix in its remote
                            # tier; prefill restores it without transfer.
                            outcome = "ok"
                            injected = int(body.get("l3_blocks", 0) or 0)
                        else:
                            outcome = "miss"
        except asyncio.TimeoutError:
            outcome = "timeout"
        except Exception as e:  # noqa: BLE001 - any transport failure
            logger.warning("fleet pull %s <- %s failed: %s",
                           server_url, holder_url, e)
            outcome = "unreachable"
        finally:
            left = self._inflight_by_holder.get(holder_key, 1) - 1
            if left <= 0:
                self._inflight_by_holder.pop(holder_key, None)
            else:
                self._inflight_by_holder[holder_key] = left
        elapsed = time.monotonic() - t0
        router_metrics.kv_pull_latency.labels(server=server_url).observe(
            elapsed)
        if outcome == "ok":
            self.pulls_succeeded += 1
            router_metrics.kv_pull_success.labels(server=server_url).inc()
            # Volume counters: what the pull actually moved / saved.
            if pulled_bytes > 0:
                router_metrics.kv_pull_bytes.labels(
                    server=server_url).inc(pulled_bytes)
            if tokens_saved > 0:
                router_metrics.kv_pull_tokens_saved.labels(
                    server=server_url).inc(tokens_saved)
        elif outcome == "rejected":
            self.pulls_rejected += 1
            router_metrics.kv_pull_rejected.labels(server=server_url).inc()
        else:
            self.pulls_failed += 1
            router_metrics.kv_pull_failures.labels(
                server=server_url, reason=outcome).inc()
        self._record_economics(
            server_url, holder, holder_url, matched_chars, outcome,
            bytes_moved=pulled_bytes, tokens_saved=tokens_saved,
            pull_seconds=elapsed)
        logger.info(
            "fleet pull %s <- %s (%s): %s, %d blocks, %.1f ms",
            server_url, holder_url,
            "l3" if holder == L3_INSTANCE else holder,
            outcome, injected, elapsed * 1e3)
        return {"holder": holder, "holder_url": holder_url,
                "matched_chars": matched_chars, "outcome": outcome,
                "injected_blocks": injected, "seconds": elapsed}

    def health(self) -> dict:
        return {
            "pulls_attempted": self.pulls_attempted,
            "pulls_succeeded": self.pulls_succeeded,
            "pulls_failed": self.pulls_failed,
            "pulls_rejected": self.pulls_rejected,
            "pulls_coalesced": self.pulls_coalesced,
            "l3_pulls": self.l3_pulls,
            "min_match_chars": self.config.min_match_chars,
            "pull_max_concurrency": self.config.pull_max_concurrency,
            "l3_url": self.config.l3_url,
            "economics": self.ledger.summary(),
            "auto_min_match": {
                "enabled": self.config.auto_min_match,
                "applied": self.auto_min_match_applied,
                "last": self.auto_min_match_last,
            },
        }


@dataclass
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # Desired replicas ≈ total backlog / target backlog per replica.
    queue_depth_target: float = 4.0
    # Scale out one extra replica when mean HBM KV occupancy crosses this.
    hbm_usage_high: float = 0.9
    drain_timeout_s: float = 120.0


class AutoscaleRecommender:
    """Load-predictive replica-count recommendation.

    Passive: every call to :meth:`recommend` folds the freshest signal
    snapshot; the KEDA/HPA manifests under deploy/autoscaling/ (or the
    helm-rendered equivalents) act on the exported gauges, and
    :meth:`scale_in` implements the graceful half of the loop.
    """

    def __init__(self, config: AutoscaleConfig,
                 kv_controller: Optional[KVController] = None,
                 api_key: Optional[str] = None):
        self.config = config
        self.kv_controller = kv_controller
        self.api_key = api_key
        self.last: dict = {}

    def recommend(self, endpoints, engine_stats: Dict,
                  qos=None) -> dict:
        from production_stack_tpu.router import metrics as router_metrics

        current = len(endpoints)
        waiting = running = 0
        usages: List[float] = []
        for stats in (engine_stats or {}).values():
            waiting += stats.num_queuing_requests
            running += stats.num_running_requests
            usages.append(stats.gpu_cache_usage_perc)
        headrooms = [
            stats.hbm_headroom_bytes
            for stats in (engine_stats or {}).values()
            if getattr(stats, "hbm_headroom_bytes", -1.0) >= 0
        ]
        qos_backlog = 0
        if qos is not None:
            try:
                qos_backlog = int(qos.queue.queued())
            except Exception:  # noqa: BLE001 - QoS health is advisory
                qos_backlog = 0
        backlog = waiting + qos_backlog
        desired = math.ceil(backlog / max(self.config.queue_depth_target,
                                          1e-9))
        desired = max(desired, 1 if (running or backlog) else 0)
        mean_usage = sum(usages) / len(usages) if usages else 0.0
        if usages and mean_usage >= self.config.hbm_usage_high:
            # KV pressure scales out even when queues look shallow: an
            # HBM-full fleet preempts before it queues.
            desired = max(desired, current + 1)
        desired = min(max(desired, self.config.min_replicas),
                      self.config.max_replicas)
        self.last = {
            "recommended_replicas": desired,
            "current_replicas": current,
            "signals": {
                "queue_depth": waiting,
                "running": running,
                "qos_backlog": qos_backlog,
                "mean_hbm_kv_usage": round(mean_usage, 4),
                "min_hbm_headroom_bytes": (
                    min(headrooms) if headrooms else None),
            },
        }
        router_metrics.autoscale_recommended_replicas.set(desired)
        router_metrics.autoscale_current_replicas.set(current)
        return self.last

    def pick_scale_in_victim(self, endpoints, engine_stats: Dict,
                             request_stats: Dict) -> Optional[str]:
        """Least-loaded replica: fewest queued+running requests.

        A replica with no scraped engine stats is UNKNOWN, not idle — a
        just-started replica must not beat an established idle one. The
        router's own request accounting stands in when the scrape is
        missing; a replica unknown to both sides sorts last and is only
        picked when every replica is unknown."""
        if not endpoints:
            return None

        def load(url: str) -> float:
            stats = (engine_stats or {}).get(url)
            if stats is not None:
                return stats.num_queuing_requests + stats.num_running_requests
            rstats = (request_stats or {}).get(url)
            if rstats is not None:
                return rstats.in_prefill_requests + rstats.in_decoding_requests
            return float("inf")

        return min((ep.url for ep in endpoints), key=load)

    async def scale_in(self, url: str) -> dict:
        """Gracefully retire ``url``: evict it from the KV controller
        (so no routing decision or pull targets it mid-drain), then
        drive the engine's ``/drain`` hook and report the outcome. The
        actual pod deletion is the orchestrator's job (HPA/KEDA +
        preStop); this is the data-plane half."""
        evicted: List[str] = []
        if self.kv_controller is not None:
            evicted = await self.kv_controller.deregister_url(url)
        drain_status: Optional[int] = None
        drain_body: dict = {}
        try:
            import aiohttp

            headers = {}
            if self.api_key:
                headers["Authorization"] = f"Bearer {self.api_key}"
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"{url.rstrip('/')}/drain",
                    params={"timeout_s": str(self.config.drain_timeout_s)},
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=self.config.drain_timeout_s + 10.0),
                ) as resp:
                    drain_status = resp.status
                    try:
                        drain_body = await resp.json()
                    except Exception:  # noqa: BLE001 - non-JSON drain reply
                        drain_body = {}
        except Exception as e:  # noqa: BLE001 - engine may already be gone
            logger.warning("scale-in drain of %s failed: %s", url, e)
            drain_body = {"error": str(e)}
        return {"url": url, "deregistered_instances": evicted,
                "drain_status": drain_status, "drain": drain_body}


def initialize_fleet(args, kv_controller, fault_tolerance=None):
    """Build (FleetCache | None, AutoscaleRecommender | None) from parsed
    router args — both None unless their flags are set, preserving the
    flag-off request path byte for byte."""
    from production_stack_tpu.utils import auth

    keys = auth.resolve_api_keys(getattr(args, "api_key", None))
    key = keys[0] if keys else None
    fleet = None
    if getattr(args, "fleet_cache", False):
        fleet = FleetCache(
            FleetCacheConfig(
                pull_timeout_s=args.fleet_pull_timeout,
                min_match_chars=args.fleet_min_match_chars,
                l3_url=args.fleet_l3_url,
                api_key=key,
                pull_max_concurrency=getattr(
                    args, "kv_pull_max_concurrency", 8),
                prefill_tokens_per_s_floor=getattr(
                    args, "fleet_prefill_tokens_per_s",
                    DEFAULT_PREFILL_TPS_FLOOR),
                chars_per_token=getattr(
                    args, "fleet_chars_per_token", DEFAULT_CHARS_PER_TOKEN),
                auto_min_match=getattr(
                    args, "fleet_auto_min_match", False),
                auto_min_match_interval_s=getattr(
                    args, "fleet_auto_min_match_interval", 30.0),
                auto_min_match_damping=getattr(
                    args, "fleet_auto_min_match_damping", 0.3),
            ),
            kv_controller,
            fault_tolerance=fault_tolerance,
        )
    autoscaler = None
    if getattr(args, "autoscale", False):
        autoscaler = AutoscaleRecommender(
            AutoscaleConfig(
                min_replicas=args.autoscale_min_replicas,
                max_replicas=args.autoscale_max_replicas,
                queue_depth_target=args.autoscale_queue_depth_target,
                hbm_usage_high=args.autoscale_hbm_usage_high,
                drain_timeout_s=args.autoscale_drain_timeout,
            ),
            kv_controller=kv_controller,
            api_key=key,
        )
    return fleet, autoscaler
