"""KV offload tiers: TPU HBM -> host RAM -> remote cache server.

The reference stack buys this from LMCache: engine KV blocks spill to a CPU
buffer (``values-05-cpu-offloading.yaml``, 60 GB buffers in
``values-17-kv-aware.yaml:20-25``) and optionally to a remote
``lmcache_experimental_server`` (CacheServer CRD,
``operator/internal/controller/cacheserver_controller.go:135-206``). Here it
is native: the engine's block allocator calls ``on_evict`` just before
recycling a cached page, the pages land in this store keyed by their prefix
chain hash, and ``allocate_prompt`` consults :meth:`contains` so evicted
prefixes re-enter HBM with a device_put instead of a recompute.

Serialization is a single .npz payload per block (k and v pages for every
layer), the same wire format the cache server and the disaggregated-prefill
transfer use.
"""

from __future__ import annotations

import io
import threading
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def _dtype_name(arr: np.ndarray) -> str:
    return str(arr.dtype)


def _resolve_dtype(name: str) -> np.dtype:
    # np.savez cannot represent ml_dtypes (bfloat16 degrades to void), so
    # the wire format ships raw bytes + a dtype name resolved here.
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _pack_arrays(**arrays: np.ndarray) -> bytes:
    buf = io.BytesIO()
    fields = {}
    for key, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        fields[key] = np.frombuffer(arr.tobytes(), np.uint8)
        fields[f"{key}_shape"] = np.asarray(arr.shape, np.int64)
        fields[f"{key}_dtype"] = np.frombuffer(
            _dtype_name(arr).encode(), np.uint8
        )
    np.savez(buf, **fields)
    return buf.getvalue()


def _unpack_arrays(data: bytes, keys) -> dict:
    out = {}
    with np.load(io.BytesIO(data)) as z:
        for key in keys:
            shape = tuple(z[f"{key}_shape"])
            dtype = _resolve_dtype(bytes(z[f"{key}_dtype"]).decode())
            out[key] = np.frombuffer(
                z[key].tobytes(), dtype
            ).reshape(shape)
    return out


def pack_block(k, v) -> bytes:
    """Serialize one block's pages ([L, bs, KVH, D] each) to bytes.

    Int8 KV-cache blocks arrive as ``(data, scales)`` tuples (scales
    [L, bs*KVH] f32); they ship under dedicated ``k_scale``/``v_scale``
    keys — for a 128-dim head the payload is ~0.52x the bf16 block, which
    is the point of quantized offload (every spilled byte moves over host
    RAM or the cache-server socket)."""
    if isinstance(k, (tuple, list)):
        return _pack_arrays(k=k[0], k_scale=k[1], v=v[0], v_scale=v[1])
    return _pack_arrays(k=k, v=v)


def unpack_block(data: bytes):
    """Inverse of :func:`pack_block`: returns (k, v) bare arrays for bf16
    payloads, ((k, k_scale), (v, v_scale)) tuples for int8 ones (detected
    from the key set — both directions of a mixed-fleet rollout parse)."""
    with np.load(io.BytesIO(data)) as z:
        quantized = "k_scale_shape" in z.files
    if quantized:
        out = _unpack_arrays(data, ("k", "k_scale", "v", "v_scale"))
        return ((out["k"], out["k_scale"]), (out["v"], out["v_scale"]))
    out = _unpack_arrays(data, ("k", "v"))
    return out["k"], out["v"]


# Disaggregated-prefill transfer wire format v2: a small JSON header plus
# the RAW array bytes — no zip container, no CRC, no intermediate copies
# (the npz path cost ~3 full copies + a CRC pass per side at multi-GB KV
# sizes). The sender can write the returned buffers straight to the socket;
# the receiver reinterprets the body in place via np.frombuffer offsets.
_TRANSFER_MAGIC = b"TKV2"


def _raw_view(arr: np.ndarray) -> memoryview:
    return memoryview(np.ascontiguousarray(arr).view(np.uint8).reshape(-1))


def pack_transfer_buffers(
    hashes, num_tokens: int, k, v
) -> "list":
    """Zero-copy packing: returns [header_bytes, *array_views] suitable
    for writing sequentially to a socket/stream. Int8 KV payloads arrive
    as ``(data, scales)`` tuples; their views follow the header in the
    FIXED order k, k_scale, v, v_scale (the header's key order), so a
    receiver can walk the body with frombuffer offsets either way."""
    import json as _json
    import struct

    fields = {}
    if isinstance(k, (tuple, list)):
        fields["k"], fields["k_scale"] = k[0], k[1]
        fields["v"], fields["v_scale"] = v[0], v[1]
    else:
        fields["k"], fields["v"] = k, v
    header = _json.dumps({
        "hashes": [int(h) for h in hashes],
        "num_tokens": int(num_tokens),
        **{key: {"dtype": _dtype_name(arr), "shape": list(arr.shape)}
           for key, arr in fields.items()},
    }).encode()
    head = _TRANSFER_MAGIC + struct.pack("<I", len(header)) + header
    return [head] + [_raw_view(arr) for arr in fields.values()]


def pack_transfer(hashes, num_tokens: int, k, v) -> bytes:
    """One-shot packing for callers that need a single bytes payload."""
    return b"".join(bytes(b) for b in pack_transfer_buffers(
        hashes, num_tokens, k, v))


def unpack_transfer(data: bytes) -> dict:
    """Inverse of pack_transfer. Array data is reinterpreted in place
    (frombuffer at offsets — no slicing copies). Legacy .npz payloads
    (round-1 engines) still unpack; int8 payloads come back out as
    (data, scales) tuples under "k"/"v"."""
    if data[:4] == _TRANSFER_MAGIC:
        import json as _json
        import struct

        (hlen,) = struct.unpack_from("<I", data, 4)
        header = _json.loads(data[8 : 8 + hlen].decode())
        offset = 8 + hlen
        quantized = "k_scale" in header
        keys = (("k", "k_scale", "v", "v_scale") if quantized
                else ("k", "v"))
        out = {}
        for key in keys:
            dtype = _resolve_dtype(header[key]["dtype"])
            shape = tuple(header[key]["shape"])
            count = int(np.prod(shape)) if shape else 1
            out[key] = np.frombuffer(
                data, dtype=dtype, count=count, offset=offset
            ).reshape(shape)
            offset += count * dtype.itemsize
        if quantized:
            return {
                "hashes": [int(h) for h in header["hashes"]],
                "num_tokens": int(header["num_tokens"]),
                "k": (out["k"], out["k_scale"]),
                "v": (out["v"], out["v_scale"]),
            }
        return {
            "hashes": [int(h) for h in header["hashes"]],
            "num_tokens": int(header["num_tokens"]),
            "k": out["k"],
            "v": out["v"],
        }
    legacy = _unpack_arrays(data, ("hashes", "num_tokens", "k", "v"))
    return {
        "hashes": [int(h) for h in legacy["hashes"]],
        "num_tokens": int(legacy["num_tokens"][0]),
        "k": legacy["k"],
        "v": legacy["v"],
    }


class RemoteKVClient:
    """Blocking HTTP client for the standalone cache server
    (:mod:`production_stack_tpu.kv.cache_server`). Used from the engine
    thread; failures degrade to recompute, never to request failure."""

    def __init__(self, base_url: str, timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def put(self, prefix_hash: int, data: bytes) -> bool:
        req = urllib.request.Request(
            f"{self.base_url}/v1/blocks/{prefix_hash}", data=data,
            method="PUT",
            headers={"Content-Type": "application/octet-stream"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                return True
        except (urllib.error.URLError, OSError) as e:
            logger.debug("remote KV put failed: %s", e)
            return False

    def get(self, prefix_hash: int) -> Optional[bytes]:
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/v1/blocks/{prefix_hash}",
                timeout=self.timeout,
            ) as resp:
                return resp.read()
        except (urllib.error.URLError, OSError):
            return None

    def contains(self, prefix_hash: int) -> bool:
        # Existence probes run on the engine thread during prompt
        # allocation — keep the worst case short.
        req = urllib.request.Request(
            f"{self.base_url}/v1/blocks/{prefix_hash}", method="HEAD"
        )
        try:
            with urllib.request.urlopen(req, timeout=min(1.0, self.timeout)):
                return True
        except (urllib.error.URLError, OSError):
            return False


class HostKVStore:
    """LRU byte-capped host-RAM block store with an optional remote tier.

    Thread-safe: written from the engine thread (eviction hook) and read
    from server threads (extract)."""

    def __init__(self, capacity_bytes: int, remote_url: Optional[str] = None):
        self.capacity_bytes = capacity_bytes
        self._store: "OrderedDict[int, Tuple[np.ndarray, np.ndarray]]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.remote = RemoteKVClient(remote_url) if remote_url else None
        self.hits = 0
        self.misses = 0
        self.stored = 0
        self.evicted = 0
        # Remote (L3) tier traffic, for tpu:l3_* metrics: blocks/bytes
        # spilled up to the cache server and fetched back from it.
        self.remote_put_blocks = 0
        self.remote_put_bytes = 0
        self.remote_get_blocks = 0
        self.remote_get_bytes = 0
        # Remote uploads happen on a background writer so a slow/unreachable
        # cache server never stalls the engine thread (put is called from
        # the allocator's eviction hook, under engine locks). Bounded queue:
        # under pressure we drop uploads (cache, not correctness).
        self._remote_queue: "list[Tuple[int, bytes]]" = []
        self._remote_inflight = 0
        self._remote_cv = threading.Condition()
        self._writer: Optional[threading.Thread] = None
        if self.remote is not None:
            self._writer = threading.Thread(
                target=self._remote_writer, daemon=True, name="kv-offload-tx"
            )
            self._writer.start()

    _REMOTE_QUEUE_MAX = 256

    def _enqueue_remote(self, prefix_hash: int, data: bytes) -> None:
        with self._remote_cv:
            if len(self._remote_queue) >= self._REMOTE_QUEUE_MAX:
                self._remote_queue.pop(0)  # drop oldest upload
            self._remote_queue.append((prefix_hash, data))
            self._remote_cv.notify()

    def _remote_writer(self) -> None:
        while True:
            with self._remote_cv:
                while not self._remote_queue:
                    self._remote_cv.wait()
                prefix_hash, data = self._remote_queue.pop(0)
                self._remote_inflight += 1
            try:
                if self.remote.put(prefix_hash, data):
                    with self._lock:
                        self.remote_put_blocks += 1
                        self.remote_put_bytes += len(data)
            finally:
                with self._remote_cv:
                    self._remote_inflight -= 1
                    self._remote_cv.notify_all()

    def flush_remote(self, timeout: float = 10.0) -> None:
        """Wait for queued AND in-flight remote uploads to drain
        (tests/shutdown): the writer pops before it PUTs, so an empty
        queue alone does not mean the last upload landed."""
        import time as _time

        deadline = _time.time() + timeout
        while _time.time() < deadline:
            with self._remote_cv:
                if not self._remote_queue and not self._remote_inflight:
                    return
            _time.sleep(0.02)

    @staticmethod
    def _size(k, v) -> int:
        # Multi-host engines stage per-process SHARD DICTS
        # ({shard_index: ndarray}) instead of whole-block arrays; sizes
        # stay equal across processes (equal mesh splits), which keeps
        # the per-process LRU states in lockstep.
        def nbytes(x):
            if isinstance(x, dict):
                return sum(nbytes(a) for a in x.values())
            if isinstance(x, (tuple, list)):
                # int8 KV leaves: (data, scales) — possibly of shard
                # dicts in multi-host staging.
                return sum(nbytes(e) for e in x)
            return x.nbytes

        return nbytes(k) + nbytes(v)

    def put(self, prefix_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        size = self._size(k, v)
        spill: "list[Tuple[int, np.ndarray, np.ndarray]]" = []
        with self._lock:
            if prefix_hash in self._store:
                return
            # Evict LRU entries to fit; spill them to the remote tier.
            while self._bytes + size > self.capacity_bytes and self._store:
                old_hash, (ok, ov) = self._store.popitem(last=False)
                self._bytes -= self._size(ok, ov)
                self.evicted += 1
                spill.append((old_hash, ok, ov))
            if self._bytes + size <= self.capacity_bytes:
                self._store[prefix_hash] = (k, v)
                self._bytes += size
                self.stored += 1
            elif self.remote is not None:
                # Doesn't fit locally (remote-only config, or block larger
                # than the host budget): ship it straight to the remote tier.
                spill.append((prefix_hash, k, v))
                self.stored += 1
        if self.remote is not None:
            for h, sk, sv in spill:
                self._enqueue_remote(h, pack_block(sk, sv))

    def get(self, prefix_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            entry = self._store.get(prefix_hash)
            if entry is not None:
                self._store.move_to_end(prefix_hash)
                self.hits += 1
                return entry
        if self.remote is not None:
            data = self.remote.get(prefix_hash)
            if data is not None:
                try:
                    k, v = unpack_block(data)
                except Exception as e:  # noqa: BLE001 - corrupt remote block
                    logger.warning("corrupt remote KV block %d: %s",
                                   prefix_hash, e)
                else:
                    with self._lock:
                        self.hits += 1
                        self.remote_get_blocks += 1
                        self.remote_get_bytes += len(data)
                    return k, v
        with self._lock:
            self.misses += 1
        return None

    def contains(self, prefix_hash: int) -> bool:
        with self._lock:
            if prefix_hash in self._store:
                return True
        return self.remote is not None and self.remote.contains(prefix_hash)

    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": len(self._store),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "stored": self.stored,
                "evicted": self.evicted,
                "remote": self.remote is not None,
                "remote_put_blocks": self.remote_put_blocks,
                "remote_put_bytes": self.remote_put_bytes,
                "remote_get_blocks": self.remote_get_blocks,
                "remote_get_bytes": self.remote_get_bytes,
            }
