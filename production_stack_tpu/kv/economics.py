"""Measured fleet-pull economics: the ledger behind the crossover advisor.

The fleet tier (``kv/fleet.py``) can move a prefix's KV blocks across
replicas instead of recomputing them — but a pull is only worth its
round-trip when the transfer is faster than the prefill it replaces.
This module answers that question from *measurement*, not configuration:

- :class:`PullLedger` lands one record per orchestrated pull in a
  bounded ring: bytes moved, tokens saved, pull wall time, the holder it
  came from, and an estimated recompute cost derived from prefill
  throughput (a live measured source where one is wired, else the
  configured tokens/s floor). Each record is classified **win** or
  **loss** by net latency (``est_recompute_s - pull_s``); failed and
  holder-rejected pulls are always losses with zero tokens saved.
- :meth:`PullLedger.advise` fits the measured transfer model
  (``pull_s ≈ overhead + bytes / bandwidth``) over *successful* pulls
  only — failures must not skew the bandwidth estimate — and computes
  the break-even match length: the shortest prefix for which pulling
  beats recomputing. Served on ``GET /debug/kv/economics`` as a
  recommended ``--fleet-min-match-chars``, and applied on a damped
  interval when ``--fleet-auto-min-match`` is set.

Stdlib-only, like ``obs/``: the ledger itself exports nothing — the
fleet cache increments ``vllm_router:kv_pull_{wins,losses}_total`` and
``vllm_router:kv_pull_net_seconds_saved_total`` from the classification
this module returns, so flag-off deployments emit no series.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable, Deque, List, Optional, Tuple

DEFAULT_CAPACITY = 512
# chars per token for the break-even conversion: the controller trie is
# character-chunked, so the advisor's output unit is chars. ~4 chars per
# (BPE) token is the usual English-text rule of thumb.
DEFAULT_CHARS_PER_TOKEN = 4.0
# Conservative recompute floor when no measured prefill throughput is
# wired: well below a real TPU prefill rate, so the advisor errs toward
# "recompute is cheap" (longer recommended min-match) rather than
# overselling pulls.
DEFAULT_PREFILL_TPS_FLOOR = 2000.0


def step_recorder_prefill_tps(recorder) -> Optional[float]:
    """Measured prefill tokens/s from a StepRecorder's per-kind rollups
    (``obs/steps.py``): tokens over wall seconds across the prefill and
    prefill_chunk kinds. None when the recorder has no prefill samples —
    the caller falls back to its configured floor."""
    try:
        stats = recorder.kind_stats()
    except Exception:  # noqa: BLE001 - recorder is optional telemetry
        return None
    tokens = 0.0
    wall = 0.0
    for kind in ("prefill", "prefill_chunk"):
        s = stats.get(kind) or {}
        tokens += float(s.get("tokens", 0) or 0)
        wall += float(s.get("wall_s", 0.0) or 0.0)
    if tokens <= 0 or wall <= 0:
        return None
    return tokens / wall


class PullLedger:
    """Bounded ring of fleet-pull outcomes plus the economics derived
    from it. Single event loop, no locking (same contract as ``obs/``).

    ``prefill_tps_fn``: optional zero-arg callable returning a measured
    prefill tokens/s (or None). When it yields a positive value the
    recompute estimate uses it (source ``measured``); otherwise the
    configured ``prefill_tokens_per_s_floor`` applies (source ``floor``).
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 prefill_tokens_per_s_floor: float = DEFAULT_PREFILL_TPS_FLOOR,
                 prefill_tps_fn: Optional[Callable[[], Optional[float]]] = None,
                 chars_per_token: float = DEFAULT_CHARS_PER_TOKEN):
        self.capacity = int(capacity)
        self.prefill_tokens_per_s_floor = float(prefill_tokens_per_s_floor)
        self.prefill_tps_fn = prefill_tps_fn
        self.chars_per_token = float(chars_per_token)
        self._records: Deque[dict] = deque(maxlen=self.capacity)
        # Transfer-model samples: (bytes, seconds) of SUCCESSFUL pulls
        # that actually moved bytes. Failure paths never land here.
        self._bw_samples: Deque[Tuple[float, float]] = deque(
            maxlen=self.capacity)
        # bytes-per-token ratio accumulators (successful pulls only).
        self._bpt_bytes = 0.0
        self._bpt_tokens = 0.0
        self.recorded_total = 0
        self.wins = 0
        self.losses = 0
        self.net_seconds_saved_total = 0.0
        self.bytes_moved_total = 0
        self.tokens_saved_total = 0
        self.pull_seconds_total = 0.0

    # -- recompute model ---------------------------------------------------
    def prefill_tokens_per_s(self) -> Tuple[float, str]:
        """(tokens/s, source) — measured when the wired source has data,
        else the configured floor."""
        if self.prefill_tps_fn is not None:
            try:
                measured = self.prefill_tps_fn()
            except Exception:  # noqa: BLE001 - source is best-effort
                measured = None
            if measured is not None and measured > 0:
                return float(measured), "measured"
        return self.prefill_tokens_per_s_floor, "floor"

    # -- recording ---------------------------------------------------------
    def record(self, *, server_url: str, holder: str, holder_url: str,
               matched_chars: int, outcome: str, bytes_moved: int = 0,
               tokens_saved: int = 0, pull_seconds: float = 0.0) -> dict:
        """Land one pull outcome; returns the classified record.

        Any outcome other than ``ok`` is a loss with zero tokens saved by
        definition — a failed transfer saved nothing and cost its wall
        time — and contributes nothing to the transfer model.
        """
        ok = outcome == "ok"
        if not ok:
            tokens_saved = 0
            bytes_moved = 0
        tps, tps_source = self.prefill_tokens_per_s()
        est_recompute_s = tokens_saved / tps if ok and tokens_saved > 0 \
            else 0.0
        net = est_recompute_s - pull_seconds
        win = ok and net > 0
        rec = {
            "t": time.time(),
            "server_url": server_url,
            "holder": holder,
            "holder_url": holder_url,
            "matched_chars": matched_chars,
            "outcome": outcome,
            "bytes_moved": int(bytes_moved),
            "tokens_saved": int(tokens_saved),
            "pull_seconds": round(float(pull_seconds), 6),
            "est_recompute_seconds": round(est_recompute_s, 6),
            "net_seconds_saved": round(net, 6),
            "classification": "win" if win else "loss",
            "prefill_tokens_per_s": round(tps, 3),
            "prefill_tps_source": tps_source,
        }
        self._records.append(rec)
        self.recorded_total += 1
        if win:
            self.wins += 1
        else:
            self.losses += 1
        self.net_seconds_saved_total += net
        self.bytes_moved_total += int(bytes_moved)
        self.tokens_saved_total += int(tokens_saved)
        self.pull_seconds_total += float(pull_seconds)
        if ok and bytes_moved > 0 and pull_seconds > 0:
            self._bw_samples.append((float(bytes_moved),
                                     float(pull_seconds)))
            if tokens_saved > 0:
                self._bpt_bytes += float(bytes_moved)
                self._bpt_tokens += float(tokens_saved)
        return rec

    # -- transfer model ----------------------------------------------------
    def _fit(self) -> Tuple[float, float]:
        """(overhead_s, per_byte_s): least-squares line through the
        successful-pull samples (``seconds = overhead + bytes*per_byte``).
        Falls back to a zero-overhead aggregate ratio when the samples
        don't span distinct transfer sizes (a one-point line has no
        intercept)."""
        xs = [b for b, _ in self._bw_samples]
        ys = [s for _, s in self._bw_samples]
        n = len(xs)
        total_bytes = sum(xs)
        total_secs = sum(ys)
        ratio = total_secs / total_bytes if total_bytes > 0 else 0.0
        if n < 2:
            return 0.0, ratio
        mean_x = total_bytes / n
        mean_y = total_secs / n
        var = sum((x - mean_x) ** 2 for x in xs)
        if var <= 0:
            return 0.0, ratio
        cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, ys))
        slope = cov / var
        intercept = mean_y - slope * mean_x
        if slope <= 0:
            # Noise swamped the size signal; keep the aggregate ratio and
            # charge no fixed overhead rather than extrapolate nonsense.
            return 0.0, ratio
        return max(intercept, 0.0), slope

    def pull_bandwidth_bytes_per_s(self) -> Optional[float]:
        """Aggregate measured transfer bandwidth (successful pulls)."""
        total_bytes = sum(b for b, _ in self._bw_samples)
        total_secs = sum(s for _, s in self._bw_samples)
        if total_bytes <= 0 or total_secs <= 0:
            return None
        return total_bytes / total_secs

    def bytes_per_token(self) -> Optional[float]:
        return (self._bpt_bytes / self._bpt_tokens
                if self._bpt_tokens > 0 else None)

    # -- the crossover advisor --------------------------------------------
    def advise(self, current_min_match_chars: Optional[int] = None) -> dict:
        """Break-even match length from the measured transfer model.

        Pulling a prefix of T tokens costs ``overhead + T*bpt*per_byte``;
        recomputing it costs ``T / prefill_tps``. Pulling wins beyond
        ``T* = overhead / (1/tps - bpt*per_byte)`` — provided the
        per-token transfer is cheaper than the per-token recompute at
        all; otherwise pulling never wins and no threshold helps.
        """
        tps, tps_source = self.prefill_tokens_per_s()
        out = {
            "prefill_tokens_per_s": round(tps, 3),
            "prefill_tps_source": tps_source,
            "chars_per_token": self.chars_per_token,
            "current_min_match_chars": current_min_match_chars,
            "samples": len(self._bw_samples),
            "bandwidth_bytes_per_s": None,
            "overhead_seconds": None,
            "bytes_per_token": None,
            "breakeven_tokens": None,
            "recommended_min_match_chars": None,
            "pull_never_wins": False,
            "reason": None,
        }
        bpt = self.bytes_per_token()
        if not self._bw_samples or bpt is None:
            out["reason"] = "no successful pulls measured yet"
            return out
        overhead, per_byte = self._fit()
        out["overhead_seconds"] = round(overhead, 6)
        out["bandwidth_bytes_per_s"] = (
            round(1.0 / per_byte, 3) if per_byte > 0
            else self.pull_bandwidth_bytes_per_s())
        out["bytes_per_token"] = round(bpt, 3)
        recompute_s_per_token = 1.0 / tps
        pull_s_per_token = bpt * per_byte
        if recompute_s_per_token <= pull_s_per_token:
            out["pull_never_wins"] = True
            out["reason"] = ("measured per-token transfer cost exceeds "
                             "per-token recompute; no match length "
                             "amortizes it")
            return out
        breakeven = overhead / (recompute_s_per_token - pull_s_per_token)
        out["breakeven_tokens"] = round(breakeven, 3)
        out["recommended_min_match_chars"] = max(
            1, int(math.ceil(breakeven * self.chars_per_token)))
        return out

    # -- debug surface -----------------------------------------------------
    def summary(self) -> dict:
        return {
            "capacity": self.capacity,
            "recorded_total": self.recorded_total,
            "wins": self.wins,
            "losses": self.losses,
            "net_seconds_saved_total": round(
                self.net_seconds_saved_total, 6),
            "bytes_moved_total": self.bytes_moved_total,
            "tokens_saved_total": self.tokens_saved_total,
            "pull_seconds_total": round(self.pull_seconds_total, 6),
            "pull_bandwidth_bytes_per_s": self.pull_bandwidth_bytes_per_s(),
            "bytes_per_token": self.bytes_per_token(),
        }

    def snapshot(self, limit: int = 100) -> List[dict]:
        """Newest-first records (same ordering contract as the other
        ``/debug`` rings)."""
        out = list(self._records)
        out.reverse()
        return out[:max(int(limit), 0)]

    def fed_snapshot(self, limit: int = 100) -> dict:
        """Worker-local state for the federation plane: the cumulative
        summary (wins/losses/seconds-saved sum across workers — each
        worker only ledgers the pulls its own process brokered) plus
        newest-first ring records for ``federation.merge_rings`` (time
        key ``t``)."""
        return {
            "summary": self.summary(),
            "records": self.snapshot(limit=limit),
        }
