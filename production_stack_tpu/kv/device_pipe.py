"""Device-to-device KV pipe over ``jax.experimental.transfer`` (DCN).

The reference moves disaggregated-prefill KV device-to-device through a
NIXL/UCX side channel wired into its engine pods
(``helm/templates/deployment-vllm-multi.yaml:267-305``,
``examples/disaggregated_prefill/pd.yaml``). This is the TPU-native
equivalent: each engine process runs a ``TransferServer`` bound to its
PJRT client, the prefill side parks the gathered KV pages as *device*
arrays awaiting pull, and the decode side pulls them straight into its own
device memory over the transfer runtime — no host staging, no HTTP body.

Availability: the transfer runtime needs
``PJRT_Client_CreateBuffersForAsyncHostToDevice`` from the backend plugin.
Standard TPU-VM libtpu has it; some dev runtimes (CPU emulation, tunneled
chips) do not — and a failed pull can fatally abort the *process* (a CHECK
in the bulk-transport layer), so availability is probed in a THROWAWAY
SUBPROCESS once and cached. When unavailable, callers fall back to the
zero-copy TKV2 HTTP relay (:mod:`production_stack_tpu.kv.offload`).
"""

from __future__ import annotations

import itertools
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# The probe runs the REAL topology — offerer and puller in separate
# processes (engines are separate processes; a same-process loopback
# pull succeeds on runtimes whose cross-process transport is broken, so
# probing loopback would steer engines onto a crashing path). Probing
# the parent's backend explicitly closes the round-4 bug where the
# subprocess picked the env-default backend (the tunneled TPU plugin)
# even under a CPU mesh.
_PROBE_OFFER = r"""
import sys, time
import jax
if sys.argv[1] == "cpu":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.experimental import transfer
srv = transfer.start_transfer_server(jax.devices()[0].client)
x = jnp.arange(2048, dtype=jnp.bfloat16).reshape(2, 32, 32)
srv.await_pull(1, [x])
with open(sys.argv[2], "w") as f:
    f.write(srv.address())
time.sleep(60)
"""

_PROBE_PULL = r"""
import sys
import jax
if sys.argv[1] == "cpu":
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.experimental import transfer
with open(sys.argv[2]) as f:
    addr = f.read().strip()
srv = transfer.start_transfer_server(jax.devices()[0].client)
conn = srv.connect(addr)
x = jnp.arange(2048, dtype=jnp.bfloat16).reshape(2, 32, 32)
spec = jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
out = conn.pull(1, [spec])
assert bool(jnp.all(out[0] == x))
print("DEVICE_PIPE_OK")
"""

_probe_result: Optional[bool] = None
_probe_lock = threading.Lock()


def device_pipe_available(timeout: float = 120.0) -> bool:
    """True when the transfer runtime round-trips on this backend.

    Probed in a subprocess (a failing pull can fatally abort the process,
    not just raise) and cached for the engine's lifetime. Overridable with
    ``TPU_STACK_KV_DEVICE_PIPE=0|1`` (1 skips the probe — trusted envs)."""
    global _probe_result
    override = os.environ.get("TPU_STACK_KV_DEVICE_PIPE")
    if override is not None:
        return override not in ("0", "false", "off")
    with _probe_lock:
        if _probe_result is None:
            offerer = None
            try:
                import tempfile

                import jax

                platform = jax.devices()[0].platform
                with tempfile.TemporaryDirectory() as d:
                    addr_file = os.path.join(d, "addr")
                    offerer = subprocess.Popen(
                        [sys.executable, "-c", _PROBE_OFFER, platform,
                         addr_file],
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                    deadline = time.monotonic() + timeout / 2
                    while (not os.path.exists(addr_file)
                           or not open(addr_file).read().strip()):
                        if (offerer.poll() is not None
                                or time.monotonic() > deadline):
                            raise RuntimeError("probe offerer died")
                        time.sleep(0.1)
                    proc = subprocess.run(
                        [sys.executable, "-c", _PROBE_PULL, platform,
                         addr_file],
                        capture_output=True, timeout=timeout,
                    )
                    _probe_result = b"DEVICE_PIPE_OK" in proc.stdout
            except Exception:  # noqa: BLE001 - treat as unavailable
                _probe_result = False
            finally:
                if offerer is not None and offerer.poll() is None:
                    offerer.kill()
            logger.info("KV device pipe %s",
                        "available" if _probe_result else
                        "unavailable (falling back to HTTP relay)")
        return _probe_result


class KVDevicePipe:
    """One per engine process: offers extracted KV pages for pull and
    pulls offered pages from peers, all as device arrays."""

    # Offers not pulled within this window are dropped from OUR table (the
    # decode side re-requests through the HTTP fallback on miss). NOTE:
    # expiry does NOT reclaim HBM — the experimental transfer API has no
    # await_pull cancel, so the server-side registration keeps the device
    # buffers alive until the peer pulls or the process exits. The
    # MAX_PENDING_OFFERS cap below bounds that pinned memory: offer()
    # refuses when full and the caller falls back to the HTTP relay.
    OFFER_TTL_SEC = 120.0

    # Upper bound on concurrently registered (offered, not yet released)
    # page bundles. At the default disagg shapes one bundle is tens of MB,
    # so 8 bounds pinned HBM to a few hundred MB worst case.
    MAX_PENDING_OFFERS = 8

    def __init__(self, listen: str = "0.0.0.0:0"):
        import jax
        from jax.experimental import transfer

        self._transfer = transfer
        self._server = transfer.start_transfer_server(
            jax.devices()[0].client, listen)
        self._uuid = itertools.count(int(time.time() * 1000) % (1 << 30))
        # uuid -> (arrays, deadline): keeps device buffers alive until
        # pulled or expired.
        self._pending: Dict[int, Tuple[Any, float]] = {}
        # uuids with a live await_pull registration. Unlike _pending this
        # never decays with the TTL (expiry cannot unregister buffers);
        # entries leave only via release() of that exact uuid, so
        # duplicate/bogus release calls cannot undercount pinned HBM.
        self._registered: set = set()
        self._conns: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def address(self) -> str:
        return self._server.address()

    def offer(self, arrays: List[Any]) -> Optional[int]:
        """Park device arrays for a peer to pull; returns the pull uuid,
        or None when MAX_PENDING_OFFERS registrations are already
        outstanding (un-released) — the caller must fall back to the HTTP
        relay rather than pin more HBM behind an uncancellable
        await_pull."""
        now = time.monotonic()
        with self._lock:
            self._pending = {
                u: (a, dl) for u, (a, dl) in self._pending.items()
                if dl > now
            }
            if len(self._registered) >= self.MAX_PENDING_OFFERS:
                logger.warning(
                    "KV device pipe: %d offers outstanding, refusing new "
                    "offer (HTTP relay fallback)", len(self._registered))
                return None
            uuid = next(self._uuid)
            self._registered.add(uuid)
            self._pending[uuid] = (arrays, now + self.OFFER_TTL_SEC)
        try:
            self._server.await_pull(uuid, arrays)
        except Exception:  # noqa: BLE001 - registration failed: no pin
            with self._lock:
                self._registered.discard(uuid)
                self._pending.pop(uuid, None)
            raise
        return uuid

    def release(self, uuid: int) -> None:
        """Mark an offer consumed (peer pulled it, or the handoff was
        abandoned and the puller told us). Frees a MAX_PENDING_OFFERS
        slot; the device buffers themselves are reclaimed by the transfer
        server once pulled."""
        with self._lock:
            self._pending.pop(uuid, None)
            self._registered.discard(uuid)

    def pull(self, address: str, uuid: int, specs: List[Any]) -> List[Any]:
        """Pull device arrays matching ``specs`` (ShapeDtypeStructs with
        shardings) from the peer transfer server at ``address``."""
        with self._lock:
            conn = self._conns.get(address)
            if conn is None:
                conn = self._server.connect(address)
                self._conns[address] = conn
        return conn.pull(uuid, specs)
