"""OpenAI-compatible protocol models shared by router and engine.

Mirrors the surface of the reference's ``src/vllm_router/protocols.py:11-57``
(ModelCard/ModelList/ErrorResponse with extra-field tolerance), extended with
the request/response models the TPU engine needs to implement the OpenAI API
natively (the reference outsources those to vLLM).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class OpenAIBaseModel(BaseModel):
    """Base model that tolerates (and logs once) extra fields.

    cf. reference src/vllm_router/protocols.py:11-33.
    """

    model_config = ConfigDict(extra="allow")

    def __init__(self, **data: Any):
        super().__init__(**data)
        declared = set(self.__class__.model_fields)
        extras = set(data) - declared
        if extras:
            logger.debug(
                "Extra fields on %s: %s", self.__class__.__name__, sorted(extras)
            )


class ModelCard(OpenAIBaseModel):
    id: str
    object: str = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "production-stack-tpu"
    root: Optional[str] = None
    parent: Optional[str] = None


class ModelList(OpenAIBaseModel):
    object: str = "list"
    data: List[ModelCard] = Field(default_factory=list)


class ErrorResponse(OpenAIBaseModel):
    object: str = "error"
    message: str
    type: str = "invalid_request_error"
    param: Optional[str] = None
    code: Optional[int] = None


# ---------------------------------------------------------------------------
# Engine-side request/response models (OpenAI API implemented natively).
# ---------------------------------------------------------------------------


class ChatMessage(OpenAIBaseModel):
    role: str
    content: Union[str, List[Dict[str, Any]], None] = None
    name: Optional[str] = None


class SamplingParamsMixin(BaseModel):
    model_config = ConfigDict(extra="allow")

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    n: int = 1
    stop: Optional[Union[str, List[str]]] = None
    seed: Optional[int] = None
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    ignore_eos: bool = False
    stream: bool = False
    stream_options: Optional[Dict[str, Any]] = None
    logprobs: Optional[Union[bool, int]] = None
    top_logprobs: Optional[int] = None


class ChatCompletionRequest(SamplingParamsMixin, OpenAIBaseModel):
    model: str
    messages: List[ChatMessage]
    user: Optional[str] = None


class CompletionRequest(SamplingParamsMixin, OpenAIBaseModel):
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    echo: bool = False
    user: Optional[str] = None


class EmbeddingRequest(OpenAIBaseModel):
    model: str
    input: Union[str, List[str], List[int], List[List[int]]]
    encoding_format: str = "float"
    user: Optional[str] = None


class TokenizeRequest(OpenAIBaseModel):
    model: Optional[str] = None
    prompt: Optional[str] = None
    messages: Optional[List[ChatMessage]] = None
    add_special_tokens: bool = True


class DetokenizeRequest(OpenAIBaseModel):
    model: Optional[str] = None
    tokens: List[int] = Field(default_factory=list)


class RerankRequest(OpenAIBaseModel):
    model: str
    query: str
    documents: List[str] = Field(default_factory=list)
    top_n: Optional[int] = None


class ScoreRequest(OpenAIBaseModel):
    model: str
    text_1: Union[str, List[str]]
    text_2: Union[str, List[str]]


class UsageInfo(OpenAIBaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatCompletionChoice(OpenAIBaseModel):
    index: int = 0
    message: Optional[ChatMessage] = None
    delta: Optional[Dict[str, Any]] = None
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class ChatCompletionResponse(OpenAIBaseModel):
    id: str = Field(default_factory=lambda: f"chatcmpl-{uuid.uuid4().hex}")
    object: Literal["chat.completion", "chat.completion.chunk"] = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatCompletionChoice] = Field(default_factory=list)
    usage: Optional[UsageInfo] = None


class CompletionChoice(OpenAIBaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionResponse(OpenAIBaseModel):
    id: str = Field(default_factory=lambda: f"cmpl-{uuid.uuid4().hex}")
    object: str = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: Optional[UsageInfo] = None


def request_id(prefix: str = "req") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"
