"""Mixtral-style sparse-MoE decoder (BASELINE config 5: Mixtral-8x7B).

Llama attention + a top-k routed expert MLP. Expert compute is expressed as
a dense einsum over all experts weighted by the routing mask — on TPU this
keeps the MXU busy with one big batched matmul and avoids dynamic shapes;
with an ``ep`` mesh axis the expert dimension shards across chips and XLA
inserts the all-to-all. (Capacity-based token dropping is not needed because
every token computes its top-k experts exactly.)
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.models.llama import rms_norm, rope
from production_stack_tpu.ops.attention import (
    context_prefill_attention,
    paged_decode_attention,
    prefill_attention,
    write_kv_pages,
)


def init_params(cfg: ModelConfig, rng: jax.Array, **_unused) -> Dict:
    dtype = cfg.jnp_dtype
    H, KVH, D, Hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.hidden_size
    I, L, V, E = cfg.intermediate_size, cfg.num_layers, cfg.vocab_size, cfg.num_experts
    keys = jax.random.split(rng, 12)

    def stack(key, shape, fan_in):
        return (
            jax.random.normal(key, (L,) + shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(dtype)

    return {
        "embed": (0.02 * jax.random.normal(keys[0], (V, Hd), jnp.float32)).astype(dtype),
        "layers": {
            "attn_norm": jnp.ones((L, Hd), dtype),
            "wq": stack(keys[1], (Hd, H * D), Hd),
            "wk": stack(keys[2], (Hd, KVH * D), Hd),
            "wv": stack(keys[3], (Hd, KVH * D), Hd),
            "wo": stack(keys[4], (H * D, Hd), H * D),
            "mlp_norm": jnp.ones((L, Hd), dtype),
            "router": stack(keys[5], (Hd, E), Hd),
            "w_gate": stack(keys[6], (E, Hd, I), Hd),
            "w_up": stack(keys[7], (E, Hd, I), Hd),
            "w_down": stack(keys[8], (E, I, Hd), I),
        },
        "final_norm": jnp.ones((Hd,), dtype),
        "lm_head": (
            jax.random.normal(keys[9], (Hd, V), jnp.float32) / jnp.sqrt(Hd)
        ).astype(dtype),
    }


def moe_mlp(cfg: ModelConfig, p: Dict, h: jax.Array) -> jax.Array:
    """Top-k routed expert MLP. h: [B, T, Hd] -> [B, T, Hd]."""
    B, T, Hd = h.shape
    E, K = cfg.num_experts, cfg.experts_per_token
    router_logits = (h @ p["router"]).astype(jnp.float32)  # [B,T,E]
    topk_vals, topk_idx = jax.lax.top_k(router_logits, K)
    topk_w = jax.nn.softmax(topk_vals, axis=-1)  # [B,T,K]
    one_hot = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [B,T,K,E]
    dense_w = jnp.einsum("btk,btke->bte", topk_w, one_hot)  # [B,T,E]
    # All-expert compute, weighted combine (MXU-dense, EP-shardable).
    gate = jnp.einsum("bth,ehi->btei", h, p["w_gate"])
    up = jnp.einsum("bth,ehi->btei", h, p["w_up"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(h.dtype) * up
    out = jnp.einsum("btei,eih->bteh", act, p["w_down"])
    return jnp.einsum(
        "bteh,bte->bth", out.astype(jnp.float32), dense_w
    ).astype(h.dtype)


def _layer(
    cfg: ModelConfig, mode: str, x, p, kv, layer,
    positions, slot_mapping, block_tables, context_lens, seq_lens,
):
    B, T, Hd = x.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    scale = 1.0 / (D ** 0.5)
    k_pages, v_pages = kv  # stacked [L, NB, bs, KVH, D]

    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q = rope((h @ p["wq"]).reshape(B, T, H, D), positions, cfg.rope_theta)
    k = rope((h @ p["wk"]).reshape(B, T, KVH, D), positions, cfg.rope_theta)
    v = (h @ p["wv"]).reshape(B, T, KVH, D)
    k_pages, v_pages = write_kv_pages(
        k_pages, v_pages, k, v, slot_mapping, layer)
    if mode == "prefill":
        attn = prefill_attention(q, k, v, scale=scale, seq_lens=seq_lens)
    elif mode == "prefill_cached":
        # Suffix prefill after a prefix-cache hit: attend over HBM pages
        # (cached prefix + just-written suffix).
        attn = context_prefill_attention(
            q, k_pages, v_pages, block_tables, positions, context_lens,
            layer, scale=scale, k_new=k, v_new=v, suffix_lens=seq_lens,
        )
    else:
        attn = paged_decode_attention(
            q[:, 0], k_pages, v_pages, block_tables, context_lens, layer,
            scale=scale,
        )[:, None]
    x = x + attn.reshape(B, T, H * D) @ p["wo"]

    h = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    x = x + moe_mlp(cfg, p, h)
    return x, (k_pages, v_pages)


def apply(
    params: Dict,
    cfg: ModelConfig,
    token_ids, positions, kv_pages, slot_mapping, block_tables,
    context_lens, seq_lens, *, mode: str, adapter_ids=None, output_hidden: bool = False,
    last_token=None,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    del adapter_ids  # LoRA slots are a Llama-family feature for now
    x = params["embed"][token_ids].astype(cfg.jnp_dtype)
    k_all, v_all = kv_pages
    layer_fn = functools.partial(
        _layer, cfg, mode,
        positions=positions, slot_mapping=slot_mapping,
        block_tables=block_tables, context_lens=context_lens, seq_lens=seq_lens,
    )

    # Stacked KV pages ride the scan carry whole (in-place under XLA);
    # see llama.apply.
    L = (k_all[0] if isinstance(k_all, tuple) else k_all).shape[0]

    def scan_body(carry, layer_params):
        x, k_all, v_all, l = carry
        x, (k_all, v_all) = layer_fn(x, layer_params, (k_all, v_all), l)
        return (x, k_all, v_all, l + 1), None

    (x, k_all, v_all, _), _ = jax.lax.scan(
        scan_body, (x, k_all, v_all, jnp.int32(0)), params["layers"],
        length=L,
    )
    if last_token is not None:
        # Prefill sampling reads ONE position: slice before norm + head
        # (positionwise ops commute with the slice; see llama.apply).
        x = jnp.take_along_axis(x, last_token[:, None, None], axis=1)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    if output_hidden:
        return x.astype(jnp.float32), (k_all, v_all)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, (k_all, v_all)
