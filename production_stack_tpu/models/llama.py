"""Llama-family decoder (covers Llama 2/3, Mistral, TinyLlama via config).

Functional JAX implementation built for serving with a paged KV cache:

- parameters are a pytree with per-layer leaves stacked on a leading axis so
  the decoder runs as one ``lax.scan`` over layers (single-layer trace →
  fast XLA compiles even at 80 layers);
- every forward writes fresh K/V into HBM pages (``ops.write_kv_pages``) and
  attends either causally within the prompt (prefill) or over the pages via
  paged attention (decode);
- weights use bfloat16 by default; all norms/softmax accumulate in float32.

The reference stack runs these models inside vLLM CUDA images
(``helm/templates/deployment-vllm-multi.yaml:108-199``); this module is the
TPU-native replacement at the engine layer.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.ops.attention import (
    context_prefill_attention,
    paged_decode_attention,
    prefill_attention,
    write_kv_pages,
)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def rope(
    x: jax.Array,  # [B, T, H, D]
    positions: jax.Array,  # [B, T]
    theta: float,
) -> jax.Array:
    D = x.shape[-1]
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    )
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def init_params(
    cfg: ModelConfig,
    rng: jax.Array,
    *,
    lora_slots: int = 0,
    lora_rank: int = 16,
) -> Dict:
    """Random-init parameter pytree with layer-stacked leaves.

    With ``lora_slots > 0`` the pytree carries fixed-shape LoRA slot tensors
    (zero-initialised = identity adapters) applied to the q/v projections —
    adapters hot-swap by writing a slot, never by recompiling (SURVEY §7
    "LoRA hot-swap under jit").
    """
    dtype = cfg.jnp_dtype
    H, KVH, D, Hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.hidden_size
    I, L, V = cfg.intermediate_size, cfg.num_layers, cfg.vocab_size
    keys = jax.random.split(rng, 10)

    def winit(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(dtype)

    def stack(key, shape, fan_in):
        return winit(key, (L,) + shape, fan_in)

    params = {
        "embed": (0.02 * jax.random.normal(keys[0], (V, Hd), jnp.float32)).astype(dtype),
        "layers": {
            "attn_norm": jnp.ones((L, Hd), dtype),
            "wq": stack(keys[1], (Hd, H * D), Hd),
            "wk": stack(keys[2], (Hd, KVH * D), Hd),
            "wv": stack(keys[3], (Hd, KVH * D), Hd),
            "wo": stack(keys[4], (H * D, Hd), H * D),
            "mlp_norm": jnp.ones((L, Hd), dtype),
            "w_gate": stack(keys[5], (Hd, I), Hd),
            "w_up": stack(keys[6], (Hd, I), Hd),
            "w_down": stack(keys[7], (I, Hd), I),
        },
        "final_norm": jnp.ones((Hd,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = winit(keys[8], (Hd, V), Hd)
    if lora_slots > 0:
        S, R = lora_slots, lora_rank
        params["lora"] = {
            "wq_a": jnp.zeros((L, S, Hd, R), dtype),
            "wq_b": jnp.zeros((L, S, R, H * D), dtype),
            "wv_a": jnp.zeros((L, S, Hd, R), dtype),
            "wv_b": jnp.zeros((L, S, R, KVH * D), dtype),
            "scaling": jnp.zeros((S,), jnp.float32),
        }
    return params


def _proj(h: jax.Array, p: Dict, name: str) -> jax.Array:
    """``h @ W`` for a weight leaf that may be int8-quantized
    (models/quantize.py): int8 storage halves the HBM weight read and the
    ``astype`` dequant fuses into the matmul operand; the per-output-
    channel scale applies to the [B, T, out] result."""
    w = p[name]
    if w.dtype == jnp.int8:
        out = h @ w.astype(h.dtype)
        return out * p[name + "_scale"][0].astype(h.dtype)
    return h @ w


def _lora_delta(h, a, b, scaling, adapter_ids):
    """Per-sequence LoRA delta: h [B,T,Hd] @ A[sel] @ B[sel] * scale."""
    a_sel = a[adapter_ids]  # [B, Hd, R]
    b_sel = b[adapter_ids]  # [B, R, out]
    s_sel = scaling[adapter_ids]  # [B]
    mid = jnp.einsum("bth,bhr->btr", h, a_sel)
    out = jnp.einsum("btr,bro->bto", mid, b_sel)
    return out * s_sel[:, None, None].astype(out.dtype)


def _layer(
    cfg: ModelConfig,
    mode: str,
    x: jax.Array,  # [B, T, Hd]
    layer_params: Dict,  # un-stacked (one layer's leaves)
    lora: Dict | None,  # un-stacked per-layer LoRA leaves, or None
    kv: Tuple[jax.Array, jax.Array],  # STACKED pages [L, NB, bs, KVH, D]
    layer: jax.Array,  # scalar layer index
    positions: jax.Array,
    slot_mapping: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    seq_lens: jax.Array,
    lora_scaling: jax.Array | None,
    adapter_ids: jax.Array | None,
):
    p = layer_params
    B, T, Hd = x.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    scale = 1.0 / (D ** 0.5)
    k_pages, v_pages = kv

    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    q_flat = _proj(h, p, "wq")
    v_flat = _proj(h, p, "wv")
    if lora is not None:
        q_flat = q_flat + _lora_delta(
            h, lora["wq_a"], lora["wq_b"], lora_scaling, adapter_ids
        )
        v_flat = v_flat + _lora_delta(
            h, lora["wv_a"], lora["wv_b"], lora_scaling, adapter_ids
        )
    q = q_flat.reshape(B, T, H, D)
    k = _proj(h, p, "wk").reshape(B, T, KVH, D)
    v = v_flat.reshape(B, T, KVH, D)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    k_pages, v_pages = write_kv_pages(
        k_pages, v_pages, k, v, slot_mapping, layer)

    if mode == "prefill":
        attn = prefill_attention(q, k, v, scale=scale, seq_lens=seq_lens)
    elif mode == "prefill_cached":
        # Suffix prefill after a prefix-cache hit: attend over HBM pages
        # (cached prefix + just-written suffix). The chunk's own fresh
        # k/v ride along so the flash kernel can serve the suffix from
        # VMEM and stream only the cached prefix pages.
        attn = context_prefill_attention(
            q, k_pages, v_pages, block_tables, positions, context_lens,
            layer, scale=scale, k_new=k, v_new=v, suffix_lens=seq_lens,
        )
    else:
        attn = paged_decode_attention(
            q[:, 0], k_pages, v_pages, block_tables, context_lens, layer,
            scale=scale,
        )[:, None]
    x = x + _proj(attn.reshape(B, T, H * D), p, "wo")

    h = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(
        _proj(h, p, "w_gate").astype(jnp.float32)).astype(h.dtype)
    x = x + _proj(gate * _proj(h, p, "w_up"), p, "w_down")
    return x, (k_pages, v_pages)


def embed_tokens(params: Dict, cfg: ModelConfig, token_ids: jax.Array,
                 adapter_ids: jax.Array | None):
    """Shared forward preamble: input embeddings + LoRA leaf plumbing.

    Used by both the single-program ``apply`` and the pipeline-parallel
    wrapper (``parallel/pp_serving.py``) so the two paths cannot diverge.
    Returns (x, lora_layers, lora_scaling, adapter_ids).
    """
    emb = params["embed"]
    if emb.dtype == jnp.int8:
        # Row-quantized table: dequant only the gathered rows.
        x = (emb[token_ids].astype(cfg.jnp_dtype)
             * params["embed_scale"][token_ids].astype(cfg.jnp_dtype))
    else:
        x = emb[token_ids].astype(cfg.jnp_dtype)
    lora = params.get("lora")
    lora_scaling = lora["scaling"] if lora is not None else None
    if lora is not None and adapter_ids is None:
        adapter_ids = jnp.zeros((token_ids.shape[0],), jnp.int32)
    lora_layers = (
        {k: v for k, v in lora.items() if k != "scaling"}
        if lora is not None else None
    )
    return x, lora_layers, lora_scaling, adapter_ids


def project_out(params: Dict, cfg: ModelConfig, x: jax.Array,
                output_hidden: bool) -> jax.Array:
    """Shared forward tail: final norm, then hidden states or logits."""
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    if output_hidden:
        return x.astype(jnp.float32)
    head = params.get("lm_head")
    if head is not None:
        if head.dtype == jnp.int8:
            # [Hd, V] int8 with scale [1, V]: scale per vocab channel.
            logits = (x @ head.astype(x.dtype)).astype(jnp.float32)
            return logits * params["lm_head_scale"][0]
        return (x @ head).astype(jnp.float32)
    emb = params["embed"]
    if emb.dtype == jnp.int8:
        # Tied head: embed [V, Hd] row scales [V, 1] become per-vocab
        # output scales of embed.T.
        logits = (x @ emb.T.astype(x.dtype)).astype(jnp.float32)
        return logits * params["embed_scale"][:, 0]
    return (x @ emb.T).astype(jnp.float32)


def apply(
    params: Dict,
    cfg: ModelConfig,
    token_ids: jax.Array,  # [B, T]
    positions: jax.Array,  # [B, T]
    kv_pages: Tuple[jax.Array, jax.Array],  # ([L,NB,bs,KVH,D], [L,NB,bs,KVH,D])
    slot_mapping: jax.Array,  # [B, T]
    block_tables: jax.Array,  # [B, MAXB]
    context_lens: jax.Array,  # [B]
    seq_lens: jax.Array,  # [B] valid prompt lengths (prefill padding mask)
    *,
    mode: str,  # "prefill" | "prefill_cached" | "decode"  (static)
    adapter_ids: jax.Array | None = None,  # [B] LoRA slot per sequence
    output_hidden: bool = False,  # return final hidden states, not logits
    last_token: jax.Array | None = None,  # [B] position whose logits to keep
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Full forward. Returns (logits [B, T, V], updated kv_pages), or the
    post-norm hidden states [B, T, Hd] instead of logits when
    ``output_hidden`` (the /v1/embeddings pass). With ``last_token``
    (prefill sampling: only one position's logits are ever read), the
    hidden states are sliced to that position BEFORE the norm + head, so
    the vocab projection runs on [B, 1, Hd] instead of the whole chunk —
    for a 128k-vocab model that removes a multi-GB f32 logits temp and
    ~0.8 TFLOP per 2048-token chunk, with bit-identical results."""
    x, lora_layers, lora_scaling, adapter_ids = embed_tokens(
        params, cfg, token_ids, adapter_ids)
    k_all, v_all = kv_pages

    layer_fn = functools.partial(
        _layer, cfg, mode,
        positions=positions, slot_mapping=slot_mapping,
        block_tables=block_tables, context_lens=context_lens,
        seq_lens=seq_lens, lora_scaling=lora_scaling, adapter_ids=adapter_ids,
    )

    # The STACKED KV pages ride the scan carry whole; every op addresses
    # them through the scalar layer index (flat scatter / page-level
    # gather). Loop carries alias in place under XLA, so only the touched
    # pages move — per-layer slices (or pages in the scan ys) would copy
    # the entire pool every forward step. With an int8 cache each side is
    # a (data, scales) tuple that rides the carry the same way.
    L = (k_all[0] if isinstance(k_all, tuple) else k_all).shape[0]

    if lora_layers is not None:
        def scan_body(carry, per_layer):
            x, k_all, v_all, l = carry
            layer_params, lora_p = per_layer
            x, (k_all, v_all) = layer_fn(
                x, layer_params, lora_p, (k_all, v_all), l
            )
            return (x, k_all, v_all, l + 1), None

        (x, k_all, v_all, _), _ = jax.lax.scan(
            scan_body, (x, k_all, v_all, jnp.int32(0)),
            (params["layers"], lora_layers), length=L,
        )
    else:
        def scan_body(carry, layer_params):
            x, k_all, v_all, l = carry
            x, (k_all, v_all) = layer_fn(
                x, layer_params, None, (k_all, v_all), l
            )
            return (x, k_all, v_all, l + 1), None

        (x, k_all, v_all, _), _ = jax.lax.scan(
            scan_body, (x, k_all, v_all, jnp.int32(0)),
            params["layers"], length=L,
        )
    if last_token is not None:
        x = jnp.take_along_axis(x, last_token[:, None, None], axis=1)
    return project_out(params, cfg, x, output_hidden), (k_all, v_all)
