"""Functional JAX model definitions for the TPU serving engine.

The reference stack consumes models through vLLM container images; here the
model zoo is native: Llama-family (covers Llama 2/3, Mistral, TinyLlama via
config), OPT, and Mixtral-style MoE — written as pure functions over a
parameter pytree so they jit/pjit cleanly over a ``jax.sharding.Mesh``.
"""

from production_stack_tpu.models.config import ModelConfig, get_model_config
from production_stack_tpu.models.registry import build_model

__all__ = ["ModelConfig", "get_model_config", "build_model"]
