"""Checkpoint loading: HuggingFace safetensors/torch weights -> the
engine's parameter pytrees.

The reference stack mounts HF weights into PVCs and lets vLLM load them
(``helm/values.yaml`` pvcStorage + modelURL); here the engine loads them
natively. Layer leaves are stacked on a leading axis (the models run one
``lax.scan`` over layers), and projection matrices are transposed from
HF's ``[out, in]`` to our ``x @ W`` ``[in, out]`` layout.

Entry point: :func:`load_checkpoint` — returns a params pytree matching
``init_params`` of the target architecture, or raises with the list of
unmapped tensors so partial/foreign checkpoints fail loudly instead of
serving garbage.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def _iter_checkpoint_tensors(path: str):
    """Yield (name, np.ndarray) from all safetensors / torch shards."""
    st_files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(f, framework="np") as sf:
                for name in sf.keys():
                    yield name, sf.get_tensor(name)
        return
    bin_files = sorted(glob.glob(os.path.join(path, "pytorch_model*.bin")))
    if not bin_files:
        raise FileNotFoundError(
            f"no *.safetensors or pytorch_model*.bin under {path}")
    import torch

    for f in bin_files:
        state = torch.load(f, map_location="cpu", weights_only=True)
        for name, tensor in state.items():
            yield name, tensor.to(torch.float32).numpy()


def _to_dtype(arr: np.ndarray, dtype) -> jnp.ndarray:
    return jnp.asarray(arr).astype(dtype)


# --------------------------------------------------------------------- #
# Llama family (llama / mistral)
# --------------------------------------------------------------------- #

def _load_llama(cfg: ModelConfig, path: str) -> Dict:
    L = cfg.num_layers
    dtype = cfg.jnp_dtype
    per_layer: Dict[str, List] = {
        k: [None] * L for k in (
            "attn_norm", "wq", "wk", "wv", "wo",
            "mlp_norm", "w_gate", "w_up", "w_down",
        )
    }
    top: Dict[str, jnp.ndarray] = {}
    unmapped = []

    layer_map = {
        "input_layernorm.weight": ("attn_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("mlp_norm", False),
        "mlp.gate_proj.weight": ("w_gate", True),
        "mlp.up_proj.weight": ("w_up", True),
        "mlp.down_proj.weight": ("w_down", True),
    }

    for name, arr in _iter_checkpoint_tensors(path):
        if name in ("model.embed_tokens.weight",):
            top["embed"] = _to_dtype(arr, dtype)
        elif name in ("model.norm.weight",):
            top["final_norm"] = _to_dtype(arr, dtype)
        elif name == "lm_head.weight":
            top["lm_head"] = _to_dtype(arr.T, dtype)
        elif name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_str, leaf = rest.split(".", 1)
            i = int(idx_str)
            entry = layer_map.get(leaf)
            if entry is None or i >= L:
                unmapped.append(name)
                continue
            key, transpose = entry
            per_layer[key][i] = _to_dtype(
                arr.T if transpose else arr, dtype)
        elif name.endswith("rotary_emb.inv_freq"):
            continue  # computed, not a parameter
        else:
            unmapped.append(name)

    missing = [
        f"layers.{k}[{i}]" for k, v in per_layer.items()
        for i, leaf in enumerate(v) if leaf is None
    ]
    for req_key in ("embed", "final_norm"):
        if req_key not in top:
            missing.append(req_key)
    if missing:
        raise ValueError(
            f"checkpoint at {path} is missing tensors: {missing[:8]}"
            + (f" (+{len(missing) - 8} more)" if len(missing) > 8 else ""))
    if unmapped:
        logger.warning("checkpoint: %d unmapped tensors (e.g. %s)",
                       len(unmapped), unmapped[:3])

    params: Dict = {
        "embed": top["embed"],
        "final_norm": top["final_norm"],
        "layers": {k: jnp.stack(v) for k, v in per_layer.items()},
    }
    if cfg.tie_word_embeddings or "lm_head" not in top:
        pass  # apply() falls back to embed.T
    else:
        params["lm_head"] = top["lm_head"]
    return params


# --------------------------------------------------------------------- #
# OPT
# --------------------------------------------------------------------- #

def _load_opt(cfg: ModelConfig, path: str) -> Dict:
    L = cfg.num_layers
    dtype = cfg.jnp_dtype
    keys = ("ln1_w", "ln1_b", "wq", "wq_b", "wk", "wk_b", "wv", "wv_b",
            "wo", "wo_b", "ln2_w", "ln2_b", "fc1", "fc1_b", "fc2", "fc2_b")
    per_layer: Dict[str, List] = {k: [None] * L for k in keys}
    top: Dict[str, jnp.ndarray] = {}
    unmapped = []

    layer_map = {
        "self_attn_layer_norm.weight": ("ln1_w", False),
        "self_attn_layer_norm.bias": ("ln1_b", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.q_proj.bias": ("wq_b", False),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.k_proj.bias": ("wk_b", False),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.v_proj.bias": ("wv_b", False),
        "self_attn.out_proj.weight": ("wo", True),
        "self_attn.out_proj.bias": ("wo_b", False),
        "final_layer_norm.weight": ("ln2_w", False),
        "final_layer_norm.bias": ("ln2_b", False),
        "fc1.weight": ("fc1", True),
        "fc1.bias": ("fc1_b", False),
        "fc2.weight": ("fc2", True),
        "fc2.bias": ("fc2_b", False),
    }

    prefix = "model.decoder."
    for name, arr in _iter_checkpoint_tensors(path):
        short = name[len(prefix):] if name.startswith(prefix) else name
        if short == "embed_tokens.weight":
            top["embed"] = _to_dtype(arr, dtype)
        elif short == "embed_positions.weight":
            top["pos_embed"] = _to_dtype(arr, dtype)
        elif short in ("final_layer_norm.weight",):
            top["final_ln_w"] = _to_dtype(arr, dtype)
        elif short in ("final_layer_norm.bias",):
            top["final_ln_b"] = _to_dtype(arr, dtype)
        elif short == "lm_head.weight" or name == "lm_head.weight":
            continue  # OPT ties lm_head to embeddings
        elif short.startswith("layers."):
            rest = short[len("layers."):]
            idx_str, leaf = rest.split(".", 1)
            i = int(idx_str)
            entry = layer_map.get(leaf)
            if entry is None or i >= L:
                unmapped.append(name)
                continue
            key, transpose = entry
            per_layer[key][i] = _to_dtype(
                arr.T if transpose else arr, dtype)
        else:
            unmapped.append(name)

    missing = [
        f"layers.{k}[{i}]" for k, v in per_layer.items()
        for i, leaf in enumerate(v) if leaf is None
    ]
    for req_key in ("embed", "pos_embed", "final_ln_w", "final_ln_b"):
        if req_key not in top:
            missing.append(req_key)
    if missing:
        raise ValueError(
            f"checkpoint at {path} is missing tensors: {missing[:8]}"
            + (f" (+{len(missing) - 8} more)" if len(missing) > 8 else ""))
    if unmapped:
        logger.warning("checkpoint: %d unmapped tensors (e.g. %s)",
                       len(unmapped), unmapped[:3])

    return {
        "embed": top["embed"],
        "pos_embed": top["pos_embed"],
        "final_ln_w": top["final_ln_w"],
        "final_ln_b": top["final_ln_b"],
        "layers": {k: jnp.stack(v) for k, v in per_layer.items()},
    }


# --------------------------------------------------------------------- #
# Mixtral (MoE)
# --------------------------------------------------------------------- #

def _load_mixtral(cfg: ModelConfig, path: str) -> Dict:
    L, E = cfg.num_layers, cfg.num_experts
    dtype = cfg.jnp_dtype
    per_layer: Dict[str, List] = {
        k: [None] * L for k in ("attn_norm", "wq", "wk", "wv", "wo",
                                "mlp_norm", "router")
    }
    experts: Dict[str, List] = {
        k: [[None] * E for _ in range(L)]
        for k in ("w_gate", "w_up", "w_down")
    }
    top: Dict[str, jnp.ndarray] = {}
    unmapped = []

    layer_map = {
        "input_layernorm.weight": ("attn_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("mlp_norm", False),
        "block_sparse_moe.gate.weight": ("router", True),
    }
    expert_map = {"w1": "w_gate", "w3": "w_up", "w2": "w_down"}

    for name, arr in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            top["embed"] = _to_dtype(arr, dtype)
        elif name == "model.norm.weight":
            top["final_norm"] = _to_dtype(arr, dtype)
        elif name == "lm_head.weight":
            top["lm_head"] = _to_dtype(arr.T, dtype)
        elif name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_str, leaf = rest.split(".", 1)
            i = int(idx_str)
            if leaf.startswith("block_sparse_moe.experts."):
                parts = leaf.split(".")
                e = int(parts[2])
                w = expert_map.get(parts[3])
                if w is None or i >= L or e >= E:
                    unmapped.append(name)
                    continue
                experts[w][i][e] = _to_dtype(arr.T, dtype)
                continue
            entry = layer_map.get(leaf)
            if entry is None or i >= L:
                unmapped.append(name)
                continue
            key, transpose = entry
            per_layer[key][i] = _to_dtype(
                arr.T if transpose else arr, dtype)
        else:
            unmapped.append(name)

    missing = [
        f"layers.{k}[{i}]" for k, v in per_layer.items()
        for i, leaf in enumerate(v) if leaf is None
    ] + [
        f"experts.{k}[{i}][{e}]" for k, le in experts.items()
        for i, row in enumerate(le) for e, leaf in enumerate(row)
        if leaf is None
    ]
    for req_key in ("embed", "final_norm", "lm_head"):
        if req_key not in top:
            missing.append(req_key)
    if missing:
        raise ValueError(
            f"checkpoint at {path} is missing tensors: {missing[:8]}"
            + (f" (+{len(missing) - 8} more)" if len(missing) > 8 else ""))
    if unmapped:
        logger.warning("checkpoint: %d unmapped tensors (e.g. %s)",
                       len(unmapped), unmapped[:3])

    layers = {k: jnp.stack(v) for k, v in per_layer.items()}
    for k, le in experts.items():
        layers[k] = jnp.stack([jnp.stack(row) for row in le])  # [L, E, ...]
    params = {
        "embed": top["embed"],
        "final_norm": top["final_norm"],
        "layers": layers,
    }
    if "lm_head" in top:
        params["lm_head"] = top["lm_head"]
    return params


def load_checkpoint(cfg: ModelConfig, path: str) -> Dict:
    """Load HF weights at ``path`` into the arch's parameter pytree."""
    loader = {"llama": _load_llama, "opt": _load_opt,
              "mixtral": _load_mixtral}[cfg.arch]
    logger.info("Loading %s checkpoint from %s", cfg.arch, path)
    return loader(cfg, path)


def load_whisper_checkpoint(cfg, path: str) -> Dict:
    """HF WhisperForConditionalGeneration safetensors -> the param tree of
    :mod:`production_stack_tpu.models.whisper` (reference serves Whisper via
    vLLM images; ``src/vllm_router/services/request_service/request.py:513-689``).

    torch Linear weights are [out, in] and our layout is ``x @ W`` =
    [in, out], so every projection transposes; conv1d weights go
    [out, in, k] -> [k, in, out] (WIO); k_proj carries no bias in Whisper.
    """
    dt = jnp.dtype(cfg.dtype)
    sd = {name: arr for name, arr in _iter_checkpoint_tensors(path)}

    def t(name):  # [out, in] -> [in, out]
        return _to_dtype(np.ascontiguousarray(sd[name].T), dt)

    def raw(name):
        return _to_dtype(sd[name], dt)

    def conv(name):  # [out, in, k] -> [k, in, out]
        return _to_dtype(
            np.ascontiguousarray(sd[name].transpose(2, 1, 0)), dt)

    def block(prefix: str, cross: bool) -> Dict:
        p = {
            "ln1_g": raw(f"{prefix}.self_attn_layer_norm.weight"),
            "ln1_b": raw(f"{prefix}.self_attn_layer_norm.bias"),
            "q": t(f"{prefix}.self_attn.q_proj.weight"),
            "q_b": raw(f"{prefix}.self_attn.q_proj.bias"),
            "k": t(f"{prefix}.self_attn.k_proj.weight"),
            "v": t(f"{prefix}.self_attn.v_proj.weight"),
            "v_b": raw(f"{prefix}.self_attn.v_proj.bias"),
            "o": t(f"{prefix}.self_attn.out_proj.weight"),
            "o_b": raw(f"{prefix}.self_attn.out_proj.bias"),
            "ln2_g": raw(f"{prefix}.final_layer_norm.weight"),
            "ln2_b": raw(f"{prefix}.final_layer_norm.bias"),
            "fc1": t(f"{prefix}.fc1.weight"),
            "fc1_b": raw(f"{prefix}.fc1.bias"),
            "fc2": t(f"{prefix}.fc2.weight"),
            "fc2_b": raw(f"{prefix}.fc2.bias"),
        }
        if cross:
            p.update({
                "lnx_g": raw(f"{prefix}.encoder_attn_layer_norm.weight"),
                "lnx_b": raw(f"{prefix}.encoder_attn_layer_norm.bias"),
                "xq": t(f"{prefix}.encoder_attn.q_proj.weight"),
                "xq_b": raw(f"{prefix}.encoder_attn.q_proj.bias"),
                "xk": t(f"{prefix}.encoder_attn.k_proj.weight"),
                "xv": t(f"{prefix}.encoder_attn.v_proj.weight"),
                "xv_b": raw(f"{prefix}.encoder_attn.v_proj.bias"),
                "xo": t(f"{prefix}.encoder_attn.out_proj.weight"),
                "xo_b": raw(f"{prefix}.encoder_attn.out_proj.bias"),
            })
        return p

    logger.info("Loading whisper checkpoint from %s", path)
    return {
        "conv1": conv("model.encoder.conv1.weight"),
        "conv1_b": raw("model.encoder.conv1.bias"),
        "conv2": conv("model.encoder.conv2.weight"),
        "conv2_b": raw("model.encoder.conv2.bias"),
        "enc_pos": raw("model.encoder.embed_positions.weight"),
        "enc_blocks": [
            block(f"model.encoder.layers.{i}", cross=False)
            for i in range(cfg.encoder_layers)
        ],
        "enc_ln_g": raw("model.encoder.layer_norm.weight"),
        "enc_ln_b": raw("model.encoder.layer_norm.bias"),
        "tok_emb": raw("model.decoder.embed_tokens.weight"),
        "dec_pos": raw("model.decoder.embed_positions.weight"),
        "dec_blocks": [
            block(f"model.decoder.layers.{i}", cross=True)
            for i in range(cfg.decoder_layers)
        ],
        "dec_ln_g": raw("model.decoder.layer_norm.weight"),
        "dec_ln_b": raw("model.decoder.layer_norm.bias"),
    }


def has_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and (
        bool(glob.glob(os.path.join(path, "*.safetensors")))
        or bool(glob.glob(os.path.join(path, "pytorch_model*.bin")))
    )
