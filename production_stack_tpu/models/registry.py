"""Model registry: arch name -> (init_params, apply)."""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from production_stack_tpu.models.config import ModelConfig


def build_model(cfg: ModelConfig) -> Tuple[Callable, Callable]:
    """Return (init_params(cfg, rng) -> params, apply(params, cfg, ...))."""
    if cfg.arch == "llama":
        from production_stack_tpu.models import llama as mod
    elif cfg.arch == "opt":
        from production_stack_tpu.models import opt as mod
    elif cfg.arch == "mixtral":
        from production_stack_tpu.models import mixtral as mod
    else:
        raise ValueError(f"Unknown arch {cfg.arch!r}")
    return mod.init_params, mod.apply
