"""OPT decoder (facebook/opt-*) for the smoke-test config.

BASELINE config 1 is ``facebook/opt-125m`` single-pod; the reference deploys
it via CPU vLLM (``values-01-minimal-example.yaml``). Differences from the
Llama family: learned positional embeddings (offset by 2), LayerNorm instead
of RMSNorm, ReLU MLP, no RoPE, MHA only. Same paged-KV serving interface.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.ops.attention import (
    context_prefill_attention,
    paged_decode_attention,
    prefill_attention,
    write_kv_pages,
)

POS_OFFSET = 2  # OPT's learned-position quirk


def layer_norm(x, weight, bias, eps=1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def init_params(cfg: ModelConfig, rng: jax.Array, **_unused) -> Dict:
    dtype = cfg.jnp_dtype
    H, D, Hd = cfg.num_heads, cfg.head_dim, cfg.hidden_size
    I, L, V = cfg.intermediate_size, cfg.num_layers, cfg.vocab_size
    keys = jax.random.split(rng, 8)

    def stack(key, shape, fan_in):
        return (
            jax.random.normal(key, (L,) + shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(dtype)

    return {
        "embed": (0.02 * jax.random.normal(keys[0], (V, Hd), jnp.float32)).astype(dtype),
        "pos_embed": (
            0.02 * jax.random.normal(keys[1], (cfg.max_position + POS_OFFSET, Hd), jnp.float32)
        ).astype(dtype),
        "layers": {
            "ln1_w": jnp.ones((L, Hd), dtype),
            "ln1_b": jnp.zeros((L, Hd), dtype),
            "wq": stack(keys[2], (Hd, H * D), Hd),
            "wq_b": jnp.zeros((L, H * D), dtype),
            "wk": stack(keys[3], (Hd, H * D), Hd),
            "wk_b": jnp.zeros((L, H * D), dtype),
            "wv": stack(keys[4], (Hd, H * D), Hd),
            "wv_b": jnp.zeros((L, H * D), dtype),
            "wo": stack(keys[5], (H * D, Hd), H * D),
            "wo_b": jnp.zeros((L, Hd), dtype),
            "ln2_w": jnp.ones((L, Hd), dtype),
            "ln2_b": jnp.zeros((L, Hd), dtype),
            "fc1": stack(keys[6], (Hd, I), Hd),
            "fc1_b": jnp.zeros((L, I), dtype),
            "fc2": stack(keys[7], (I, Hd), I),
            "fc2_b": jnp.zeros((L, Hd), dtype),
        },
        "final_ln_w": jnp.ones((Hd,), dtype),
        "final_ln_b": jnp.zeros((Hd,), dtype),
    }


def _layer(
    cfg: ModelConfig, mode: str, x, p, kv, layer,
    positions, slot_mapping, block_tables, context_lens, seq_lens,
):
    B, T, Hd = x.shape
    H, D = cfg.num_heads, cfg.head_dim
    scale = 1.0 / (D ** 0.5)
    k_pages, v_pages = kv  # stacked [L, NB, bs, KVH, D]

    h = layer_norm(x, p["ln1_w"], p["ln1_b"])
    q = (h @ p["wq"] + p["wq_b"]).reshape(B, T, H, D)
    k = (h @ p["wk"] + p["wk_b"]).reshape(B, T, H, D)
    v = (h @ p["wv"] + p["wv_b"]).reshape(B, T, H, D)
    k_pages, v_pages = write_kv_pages(
        k_pages, v_pages, k, v, slot_mapping, layer)
    if mode == "prefill":
        attn = prefill_attention(q, k, v, scale=scale, seq_lens=seq_lens)
    elif mode == "prefill_cached":
        # Suffix prefill after a prefix-cache hit: attend over HBM pages
        # (cached prefix + just-written suffix).
        attn = context_prefill_attention(
            q, k_pages, v_pages, block_tables, positions, context_lens,
            layer, scale=scale, k_new=k, v_new=v, suffix_lens=seq_lens,
        )
    else:
        attn = paged_decode_attention(
            q[:, 0], k_pages, v_pages, block_tables, context_lens, layer,
            scale=scale,
        )[:, None]
    x = x + attn.reshape(B, T, H * D) @ p["wo"] + p["wo_b"]

    h = layer_norm(x, p["ln2_w"], p["ln2_b"])
    h = jax.nn.relu(h @ p["fc1"] + p["fc1_b"])
    x = x + h @ p["fc2"] + p["fc2_b"]
    return x, (k_pages, v_pages)


def apply(
    params: Dict,
    cfg: ModelConfig,
    token_ids, positions, kv_pages, slot_mapping, block_tables,
    context_lens, seq_lens, *, mode: str, adapter_ids=None, output_hidden: bool = False,
    last_token=None,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    del adapter_ids  # LoRA slots are a Llama-family feature for now
    x = params["embed"][token_ids].astype(cfg.jnp_dtype)
    x = x + params["pos_embed"][positions + POS_OFFSET].astype(cfg.jnp_dtype)
    k_all, v_all = kv_pages
    layer_fn = functools.partial(
        _layer, cfg, mode,
        positions=positions, slot_mapping=slot_mapping,
        block_tables=block_tables, context_lens=context_lens, seq_lens=seq_lens,
    )

    # Stacked KV pages ride the scan carry whole (in-place under XLA);
    # see llama.apply.
    L = (k_all[0] if isinstance(k_all, tuple) else k_all).shape[0]

    def scan_body(carry, layer_params):
        x, k_all, v_all, l = carry
        x, (k_all, v_all) = layer_fn(x, layer_params, (k_all, v_all), l)
        return (x, k_all, v_all, l + 1), None

    (x, k_all, v_all, _), _ = jax.lax.scan(
        scan_body, (x, k_all, v_all, jnp.int32(0)), params["layers"],
        length=L,
    )
    if last_token is not None:
        # Prefill sampling reads ONE position: slice before norm + head
        # (positionwise ops commute with the slice; see llama.apply).
        x = jnp.take_along_axis(x, last_token[:, None, None], axis=1)
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"])
    if output_hidden:
        return x.astype(jnp.float32), (k_all, v_all)
    logits = (x @ params["embed"].T).astype(jnp.float32)
    return logits, (k_all, v_all)
