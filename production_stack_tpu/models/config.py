"""Model architecture configs and the named-model preset table.

Model weights cannot be downloaded in this environment (zero egress), so
named models resolve to architecture presets; weights come from a local
checkpoint directory when available (orbax/safetensors) or random
initialization otherwise. The preset table covers the model families the
reference stack's example configs exercise (BASELINE.json configs:
opt-125m, Llama-3-8B, Llama-3-70B, Mixtral-8x7B).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str = "tiny-llama"
    arch: str = "llama"  # llama | opt | mixtral
    vocab_size: int = 32000
    hidden_size: int = 2048
    num_layers: int = 16
    num_heads: int = 16
    num_kv_heads: int = 16
    head_dim: int = 128
    intermediate_size: int = 5632
    max_position: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    # OPT-specific
    do_layer_norm_before: bool = True
    # MoE (mixtral)
    num_experts: int = 0
    experts_per_token: int = 2
    dtype: str = "bfloat16"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def replace(self, **kwargs) -> "ModelConfig":
        return dataclasses.replace(self, **kwargs)


# Architecture presets. Sizes follow the public model cards.
_PRESETS = {
    "tiny-llama": ModelConfig(
        name="tiny-llama", arch="llama", vocab_size=512, hidden_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
        intermediate_size=256, max_position=2048, rope_theta=10000.0,
    ),
    "tiny-mixtral": ModelConfig(
        name="tiny-mixtral", arch="mixtral", vocab_size=512, hidden_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
        intermediate_size=256, max_position=2048, rope_theta=10000.0,
        num_experts=4, experts_per_token=2,
    ),
    "tiny-opt": ModelConfig(
        name="tiny-opt", arch="opt", vocab_size=512, hidden_size=128,
        num_layers=2, num_heads=4, num_kv_heads=4, head_dim=32,
        intermediate_size=512, max_position=2048,
    ),
    "facebook/opt-125m": ModelConfig(
        name="facebook/opt-125m", arch="opt", vocab_size=50272,
        hidden_size=768, num_layers=12, num_heads=12, num_kv_heads=12,
        head_dim=64, intermediate_size=3072, max_position=2048,
    ),
    # ~0.9B Llama-family preset sized to fit one v5e chip with KV headroom:
    # the flagship architecture class (GQA 16q/8kv, head_dim 128) at a scale
    # a single-chip bench can serve.
    "tpu-llama-1b": ModelConfig(
        name="tpu-llama-1b", arch="llama", vocab_size=32000,
        hidden_size=2048, num_layers=16, num_heads=16, num_kv_heads=8,
        head_dim=128, intermediate_size=7168, max_position=8192,
        rope_theta=500000.0,
    ),
    # ~3.2B Llama-family preset (Llama-3.2-3B card dimensions): the largest
    # Llama-class architecture that fits a single 16 GB v5e chip in bf16
    # with KV headroom (weights ~6.4 GB).
    "tpu-llama-3b": ModelConfig(
        name="tpu-llama-3b", arch="llama", vocab_size=128256,
        hidden_size=3072, num_layers=28, num_heads=24, num_kv_heads=8,
        head_dim=128, intermediate_size=8192, max_position=8192,
        rope_theta=500000.0,
    ),
    "meta-llama/Llama-3-8B": ModelConfig(
        name="meta-llama/Llama-3-8B", arch="llama", vocab_size=128256,
        hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=8,
        head_dim=128, intermediate_size=14336, max_position=8192,
        rope_theta=500000.0,
    ),
    "meta-llama/Llama-3-70B": ModelConfig(
        name="meta-llama/Llama-3-70B", arch="llama", vocab_size=128256,
        hidden_size=8192, num_layers=80, num_heads=64, num_kv_heads=8,
        head_dim=128, intermediate_size=28672, max_position=8192,
        rope_theta=500000.0,
    ),
    "mistralai/Mistral-7B-v0.1": ModelConfig(
        name="mistralai/Mistral-7B-v0.1", arch="llama", vocab_size=32000,
        hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=8,
        head_dim=128, intermediate_size=14336, max_position=8192,
        rope_theta=10000.0,
    ),
    "mistralai/Mixtral-8x7B-v0.1": ModelConfig(
        name="mistralai/Mixtral-8x7B-v0.1", arch="mixtral", vocab_size=32000,
        hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=8,
        head_dim=128, intermediate_size=14336, max_position=8192,
        rope_theta=1000000.0, num_experts=8, experts_per_token=2,
    ),
}

_ALIASES = {
    "meta-llama/Meta-Llama-3-8B": "meta-llama/Llama-3-8B",
    "meta-llama/Meta-Llama-3-8B-Instruct": "meta-llama/Llama-3-8B",
    "meta-llama/Llama-3.1-8B-Instruct": "meta-llama/Llama-3-8B",
    "meta-llama/Meta-Llama-3-70B": "meta-llama/Llama-3-70B",
    "mistralai/Mixtral-8x7B-Instruct-v0.1": "mistralai/Mixtral-8x7B-v0.1",
}


def _from_hf_config_json(path: str, name: str) -> ModelConfig:
    """Build a ModelConfig from a local HuggingFace config.json."""
    with open(path) as f:
        cfg = json.load(f)
    model_type = cfg.get("model_type", "llama")
    arch = {"llama": "llama", "mistral": "llama", "mixtral": "mixtral",
            "opt": "opt"}.get(model_type, "llama")
    heads = cfg.get("num_attention_heads", 32)
    hidden = cfg.get("hidden_size", 4096)
    return ModelConfig(
        name=name,
        arch=arch,
        vocab_size=cfg.get("vocab_size", 32000),
        hidden_size=hidden,
        num_layers=cfg.get("num_hidden_layers", cfg.get("num_layers", 32)),
        num_heads=heads,
        num_kv_heads=cfg.get("num_key_value_heads", heads),
        # some configs carry an explicit null head_dim
        head_dim=cfg.get("head_dim") or hidden // heads,
        intermediate_size=cfg.get("intermediate_size", cfg.get("ffn_dim", 4 * hidden)),
        max_position=cfg.get("max_position_embeddings", 8192),
        rope_theta=cfg.get("rope_theta", 10000.0),
        rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
        tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        do_layer_norm_before=cfg.get("do_layer_norm_before", True),
        num_experts=cfg.get("num_local_experts", 0),
        experts_per_token=cfg.get("num_experts_per_tok", 2),
    )


def get_model_config(model: str) -> ModelConfig:
    """Resolve a model name or local path to an architecture config."""
    if os.path.isdir(model) and os.path.exists(os.path.join(model, "config.json")):
        return _from_hf_config_json(os.path.join(model, "config.json"), model)
    key = _ALIASES.get(model, model)
    if key in _PRESETS:
        return _PRESETS[key]
    raise ValueError(
        f"Unknown model {model!r}; known presets: {sorted(_PRESETS)} "
        f"(or pass a local checkpoint directory with config.json)"
    )
