"""Weight-only int8 quantization (per-output-channel, symmetric).

Serves the BASELINE model class on one 16 GB chip: an 8 B-parameter model
is ~16 GB in bf16 (does not fit next to KV + workspace) but ~9 GB with
int8 layer weights (embed/lm_head stay bf16 by default — quantizing them
disproportionately hurts output quality for ~1 GB more;
``quantize_embeddings=True`` reclaims it when HBM is the binding
constraint). The compute path stays bf16 on the MXU — each weight is
stored as ``int8`` plus a per-output-channel ``float32`` scale, and the
dequant (`w.astype(bf16) * scale`) fuses into the matmul's operand read
under XLA, so the HBM weight traffic (the decode bottleneck) halves too.

The reference reaches this class through vLLM's quantization support in
its CUDA images (``--quantization`` engine args in
``helm/templates/deployment-vllm-multi.yaml`` extraArgs); this is the
TPU-native equivalent at the engine layer.

Two entry points with matching semantics (identical up to one-ULP
rounding-tie flips between XLA's and numpy's division):
- :func:`quantize_tree` — traceable (jax.numpy); used inside the jitted
  init so a random-init 8 B model NEVER materializes fully in bf16 on
  device (each leaf quantizes as it is created, peak = one bf16 leaf).
- :func:`quantize_loaded` — numpy; used on host-loaded checkpoints so
  the device transfer ships int8, not bf16.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

# Weight leaves quantized for the llama family; everything else (norms,
# LoRA slots) stays bf16 — they are a rounding error of the total bytes.
_LLAMA_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

# Symmetric int8 range. 127 (not 128) keeps the scale exact for the max.
_QMAX = 127.0


def _quantize_jnp(w, reduce_axis: int):
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axis,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / _QMAX
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _quantize_np(w: np.ndarray, reduce_axis: int):
    w32 = np.asarray(w, np.float32)
    amax = np.max(np.abs(w32), axis=reduce_axis, keepdims=True)
    scale = np.maximum(amax, 1e-8) / _QMAX
    q = np.clip(np.round(w32 / scale), -_QMAX, _QMAX).astype(np.int8)
    return q, scale.astype(np.float32)


def _apply_tree(params: Dict, arch: str, quant,
                quantize_embeddings: bool) -> Dict:
    if arch != "llama":
        raise ValueError(
            f"int8 quantization is supported for the llama family "
            f"(got arch {arch!r})")
    out = dict(params)
    layers = dict(params["layers"])
    for name in _LLAMA_LAYER_KEYS:
        if name in layers:
            # [L, in, out] -> int8 [L, in, out] + scale [L, 1, out]
            q, s = quant(layers[name], -2)
            layers[name] = q
            layers[name + "_scale"] = s
    out["layers"] = layers
    # embed / lm_head stay bf16 by default: quantizing them hurts output
    # quality disproportionately (standard weight-only recipes exclude
    # them) while saving only ~1 GB of an 8 B model's bytes — the HBM win
    # is nearly unchanged without them.
    if quantize_embeddings:
        # embed [V, Hd]: per-ROW scales [V, 1] — correct for both the
        # lookup (dequant the gathered rows) and the tied head
        # (x @ embed.T scales per output/vocab channel).
        q, s = quant(params["embed"], -1)
        out["embed"] = q
        out["embed_scale"] = s
        if "lm_head" in params:
            q, s = quant(params["lm_head"], -2)  # [Hd, V] -> scale [1, V]
            out["lm_head"] = q
            out["lm_head_scale"] = s
    return out


def quantize_tree(params: Dict, arch: str, *,
                  quantize_embeddings: bool = False) -> Dict:
    """Traceable int8 quantization of a params pytree (use inside jit)."""
    return _apply_tree(params, arch, _quantize_jnp, quantize_embeddings)


def quantize_loaded(loaded: Dict, arch: str, *,
                    quantize_embeddings: bool = False) -> Dict:
    """Numpy twin of :func:`quantize_tree` for host-loaded checkpoints.
    Only quantizes the leaves the checkpoint actually carries."""
    if arch != "llama":
        raise ValueError(
            f"int8 quantization is supported for the llama family "
            f"(got arch {arch!r})")
    out = dict(loaded)
    if "layers" in loaded:
        layers = dict(loaded["layers"])
        for name in _LLAMA_LAYER_KEYS:
            if name in layers:
                q, s = _quantize_np(layers[name], -2)
                layers[name] = q
                layers[name + "_scale"] = s
        out["layers"] = layers
    if quantize_embeddings:
        if "embed" in loaded:
            q, s = _quantize_np(loaded["embed"], -1)
            out["embed"] = q
            out["embed_scale"] = s
        if "lm_head" in loaded:
            q, s = _quantize_np(loaded["lm_head"], -2)
            out["lm_head"] = q
            out["lm_head_scale"] = s
    return out
