"""Whisper-class speech-to-text model (JAX), TPU-first.

Fills the ASR slot of the reference stack: the reference serves Whisper
through dedicated vLLM pods labeled ``transcription`` and proxies multipart
audio from the router (``src/vllm_router/services/request_service/
request.py:513-689``); here the model itself is in the zoo and is served by
:mod:`production_stack_tpu.engine.asr_server`.

Architecture = standard Whisper encoder-decoder:

- log-mel frontend (numpy, stdlib-only audio path): 16 kHz PCM -> 80 mel
  bins, n_fft 400, hop 160, 30 s window -> 3000 frames.
- audio encoder: two 1-D convs (second stride 2) + GELU, sinusoidal
  positions, pre-LN transformer stack.
- text decoder: learned positions, causal self-attention + cross-attention
  over encoder states, tied embedding logits.

TPU notes: all shapes are static (audio is padded/trimmed to the 30 s
window before tracing; decode scores a fixed ``max_target_len`` buffer with
position masking), so the whole transcribe step jits once and reuses the
compiled program for every request. Matmuls run in bf16 on the MXU via the
param dtype; the mel frontend stays on host (numpy) where the byte
wrangling lives.

Weights are randomly initialized for named presets (zero-egress image —
see models/config.py) or loaded from a local HF checkpoint directory.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

SAMPLE_RATE = 16000
N_FFT = 400
HOP_LENGTH = 160
N_MELS = 80
CHUNK_SECONDS = 30
N_FRAMES = SAMPLE_RATE * CHUNK_SECONDS // HOP_LENGTH  # 3000


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    name: str = "tiny-whisper"
    vocab_size: int = 512           # ByteTokenizer-compatible default
    d_model: int = 64
    encoder_layers: int = 2
    decoder_layers: int = 2
    num_heads: int = 2
    max_target_len: int = 448
    n_mels: int = N_MELS
    n_audio_ctx: int = N_FRAMES // 2  # after stride-2 conv
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


WHISPER_PRESETS = {
    # Test-scale preset: exercises every code path in seconds on CPU.
    "tiny-whisper": WhisperConfig(),
    # openai/whisper-small card dimensions (12+12 layers, d_model 768).
    "whisper-small": WhisperConfig(
        name="whisper-small", vocab_size=51865, d_model=768,
        encoder_layers=12, decoder_layers=12, num_heads=12,
    ),
}


# --------------------------------------------------------------------- #
# Mel frontend (host-side numpy; no librosa/soundfile in the image)
# --------------------------------------------------------------------- #

def _mel_filterbank(n_mels: int = N_MELS, n_fft: int = N_FFT,
                    sr: int = SAMPLE_RATE) -> np.ndarray:
    """Slaney-scale triangular mel filterbank, (n_mels, n_fft//2+1) —
    linear below 1 kHz, log above, matching librosa / HF's
    WhisperFeatureExtractor so checkpoint inputs are bit-comparable."""
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / (200.0 / 3.0)  # 15.0
    logstep = math.log(6.4) / 27.0

    def hz_to_mel(f):
        f = np.asarray(f, dtype=np.float64)
        return np.where(
            f >= min_log_hz,
            min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
            f / (200.0 / 3.0),
        )

    def mel_to_hz(m):
        m = np.asarray(m, dtype=np.float64)
        return np.where(
            m >= min_log_mel,
            min_log_hz * np.exp(logstep * (m - min_log_mel)),
            m * (200.0 / 3.0),
        )

    fft_freqs = np.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_pts = mel_to_hz(np.linspace(hz_to_mel(0.0), hz_to_mel(sr / 2),
                                    n_mels + 2))
    fb = np.zeros((n_mels, len(fft_freqs)), dtype=np.float32)
    for i in range(n_mels):
        lo, ctr, hi = mel_pts[i], mel_pts[i + 1], mel_pts[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-8)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-8)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    # Area-normalize each filter.
    enorm = 2.0 / (mel_pts[2:] - mel_pts[:-2])
    fb *= enorm[:, None]
    return fb


_FILTERBANK: Optional[np.ndarray] = None


def log_mel_spectrogram(audio: np.ndarray) -> np.ndarray:
    """float32 PCM [-1, 1] -> (n_mels, N_FRAMES) log-mel features,
    padded/trimmed to the 30 s window (whisper's audio.py contract)."""
    global _FILTERBANK
    if _FILTERBANK is None:
        _FILTERBANK = _mel_filterbank()
    target = SAMPLE_RATE * CHUNK_SECONDS
    audio = np.asarray(audio, dtype=np.float32)[:target]
    if len(audio) < target:
        audio = np.pad(audio, (0, target - len(audio)))
    # Whisper's STFT contract is center=True: reflect-pad N_FFT//2 per side
    # so exactly N_FRAMES (3000) frames come out; without it the framing
    # yields 2998 and the stride-2 encoder conv misaligns with enc_pos.
    audio = np.pad(audio, (N_FFT // 2, N_FFT // 2), mode="reflect")
    window = np.hanning(N_FFT + 1)[:-1].astype(np.float32)
    n_frames = 1 + (len(audio) - N_FFT) // HOP_LENGTH
    idx = (np.arange(N_FFT)[None, :]
           + HOP_LENGTH * np.arange(n_frames)[:, None])
    frames = audio[idx] * window
    spec = np.abs(np.fft.rfft(frames, axis=-1)) ** 2  # (T, n_fft//2+1)
    mel = _FILTERBANK @ spec.T                        # (n_mels, T)
    log_mel = np.log10(np.maximum(mel, 1e-10))
    log_mel = np.maximum(log_mel, log_mel.max() - 8.0)
    log_mel = (log_mel + 4.0) / 4.0
    return log_mel[:, :N_FRAMES].astype(np.float32)


def decode_wav_bytes(data: bytes) -> np.ndarray:
    """WAV bytes -> mono float32 PCM at 16 kHz (stdlib ``wave`` only;
    non-16k inputs are linearly resampled)."""
    import io
    import wave

    with wave.open(io.BytesIO(data)) as w:
        n = w.getnframes()
        raw = w.readframes(n)
        width = w.getsampwidth()
        channels = w.getnchannels()
        rate = w.getframerate()
    if width == 2:
        pcm = np.frombuffer(raw, dtype="<i2").astype(np.float32) / 32768.0
    elif width == 1:
        pcm = (np.frombuffer(raw, dtype=np.uint8).astype(np.float32)
               - 128.0) / 128.0
    elif width == 4:
        pcm = (np.frombuffer(raw, dtype="<i4").astype(np.float32)
               / 2147483648.0)
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if channels > 1:
        pcm = pcm.reshape(-1, channels).mean(axis=1)
    if rate != SAMPLE_RATE and len(pcm):
        t_new = np.linspace(0, len(pcm) - 1,
                            int(len(pcm) * SAMPLE_RATE / rate))
        pcm = np.interp(t_new, np.arange(len(pcm)), pcm).astype(np.float32)
    return pcm


# --------------------------------------------------------------------- #
# Model
# --------------------------------------------------------------------- #

def _sinusoids(length: int, channels: int) -> np.ndarray:
    log_timescale = math.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)],
                          axis=1).astype(np.float32)


def _dense(key, shape, dtype, scale=0.02):
    return (jax.random.normal(key, shape) * scale).astype(dtype)


def init_whisper_params(cfg: WhisperConfig, seed: int = 0) -> Dict:
    dt = jnp.dtype(cfg.dtype)
    key = jax.random.PRNGKey(seed)
    ks = iter(jax.random.split(key, 16 + 12 * (cfg.encoder_layers
                                               + cfg.decoder_layers)))
    d = cfg.d_model

    def block():
        # q/v/out projections carry biases, k does not — HF Whisper's exact
        # parameterization, so checkpoints load without residue.
        return {
            "ln1_g": jnp.ones((d,), dt), "ln1_b": jnp.zeros((d,), dt),
            "q": _dense(next(ks), (d, d), dt), "q_b": jnp.zeros((d,), dt),
            "k": _dense(next(ks), (d, d), dt),
            "v": _dense(next(ks), (d, d), dt), "v_b": jnp.zeros((d,), dt),
            "o": _dense(next(ks), (d, d), dt), "o_b": jnp.zeros((d,), dt),
            "ln2_g": jnp.ones((d,), dt), "ln2_b": jnp.zeros((d,), dt),
            "fc1": _dense(next(ks), (d, 4 * d), dt),
            "fc1_b": jnp.zeros((4 * d,), dt),
            "fc2": _dense(next(ks), (4 * d, d), dt),
            "fc2_b": jnp.zeros((d,), dt),
        }

    def cross():
        return {
            "lnx_g": jnp.ones((d,), dt), "lnx_b": jnp.zeros((d,), dt),
            "xq": _dense(next(ks), (d, d), dt), "xq_b": jnp.zeros((d,), dt),
            "xk": _dense(next(ks), (d, d), dt),
            "xv": _dense(next(ks), (d, d), dt), "xv_b": jnp.zeros((d,), dt),
            "xo": _dense(next(ks), (d, d), dt), "xo_b": jnp.zeros((d,), dt),
        }

    params = {
        "conv1": _dense(next(ks), (3, cfg.n_mels, d), dt),
        "conv1_b": jnp.zeros((d,), dt),
        "conv2": _dense(next(ks), (3, d, d), dt),
        "conv2_b": jnp.zeros((d,), dt),
        "enc_pos": jnp.asarray(_sinusoids(cfg.n_audio_ctx, d), dt),
        "enc_blocks": [block() for _ in range(cfg.encoder_layers)],
        "enc_ln_g": jnp.ones((d,), dt), "enc_ln_b": jnp.zeros((d,), dt),
        "tok_emb": _dense(next(ks), (cfg.vocab_size, d), dt),
        "dec_pos": _dense(next(ks), (cfg.max_target_len, d), dt),
        "dec_blocks": [{**block(), **cross()}
                       for _ in range(cfg.decoder_layers)],
        "dec_ln_g": jnp.ones((d,), dt), "dec_ln_b": jnp.zeros((d,), dt),
    }
    return params


def _ln(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g + b


def _mha(q, k, v, heads: int, mask=None):
    """(Tq,d),(Tk,d),(Tk,d) -> (Tq,d) multi-head attention."""
    tq, d = q.shape
    tk = k.shape[0]
    hd = d // heads
    qh = q.reshape(tq, heads, hd).transpose(1, 0, 2)
    kh = k.reshape(tk, heads, hd).transpose(1, 0, 2)
    vh = v.reshape(tk, heads, hd).transpose(1, 0, 2)
    scores = jnp.einsum("hqd,hkd->hqk", qh, kh).astype(jnp.float32)
    scores = scores / math.sqrt(hd)
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("hqk,hkd->hqd", probs, vh)
    return out.transpose(1, 0, 2).reshape(tq, d)


def _self_block(x, blk, heads, mask=None):
    h = _ln(x, blk["ln1_g"], blk["ln1_b"])
    att = _mha(h @ blk["q"] + blk["q_b"], h @ blk["k"],
               h @ blk["v"] + blk["v_b"], heads, mask)
    x = x + att @ blk["o"] + blk["o_b"]
    h = _ln(x, blk["ln2_g"], blk["ln2_b"])
    x = x + (jax.nn.gelu(h @ blk["fc1"] + blk["fc1_b"])
             @ blk["fc2"] + blk["fc2_b"])
    return x


def encode_audio(params: Dict, cfg: WhisperConfig,
                 mel: jnp.ndarray) -> jnp.ndarray:
    """(n_mels, N_FRAMES) log-mel -> (n_audio_ctx, d_model) states."""
    x = mel.T.astype(params["conv1"].dtype)  # (T, n_mels)
    # conv1: k=3 stride 1; conv2: k=3 stride 2. Explicit (1, 1) padding —
    # torch's padding=1 — NOT "SAME": with stride 2, SAME pads (0, 1) and
    # shifts every output frame one sample against HF checkpoints.
    x = jax.lax.conv_general_dilated(
        x[None], params["conv1"], window_strides=(1,), padding=[(1, 1)],
        dimension_numbers=("NWC", "WIO", "NWC"))[0] + params["conv1_b"]
    x = jax.nn.gelu(x)
    x = jax.lax.conv_general_dilated(
        x[None], params["conv2"], window_strides=(2,), padding=[(1, 1)],
        dimension_numbers=("NWC", "WIO", "NWC"))[0] + params["conv2_b"]
    x = jax.nn.gelu(x)
    x = x + params["enc_pos"]
    for blk in params["enc_blocks"]:
        x = _self_block(x, blk, cfg.num_heads)
    return _ln(x, params["enc_ln_g"], params["enc_ln_b"])


def decoder_logits(params: Dict, cfg: WhisperConfig, tokens: jnp.ndarray,
                   n_tokens: jnp.ndarray,
                   enc: jnp.ndarray) -> jnp.ndarray:
    """Fixed-size decode: ``tokens`` is the (max_target_len,) buffer with
    ``n_tokens`` valid entries; returns logits at the last valid position.

    Static shapes keep this a single compiled XLA program per model — the
    greedy loop re-invokes it with an updated buffer (O(n^2) attention,
    bounded by max_target_len=448; fine for the 30 s ASR window).
    """
    t = cfg.max_target_len
    x = params["tok_emb"][tokens] + params["dec_pos"]
    positions = jnp.arange(t)
    valid = positions < n_tokens
    causal = (positions[None, :] <= positions[:, None]) & valid[None, :]
    for blk in params["dec_blocks"]:
        h = _ln(x, blk["ln1_g"], blk["ln1_b"])
        att = _mha(h @ blk["q"] + blk["q_b"], h @ blk["k"],
                   h @ blk["v"] + blk["v_b"], cfg.num_heads, causal[None])
        x = x + att @ blk["o"] + blk["o_b"]
        h = _ln(x, blk["lnx_g"], blk["lnx_b"])
        xatt = _mha(h @ blk["xq"] + blk["xq_b"], enc @ blk["xk"],
                    enc @ blk["xv"] + blk["xv_b"], cfg.num_heads)
        x = x + xatt @ blk["xo"] + blk["xo_b"]
        h = _ln(x, blk["ln2_g"], blk["ln2_b"])
        x = x + (jax.nn.gelu(h @ blk["fc1"] + blk["fc1_b"])
                 @ blk["fc2"] + blk["fc2_b"])
    x = _ln(x, params["dec_ln_g"], params["dec_ln_b"])
    last = x[n_tokens - 1]
    return (last @ params["tok_emb"].T.astype(last.dtype)).astype(
        jnp.float32)


class WhisperModel:
    """Greedy transcriber wrapping the pure functions above with jit.

    ``params`` overrides random init (checkpoint loading lives in
    :func:`production_stack_tpu.models.weights.load_whisper_checkpoint`).
    """

    def __init__(self, cfg: WhisperConfig, seed: int = 0,
                 params: Optional[Dict] = None):
        self.cfg = cfg
        self.params = (params if params is not None
                       else init_whisper_params(cfg, seed))
        self._encode = jax.jit(
            lambda mel: encode_audio(self.params, cfg, mel))
        # mask: [vocab] additive logits mask (0 / -inf) — how suppression
        # works in HF's SuppressTokensLogitsProcessor: masked BEFORE the
        # argmax, so a suppressed token is never selected or fed back.
        self._step = jax.jit(
            lambda tokens, n, enc, mask: jnp.argmax(
                decoder_logits(self.params, cfg, tokens, n, enc) + mask))

    def transcribe_tokens(self, audio: np.ndarray, sot, eot: int,
                          max_tokens: int = 64,
                          suppress: Tuple[int, ...] = (),
                          begin_suppress: Tuple[int, ...] = ()) -> List[int]:
        """float32 PCM -> generated token ids (greedy, until EOT).

        ``sot`` may be a single id or a forced prefix sequence (HF
        checkpoints force [startoftranscript, language, task,
        notimestamps]); the prefix is not part of the returned ids.
        ``suppress`` masks logits at every step; ``begin_suppress`` only at
        the first generated position (HF semantics — e.g. EOT can't be the
        whole transcript)."""
        mel = jnp.asarray(log_mel_spectrogram(audio))
        enc = self._encode(mel)
        prefix = [int(sot)] if isinstance(sot, int) else [int(t) for t in sot]
        buf = np.zeros((self.cfg.max_target_len,), dtype=np.int32)
        buf[:len(prefix)] = prefix
        n = len(prefix)
        out: List[int] = []
        mask = np.zeros((self.cfg.vocab_size,), np.float32)
        for t in suppress:
            if 0 <= t < self.cfg.vocab_size:
                mask[t] = -np.inf
        begin_mask = mask.copy()
        for t in begin_suppress:
            if 0 <= t < self.cfg.vocab_size:
                begin_mask[t] = -np.inf
        limit = min(max_tokens, self.cfg.max_target_len - n)
        for i in range(limit):
            m = begin_mask if i == 0 else mask
            nxt = int(self._step(
                jnp.asarray(buf), jnp.int32(n), enc, jnp.asarray(m)))
            if nxt == eot:
                break
            out.append(nxt)
            buf[n] = nxt
            n += 1
        return out


def whisper_config_from_hf(path: str) -> WhisperConfig:
    """Build a WhisperConfig from a local HF checkpoint's config.json."""
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        cfg = json.load(f)
    if cfg.get("model_type") != "whisper":
        raise ValueError(f"{path} is not a whisper checkpoint")
    return WhisperConfig(
        name=path,
        vocab_size=cfg.get("vocab_size", 51865),
        d_model=cfg.get("d_model", 768),
        encoder_layers=cfg.get("encoder_layers", 12),
        decoder_layers=cfg.get("decoder_layers", 12),
        num_heads=cfg.get("encoder_attention_heads", 12),
        max_target_len=cfg.get("max_target_positions", 448),
        n_mels=cfg.get("num_mel_bins", N_MELS),
        n_audio_ctx=cfg.get("max_source_positions", N_FRAMES // 2),
    )


def get_whisper_config(model: str) -> WhisperConfig:
    import os

    if os.path.isdir(model) and os.path.exists(
            os.path.join(model, "config.json")):
        return whisper_config_from_hf(model)
    key = model.split("/")[-1].lower()
    aliases = {"whisper-small": "whisper-small",
               "whisper-tiny": "tiny-whisper",
               "tiny-whisper": "tiny-whisper"}
    if key in aliases:
        return WHISPER_PRESETS[aliases[key]]
    raise ValueError(
        f"Unknown whisper model {model!r}; presets: "
        f"{sorted(WHISPER_PRESETS)}")


def is_whisper_model(model: str) -> bool:
    import json
    import os

    cfg_path = os.path.join(model, "config.json")
    if os.path.isdir(model) and os.path.exists(cfg_path):
        try:
            with open(cfg_path) as f:
                return json.load(f).get("model_type") == "whisper"
        except (OSError, ValueError):
            return False
    return "whisper" in model.split("/")[-1].lower()
