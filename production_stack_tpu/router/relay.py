"""Relay pump tier: stream committed responses off the event loop.

``BENCH_SATURATION_r13.json`` convicted the streaming relay — the
per-chunk ``await response.write()`` path through aiohttp's payload
writer — as the router's throughput ceiling (~50 of ~80 attributed
on-loop seconds at the knee), and ``BENCH_SATURATION_r16.json`` proved
SO_REUSEPORT workers alone cannot buy it back on a small host. This
module takes that copy off the loop: once a streamed response is
COMMITTED (headers sent, first chunk delivered through the normal
aiohttp path, so the PR 6 failover window is closed), the handler hands
the client socket to a small pool of pump threads that move the
remaining upstream chunks with direct socket I/O. The event loop keeps
doing what only it can do — upstream reads, failover, deadlines, SLO
bookkeeping — and stops burning CPU on byte shoveling:

- The pump duplicates the client socket fd (``sock.dup()``: same open
  file description, so kernel-level ordering with the bytes aiohttp
  already buffered is preserved once the transport's write buffer is
  drained — ``try_handoff`` waits for exactly that before duping).
  The dup shares ``O_NONBLOCK`` with the asyncio transport, so each
  pump thread runs a tiny ``selectors`` write loop instead of blocking
  sends; the GIL is released inside ``select()`` and ``send()`` either
  way.
- Chunk payloads cross loop→pump over a plain ``deque`` (thread-safe
  appends/pops); the pump COALESCES every queued payload into one wire
  buffer per ``send()`` — replicating aiohttp's chunked framing
  (``<hex>CRLF payload CRLF``, terminal ``0CRLFCRLF``) — so N small SSE
  frames cost one syscall instead of N writer round-trips.
- Per-chunk write-completion timestamps flow pump→loop over a lock-free
  SPSC deque (``RelayJob.write_timestamps``); byte/chunk totals are
  settled into the prometheus counters once per request on the loop.
  SLO TTFT/inter-token classification keeps using the loop-side
  receive timestamps taken at feed time — the same statement position
  the flag-off path samples at, so classification inputs are identical
  by construction.
- Feeding the pump from the handler's ``async for`` still pays the
  per-chunk coroutine resumption chain (upstream ``readany`` waiter →
  ``process_request`` generator → failover wrapper → handler), which on
  a 1-CPU host costs more loop time than the socket write it replaced.
  :class:`StreamTap` removes that too: once a job exists, the upstream
  response's ``StreamReader`` is retargeted (``__class__`` swap onto a
  zero-``__slots__`` subclass — the reader's own slots forbid instance
  method overrides) so aiohttp's ``data_received`` → parser path calls
  ``tap.on_data`` directly with each decoded payload. The tap does the
  minimal loop-side bookkeeping (SLO stamp, QoS body buffer, engine
  token accounting via a caller-supplied callback) and ``feed_nowait``s
  the pump; the handler PARKS on :meth:`RelayJob.wait_done` with zero
  per-chunk resumptions. Backpressure maps HIGH_WATER onto the upstream
  protocol's ``pause_reading`` instead of an awaited drain future, and
  the fault-tolerance inter-chunk deadline moves pump-side (the select
  loop watches feed progress and fails the job with the same
  ``asyncio.TimeoutError`` the on-loop ``wait_for`` raised).
- A pump-detected client disconnect (EPIPE/ECONNRESET on send) is
  re-raised on the loop from ``feed()``/``finish()`` as aiohttp's
  ``ClientConnectionResetError`` — the exact class the flag-off
  ``response.write()`` raises — so the existing except/finally path
  classifies ``client_abort`` and releases the QoS lease unchanged.
  An upstream fault (inter-chunk deadline, engine crash) aborts the
  job: the dup closes without the terminal chunk and the handler's
  raise tears the connection down exactly as before.

Handoff is strictly best-effort: TLS transports, missing sockets, or a
write buffer that never drains fall back to the on-loop relay (counted
in ``vllm_router:relay_handoff_failures_total``), and with
``--relay-off-loop`` unset this module is never constructed — the
request path is byte-identical to a build that predates it.
"""

from __future__ import annotations

import asyncio
import selectors
import socket
import threading
import time
from collections import deque
from typing import Optional

import aiohttp
import aiohttp.streams

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

#: The class feed()/finish() raise when the pump saw the client go away.
#: aiohttp's own response.write() raises exactly this on a closed client
#: transport, so the handler's classification path needs no new branch.
CLIENT_RESET = aiohttp.ClientConnectionResetError

#: Loop-side backpressure: feed() awaits once this many payload bytes
#: are queued to a job, resuming below the low watermark — the pump-tier
#: stand-in for the transport write-buffer backpressure response.write()
#: exerted (the client's read pace still bounds router memory).
HIGH_WATER = 256 * 1024
LOW_WATER = 64 * 1024

#: Per-send coalescing cap: payloads are concatenated into one wire
#: buffer up to this size (send() usually takes the whole thing in one
#: syscall on loopback/LAN sockets).
COALESCE_MAX = 256 * 1024

#: How long try_handoff waits for aiohttp's transport buffer (headers +
#: first chunk) to reach the kernel before giving up on the handoff.
DRAIN_WAIT_S = 0.25


def seal_response(response) -> None:
    """Mark an aiohttp StreamResponse as finished after the pump wrote
    the body (terminal chunk included) through the dup'd socket.
    ``write_eof()`` — ours and the one ``finish_response`` always calls —
    becomes a no-op, and keep-alive proceeds normally: every byte the
    pump sent is already in the kernel buffer, in order, ahead of
    whatever the transport writes next."""
    response._eof_sent = True


class StreamTap:
    """Loop-side sink for an upstream response's decoded payloads.

    Installed over the aiohttp client ``StreamReader`` once a relay job
    exists (detached mode): the parser's ``feed_data`` lands here
    instead of buffering for a reader that no longer exists. Every hook
    runs ON the event loop (inside ``data_received``) — single-threaded
    with the handler, which is parked in :meth:`RelayJob.wait_done`.
    """

    __slots__ = ("job", "on_chunk", "protocol", "chunks",
                 "last_chunk_unix", "bytes")

    def __init__(self, job: "RelayJob", on_chunk=None, protocol=None):
        self.job = job
        # Caller-supplied loop-side bookkeeping: (payload, unix_now) —
        # SLO stamps, QoS body buffer, engine token accounting.
        self.on_chunk = on_chunk
        # The upstream connection's protocol: HIGH_WATER backpressure
        # maps onto pause_reading()/resume_reading() because a sync hook
        # cannot await the drain future.
        self.protocol = protocol
        self.chunks = 0
        self.last_chunk_unix = 0.0
        self.bytes = 0

    def on_data(self, data: bytes) -> None:
        job = self.job
        if job._completed or job._failed is not None:
            # Client already gone / job torn down: drop — the parked
            # handler is being woken to unwind and close the upstream.
            return
        now = time.time()
        self.chunks += 1
        self.bytes += len(data)
        self.last_chunk_unix = now
        cb = self.on_chunk
        if cb is not None:
            try:
                cb(data, now)
            except Exception:  # pragma: no cover - bookkeeping only
                logger.exception("relay tap bookkeeping failed")
        try:
            fut = job.feed_nowait(data)
        except CLIENT_RESET:
            return  # wait_done() surfaces it to the handler
        proto = self.protocol
        if fut is not None and proto is not None:
            try:
                proto.pause_reading()
            except Exception:  # pragma: no cover - transport torn down
                return
            fut.add_done_callback(lambda _f: self._resume())

    def _resume(self) -> None:
        try:
            self.protocol.resume_reading()
        except Exception:  # pragma: no cover - transport torn down
            pass

    def on_eof(self) -> None:
        self.job.finish_nowait()

    def on_error(self, exc: BaseException) -> None:
        self.job.fail(exc)


#: Live taps keyed by id(StreamReader). Entries are removed by the eof/
#: exception hooks and by remove_tap() in the detach path's finally, so
#: a reader never outlives its entry (id() reuse is therefore safe).
_TAPS: dict = {}


class _TapStream(aiohttp.streams.StreamReader):
    """Zero-slot subclass a live upstream ``StreamReader`` is retargeted
    to (``__class__`` assignment — layout-compatible because this adds
    no slots). The base class bookkeeping still runs on eof/exception so
    aiohttp's connection-reuse checks (``is_eof``) stay truthful; data
    itself bypasses the buffer entirely."""

    __slots__ = ()

    def feed_data(self, data, size=0):  # noqa: D102 - hot hook
        tap = _TAPS.get(id(self))
        if tap is None:  # pragma: no cover - racing uninstall
            return aiohttp.streams.StreamReader.feed_data(self, data, size)
        tap.on_data(data)

    def feed_eof(self):
        tap = _TAPS.pop(id(self), None)
        aiohttp.streams.StreamReader.feed_eof(self)
        if tap is not None:
            tap.on_eof()

    def set_exception(self, exc, exc_cause=None):
        tap = _TAPS.pop(id(self), None)
        try:
            aiohttp.streams.StreamReader.set_exception(self, exc, exc_cause)
        except TypeError:  # pragma: no cover - older aiohttp signature
            aiohttp.streams.StreamReader.set_exception(self, exc)
        if tap is not None:
            tap.on_error(exc)


def install_tap(content, tap: StreamTap) -> bool:
    """Retarget a live upstream ``StreamReader`` onto the tap. False if
    the object is not the plain StreamReader this build understands
    (the caller then stays on the per-chunk feed path)."""
    if type(content) is not aiohttp.streams.StreamReader:
        return False
    _TAPS[id(content)] = tap
    try:
        content.__class__ = _TapStream
    except TypeError:  # pragma: no cover - layout mismatch
        _TAPS.pop(id(content), None)
        return False
    return True


def remove_tap(content) -> None:
    """Idempotent uninstall (detach path's finally)."""
    _TAPS.pop(id(content), None)
    if type(content) is _TapStream:
        content.__class__ = aiohttp.streams.StreamReader


class RelayJob:
    """One committed response being pumped. Loop-side API: ``feed()``
    per chunk, then ``finish()`` (clean EOF) or ``abort()`` (upstream
    fault); ``ensure_closed()`` + ``settle()`` in the handler's finally.
    Everything else runs on the owning pump thread."""

    __slots__ = (
        "server_url", "_sock", "_chunked", "_loop", "_thread",
        "_lock", "_pending", "_pending_bytes", "_finishing", "_aborted",
        "_completed", "_failed", "_terminal_queued", "_done",
        "_drain_fut", "_wire", "_wire_sent", "_wire_marks", "_marks_done",
        "_registered", "_settled", "_scheduled", "write_timestamps",
        "bytes_total", "chunks_total", "_seq",
        "deadline_s", "last_activity",
    )

    def __init__(self, sock: socket.socket, chunked: bool,
                 loop: asyncio.AbstractEventLoop, server_url: str):
        self.server_url = server_url
        self._sock = sock
        self._chunked = chunked
        self._loop = loop
        self._thread: Optional["_PumpThread"] = None
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self._pending_bytes = 0
        self._finishing = False
        self._aborted = False
        self._completed = False
        self._failed: Optional[BaseException] = None
        self._terminal_queued = False
        self._done = asyncio.Event()
        self._drain_fut: Optional[asyncio.Future] = None
        # Pump-side send cursor over the current coalesced wire buffer.
        self._wire = b""
        self._wire_sent = 0
        self._wire_marks: list = []  # (end_offset, payload_len)
        self._marks_done = 0
        self._registered = False
        self._settled = False
        # True while the pump owes this job a service pass. Guards the
        # waker: feeding an already-scheduled job is a pure lock+append
        # (no syscall), which is what makes the loop-side cost of a
        # chunk cheaper than the aiohttp write it replaces.
        self._scheduled = False
        # Lock-free SPSC feedback channel (pump appends, loop reads):
        # (chunk_seq, unix_time) per payload fully handed to the kernel.
        self.write_timestamps: deque = deque(maxlen=4096)
        self.bytes_total = 0
        self.chunks_total = 0
        self._seq = 0
        # Pump-enforced inter-chunk deadline (detached mode only): if no
        # feed arrives within deadline_s the pump fails the job with the
        # same asyncio.TimeoutError the on-loop wait_for() raised.
        self.deadline_s: Optional[float] = None
        self.last_activity = time.monotonic()

    # -- loop-side API -------------------------------------------------

    @property
    def completed(self) -> bool:
        return self._completed

    @property
    def failed(self) -> bool:
        return self._failed is not None

    def _raise_failed(self) -> None:
        err = self._failed
        if isinstance(err, (asyncio.TimeoutError, aiohttp.ClientError)):
            # Typed upstream faults (pump-side inter-chunk deadline,
            # upstream connection errors recorded via fail()) keep their
            # class so the handler's except arm classifies them exactly
            # as the on-loop path would ("failed", not "client_abort").
            raise err
        raise CLIENT_RESET(
            f"client transport closed under the relay pump: {err}"
        ) from err

    def feed_nowait(self, payload: bytes) -> Optional[asyncio.Future]:
        """Queue one upstream chunk for the pump; the per-chunk hot
        path. Returns None (common case) or a drain future the caller
        must await (HIGH_WATER backpressure). Raises the same
        ``ClientConnectionResetError`` ``response.write()`` would if the
        pump already saw the client disconnect."""
        if self._failed is not None:
            self._raise_failed()
        self.last_activity = time.monotonic()
        with self._lock:
            self._pending.append(payload)
            self._pending_bytes += len(payload)
            backlog = self._pending_bytes
            need_wake = not self._scheduled
            self._scheduled = True
        if need_wake:
            self._thread.notify(self)
        if backlog >= HIGH_WATER and not self._completed:
            fut = self._loop.create_future()
            self._drain_fut = fut
            # Unconditional wake: the pump must observe the future even
            # if it drained the backlog between our append and here.
            self._thread.notify(self)
            return fut
        return None

    async def feed(self, payload: bytes) -> None:
        """Awaitable wrapper over :meth:`feed_nowait` (blocks only at
        the high watermark)."""
        fut = self.feed_nowait(payload)
        if fut is not None:
            await fut
            if self._failed is not None:
                self._raise_failed()

    def finish_nowait(self) -> None:
        """Signal clean EOF without waiting (StreamTap's eof hook —
        the parked handler observes completion via wait_done())."""
        self._finishing = True
        self._thread.notify(self)

    async def finish(self) -> None:
        """Signal clean EOF, wait for the pump to flush everything
        (terminal chunk included), re-raise a pump-side disconnect."""
        self.finish_nowait()
        await self._done.wait()
        if self._failed is not None:
            self._raise_failed()

    async def wait_done(self) -> None:
        """Park until the pump completes the job (clean flush, client
        disconnect, upstream fail(), or deadline breach), then re-raise
        the job's failure with its original class. The detached-mode
        replacement for the per-chunk feed loop."""
        await self._done.wait()
        if self._failed is not None:
            self._raise_failed()

    def fail(self, exc: BaseException) -> None:
        """Record an upstream fault (StreamTap's set_exception hook):
        stop pumping and close the dup WITHOUT the terminal chunk, and
        make wait_done() raise ``exc`` (same class the on-loop read
        would have raised)."""
        if self._completed:
            return
        if self._failed is None:
            self._failed = exc
        self._aborted = True
        self._thread.notify(self)

    def abort(self) -> None:
        """Upstream fault: stop pumping and close the dup WITHOUT the
        terminal chunk — the client sees the same truncated stream the
        on-loop path produces when the handler raises mid-body."""
        if self._completed:
            return
        self._aborted = True
        self._thread.notify(self)

    def ensure_closed(self) -> None:
        """Finally-path safety net: abort if the pump is still running
        (handler unwound via an exception or cancellation)."""
        if not self._completed:
            self.abort()

    def settle(self) -> None:
        """Account the job's totals into the prometheus counters, once.
        Loop-side, from the handler's finally."""
        if self._settled:
            return
        self._settled = True
        from production_stack_tpu.router import metrics as router_metrics

        if self.bytes_total:
            router_metrics.relay_bytes.labels(
                server=self.server_url).inc(self.bytes_total)
        if self.chunks_total:
            router_metrics.relay_chunks.labels(
                server=self.server_url).inc(self.chunks_total)

    # -- pump-side machinery (owning thread only) ----------------------

    def _queued_bytes(self) -> int:
        with self._lock:
            return self._pending_bytes + (len(self._wire) - self._wire_sent)

    def _try_sleep(self) -> bool:
        """Pump-side: atomically go quiet (clear the scheduled flag) if
        there is truly nothing left to do. False means a feed, finish,
        or abort raced in — the service loop must take another pass
        (the racing caller saw ``_scheduled`` still True and skipped
        the waker, so this pass is its only wakeup)."""
        with self._lock:
            if self._pending or self._finishing or self._aborted:
                return False
            self._scheduled = False
            return True

    def _refill_wire(self) -> bool:
        """Coalesce queued payloads (and the terminal chunk at EOF) into
        one wire buffer. True if there are bytes to send."""
        if self._wire_sent < len(self._wire):
            return True
        parts: list = []
        marks: list = []
        size = 0
        with self._lock:
            while self._pending and size < COALESCE_MAX:
                payload = self._pending.popleft()
                self._pending_bytes -= len(payload)
                if self._chunked:
                    head = b"%x\r\n" % len(payload)
                    parts += (head, payload, b"\r\n")
                    size += len(head) + len(payload) + 2
                else:
                    parts.append(payload)
                    size += len(payload)
                marks.append((size, len(payload)))
            drained = not self._pending
        if (self._finishing and drained and self._chunked
                and not self._terminal_queued):
            parts.append(b"0\r\n\r\n")
            size += 5
            self._terminal_queued = True
        if not parts:
            return False
        self._wire = b"".join(parts)
        self._wire_sent = 0
        self._wire_marks = marks
        self._marks_done = 0
        return True

    def _note_progress(self) -> None:
        now = time.time()
        while self._marks_done < len(self._wire_marks):
            end, payload_len = self._wire_marks[self._marks_done]
            if end > self._wire_sent:
                break
            self._marks_done += 1
            self._seq += 1
            self.bytes_total += payload_len
            self.chunks_total += 1
            self.write_timestamps.append((self._seq, now))

    def _release_waiters(self) -> None:
        fut = self._drain_fut
        if fut is not None and (
                self._completed or self._queued_bytes() < LOW_WATER):
            self._drain_fut = None
            self._call_on_loop(lambda: fut.done() or fut.set_result(None))

    def _call_on_loop(self, fn) -> None:
        try:
            self._loop.call_soon_threadsafe(fn)
        except RuntimeError:
            pass  # loop already closed (teardown race): nothing to wake

    def _complete(self) -> None:
        self._completed = True
        try:
            self._sock.close()
        except OSError:
            pass
        self._release_waiters()
        self._call_on_loop(self._done.set)


class _PumpThread(threading.Thread):
    """One pump worker: a selectors write loop over its jobs' dup'd
    client sockets plus a socketpair waker the loop pokes on feed/
    finish/abort."""

    def __init__(self, name: str):
        super().__init__(daemon=True, name=name)
        self.selector = selectors.DefaultSelector()
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._waker_w.setblocking(False)
        self.selector.register(self._waker_r, selectors.EVENT_READ, None)
        self._dirty: deque = deque()
        self._jobs: set = set()
        self._stopping = False

    # Called from the event-loop thread.
    def notify(self, job: RelayJob) -> None:
        self._dirty.append(job)
        self.wake()

    def wake(self) -> None:
        try:
            self._waker_w.send(b"\x01")
        except (BlockingIOError, OSError):
            pass  # already signaled / tearing down

    def stop(self) -> None:
        self._stopping = True
        self.wake()

    def job_count(self) -> int:
        return len(self._jobs)

    def queued_bytes(self) -> int:
        return sum(job._queued_bytes() for job in list(self._jobs))

    def _deadline_sweep(self) -> float:
        """Fail jobs whose pump-enforced inter-chunk deadline lapsed and
        return the select timeout that observes the nearest remaining
        deadline (0.5s idle cadence otherwise)."""
        timeout = 0.5
        now = time.monotonic()
        for job in list(self._jobs):
            deadline = job.deadline_s
            if not deadline or job._finishing or job._aborted \
                    or job._completed:
                continue
            age = now - job.last_activity
            if age >= deadline:
                self._drop(job, error=asyncio.TimeoutError(
                    f"no upstream chunk within {deadline}s "
                    f"(relay pump inter-chunk deadline)"))
            else:
                timeout = min(timeout, max(0.02, deadline - age))
        return timeout

    def run(self) -> None:
        while True:
            events = self.selector.select(timeout=self._deadline_sweep())
            if self._stopping:
                break
            ready = []
            for key, _mask in events:
                if key.data is None:
                    try:
                        while self._waker_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                else:
                    ready.append(key.data)
            while True:
                try:
                    job = self._dirty.popleft()
                except IndexError:
                    break
                self._jobs.add(job)
                if job not in ready:
                    ready.append(job)
            for job in ready:
                try:
                    self._service(job)
                except Exception:  # pragma: no cover - never kill a pump
                    logger.exception("relay pump job failed")
                    self._drop(job, error=OSError("pump internal error"))
        # Teardown: abort whatever is left so no handler waits forever.
        for job in list(self._jobs):
            self._drop(job, error=OSError("relay pump stopped"))
        try:
            self.selector.unregister(self._waker_r)
        except (KeyError, ValueError):
            pass
        self.selector.close()
        self._waker_r.close()
        self._waker_w.close()

    def _register(self, job: RelayJob) -> None:
        if not job._registered:
            try:
                self.selector.register(
                    job._sock, selectors.EVENT_WRITE, job)
                job._registered = True
            except (KeyError, ValueError, OSError):
                pass

    def _unregister(self, job: RelayJob) -> None:
        if job._registered:
            job._registered = False
            try:
                self.selector.unregister(job._sock)
            except (KeyError, ValueError, OSError):
                pass

    def _drop(self, job: RelayJob, error: Optional[BaseException] = None
              ) -> None:
        self._unregister(job)
        self._jobs.discard(job)
        if not job._completed:
            if error is not None and job._failed is None \
                    and not job._aborted:
                job._failed = error
            job._complete()

    def _service(self, job: RelayJob) -> None:
        if job._completed:
            self._jobs.discard(job)
            job._release_waiters()
            return
        while True:
            if job._aborted:
                self._drop(job)
                return
            if not job._refill_wire():
                # Nothing sendable right now. Done only at clean EOF
                # with everything flushed (terminal chunk included for
                # chunked bodies).
                if job._finishing and (
                        job._terminal_queued or not job._chunked):
                    self._drop(job)
                    return
                if job._try_sleep():
                    self._unregister(job)
                    job._release_waiters()
                    return
                continue
            view = memoryview(job._wire)[job._wire_sent:]
            try:
                sent = job._sock.send(view)
            except (BlockingIOError, InterruptedError):
                self._register(job)
                job._release_waiters()
                return
            except OSError as e:
                # EPIPE/ECONNRESET: the client went away mid-stream.
                self._drop(job, error=e)
                return
            job._wire_sent += sent
            job._note_progress()
            job._release_waiters()


class RelayPump:
    """The pump pool (--relay-off-loop / --relay-pump-threads). One
    instance per router process; jobs are assigned round-robin."""

    def __init__(self, threads: int = 2, name: str = "router"):
        self.thread_count = max(1, int(threads))
        self._name = name
        self._threads: list = []
        self._rr = 0
        self._started = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._threads = [
            _PumpThread(f"relay-pump-{self._name}-{i}")
            for i in range(self.thread_count)
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        for t in self._threads:
            t.stop()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        self._started = False

    # -- introspection (scrape-time mirror) ----------------------------

    def stats(self) -> dict:
        threads = [t for t in self._threads if t.is_alive()]
        return {
            "active_pumps": len(threads),
            "queue_depth": sum(t.job_count() for t in threads),
            "queued_bytes": sum(t.queued_bytes() for t in threads),
        }

    # -- handoff -------------------------------------------------------

    async def try_handoff(self, request, response,
                          server_url: str = "") -> Optional[RelayJob]:
        """Attempt to move a COMMITTED streamed response onto a pump.

        Returns the job, or None (counted per reason in
        ``relay_handoff_failures_total``) — the caller then stays on the
        on-loop relay, which keeps the response byte-identical."""
        from production_stack_tpu.router import metrics as router_metrics

        reason = None
        transport = getattr(request, "transport", None)
        writer = getattr(response, "_payload_writer", None)
        if not self._started or not self._threads:
            reason = "pump_not_running"
        elif transport is None or transport.is_closing():
            reason = "no_transport"
        elif transport.get_extra_info("sslcontext") is not None:
            reason = "tls"
        elif writer is None:
            reason = "no_writer"
        elif getattr(response, "_compression", False):
            reason = "compression"
        if reason is None:
            sock = transport.get_extra_info("socket")
            if sock is None:
                reason = "no_socket"
        if reason is None:
            # The bytes aiohttp already accepted (headers + the first,
            # committing chunk) must reach the kernel before raw writes
            # on the dup may follow them — otherwise they'd reorder.
            deadline = time.monotonic() + DRAIN_WAIT_S
            while transport.get_write_buffer_size() > 0:
                if time.monotonic() >= deadline or transport.is_closing():
                    reason = "buffer_not_drained"
                    break
                await asyncio.sleep(0.005)
        if reason is None:
            try:
                dup = sock.dup()
            except OSError:
                reason = "dup_failed"
        if reason is not None:
            router_metrics.relay_handoff_failures.labels(
                reason=reason).inc()
            return None
        chunked = bool(getattr(writer, "chunked", False))
        job = RelayJob(dup, chunked, asyncio.get_running_loop(),
                       server_url)
        thread = self._threads[self._rr % len(self._threads)]
        self._rr += 1
        job._thread = thread
        job._scheduled = True
        thread.notify(job)
        return job
