"""SLO engine: objectives config, outcome accounting, canary prober.

The stack's introspection layer (traces, step recorder, profiler) shows
*what happened*; this module supplies *judgement*: what "serving well"
means per tenant and model, and whether the fleet is meeting it.

- :class:`SLOEngine` -- loads the ``--slo-config`` YAML (per-tenant /
  per-model TTFT, inter-token, and availability objectives), classifies
  every finished request into exactly one outcome, and maintains the
  windowed goodput ratio behind ``vllm_router:goodput_ratio``.
- :class:`CanaryProber` -- a background task issuing tiny synthetic
  completions straight at each healthy replica, measuring TTFT and
  availability independent of user traffic. Probes bypass the router
  request path entirely (direct engine POST), so they never touch QoS
  accounting, fleet pulls, or the prefix-cache trie.

Objectives file format (every section optional; tenant overrides beat
model overrides beat the default)::

    default:
      ttft_p99_s: 2.0          # per-request TTFT bound (s)
      inter_token_p99_s: 0.5   # per-request mean inter-chunk bound (s)
      availability: 0.999      # error-budget base for burn-rate alerts
    tenants:
      premium: {ttft_p99_s: 1.0}
    models:
      big-model: {ttft_p99_s: 5.0}

Outcome taxonomy (`vllm_router:request_outcomes_total{outcome=...}`):

- ``ok``           -- completed within every latency objective
- ``slow``         -- completed, but violated TTFT or inter-token
- ``shed``         -- rejected by admission control (QoS 429 or 503 shed)
- ``failed``       -- upstream 4xx/5xx, all replicas down, or a broken
                      stream after bytes were sent
- ``client_abort`` -- the client went away before the response finished
"""

from __future__ import annotations

import asyncio
import threading
import time
import uuid
from collections import deque
from typing import Dict, Optional

import aiohttp
import yaml

from production_stack_tpu.router import metrics as router_metrics
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

OUTCOMES = ("ok", "slow", "shed", "failed", "client_abort")

#: Windows exported on the ``vllm_router:goodput_ratio`` gauge.
GOODPUT_WINDOWS = (("5m", 300.0), ("1h", 3600.0))

_DEFAULT_OBJECTIVES = {
    "ttft_p99_s": 2.0,
    "inter_token_p99_s": 0.5,
    "availability": 0.999,
}


def _clean(objectives) -> dict:
    """Keep only known numeric objective keys (a typo'd key is ignored,
    never a crash at classify time)."""
    out = {}
    for key in _DEFAULT_OBJECTIVES:
        value = (objectives or {}).get(key)
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out[key] = float(value)
    return out


class SLOEngine:
    """Objective resolution + outcome accounting for the router.

    Thread-safe: the event loop observes outcomes, /metrics reads the
    goodput window concurrently.
    """

    def __init__(self, config: Optional[dict] = None, source: str = ""):
        config = config or {}
        self.source = source
        self.default = dict(_DEFAULT_OBJECTIVES)
        self.default.update(_clean(config.get("default")))
        self.tenants = {
            str(name): _clean(objectives)
            for name, objectives in (config.get("tenants") or {}).items()
        }
        self.models = {
            str(name): _clean(objectives)
            for name, objectives in (config.get("models") or {}).items()
        }
        self._lock = threading.Lock()
        # (monotonic time, was ok) per classified request; bounded so a
        # storm cannot balloon router memory — at the cap the window
        # simply covers less history than the nominal 1h.
        self._window: deque = deque(maxlen=65536)
        self.outcome_counts: Dict[str, int] = {o: 0 for o in OUTCOMES}

    @classmethod
    def from_file(cls, path: str) -> "SLOEngine":
        with open(path, encoding="utf-8") as f:
            config = yaml.safe_load(f) or {}
        if not isinstance(config, dict):
            raise ValueError(f"--slo-config {path!r} must be a YAML mapping")
        return cls(config, source=path)

    # -- objectives -------------------------------------------------------

    def objectives(self, tenant: Optional[str] = None,
                   model: Optional[str] = None,
                   base_model: Optional[str] = None) -> dict:
        """Resolve objectives: default < models[base_model] <
        models[model] < tenants[tenant].

        ``model`` is what the request named — for LoRA traffic that is
        the ADAPTER name, and ``base_model`` is the model it decorates.
        An adapter entry under ``models:`` therefore overrides its base
        model's entry (an adapter serving a latency-tolerant fine-tune
        can relax the base's bound, or tighten it), while adapters
        without their own entry inherit the base model's objectives
        instead of falling back to the default.
        """
        out = dict(self.default)
        if base_model and base_model != model and base_model in self.models:
            out.update(self.models[base_model])
        if model and model in self.models:
            out.update(self.models[model])
        if tenant and tenant in self.tenants:
            out.update(self.tenants[tenant])
        return out

    def latency_outcome(
        self,
        tenant: Optional[str],
        model: Optional[str],
        ttft_s: Optional[float] = None,
        inter_token_s: Optional[float] = None,
        base_model: Optional[str] = None,
    ) -> str:
        """``ok`` or ``slow`` for a request that completed successfully."""
        obj = self.objectives(tenant, model, base_model=base_model)
        bound = obj.get("ttft_p99_s", 0.0)
        if ttft_s is not None and bound > 0 and ttft_s > bound:
            return "slow"
        bound = obj.get("inter_token_p99_s", 0.0)
        if inter_token_s is not None and bound > 0 and inter_token_s > bound:
            return "slow"
        return "ok"

    # -- accounting -------------------------------------------------------

    def observe(self, outcome: str, tenant: Optional[str] = None,
                model: Optional[str] = None,
                adapter: Optional[str] = None) -> None:
        if outcome not in self.outcome_counts:
            outcome = "failed"  # never raise on the request path
        router_metrics.request_outcomes.labels(
            outcome=outcome, tenant=tenant or "default", model=model or ""
        ).inc()
        if adapter:
            # Additive per-adapter outcome series: the base label set on
            # request_outcomes is unchanged, so adapter-free deployments
            # keep today's exposition byte for byte.
            router_metrics.lora_requests.labels(
                adapter=adapter, outcome=outcome).inc()
        now = time.monotonic()
        with self._lock:
            self.outcome_counts[outcome] += 1
            self._window.append((now, outcome == "ok"))

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.outcome_counts)

    def goodput(self, window_s: float) -> Optional[float]:
        """Share of requests classified ``ok`` in the trailing window;
        None when the window saw no traffic (the gauge is then left at
        its last value instead of lying with 0 or 1)."""
        cutoff = time.monotonic() - window_s
        total = ok = 0
        with self._lock:
            for stamp, was_ok in reversed(self._window):
                if stamp < cutoff:
                    break
                total += 1
                ok += was_ok
        if total == 0:
            return None
        return ok / total

    def refresh_gauges(self) -> None:
        """Called from the /metrics handler (scrape-time refresh, like
        the trace-recorder mirrors)."""
        for name, seconds in GOODPUT_WINDOWS:
            ratio = self.goodput(seconds)
            if ratio is not None:
                router_metrics.goodput_ratio.labels(window=name).set(ratio)

    def fed_snapshot(self) -> dict:
        """Worker-local state for the federation plane: the outcome ring
        counts (summed across workers by ``federation.sum_counts`` — the
        reconciliation invariant Σ workers Σ outcomes == responses rides
        on this) plus this worker's goodput over each window (a ratio,
        so merged views report it per worker, never summed)."""
        return {
            "counts": self.counts(),
            "goodput": {name: self.goodput(seconds)
                        for name, seconds in GOODPUT_WINDOWS},
        }


class CanaryProber:
    """Background synthetic prober: one tiny streamed completion per
    healthy replica per interval, straight at the engine URL.

    Probing direct (not through ``route_general_request``) is what keeps
    canaries invisible to routing state: no QoS bucket debit, no fleet
    pull, no prefix-trie admission, no request-stats sample.
    """

    def __init__(
        self,
        state,
        interval_s: float,
        prompt_tokens: int = 8,
        max_tokens: int = 4,
        events=None,
        timeout_s: float = 30.0,
    ):
        self.state = state
        self.interval_s = float(interval_s)
        self.prompt_tokens = max(1, int(prompt_tokens))
        self.max_tokens = max(1, int(max_tokens))
        self.events = events
        self.timeout_s = float(timeout_s)
        self.probes_run = 0
        self.failures = 0

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.probe_all()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - prober never dies
                logger.debug("canary cycle failed: %s", e)

    async def probe_all(self) -> None:
        endpoints = [
            ep for ep in self.state.service_discovery.get_endpoint_info()
            if not ep.sleep
        ]
        if endpoints:
            await asyncio.gather(*(self.probe(ep) for ep in endpoints))

    async def probe(self, ep) -> Optional[float]:
        """One probe; returns the measured TTFT or None on failure."""
        from production_stack_tpu.router.httpclient import get_client_session
        from production_stack_tpu.utils.auth import deployment_auth_headers

        model = ep.model_names[0] if ep.model_names else ""
        body = {
            "model": model,
            "prompt": ("ping " * self.prompt_tokens).strip(),
            "max_tokens": self.max_tokens,
            "stream": True,
        }
        headers = {"X-Request-Id": f"canary-{uuid.uuid4().hex[:12]}",
                   **deployment_auth_headers()}
        self.probes_run += 1
        router_metrics.canary_probes.labels(server=ep.url).inc()
        t0 = time.monotonic()
        try:
            session = get_client_session()
            async with session.post(
                f"{ep.url}/v1/completions", json=body, headers=headers,
                timeout=aiohttp.ClientTimeout(total=self.timeout_s),
            ) as resp:
                if resp.status >= 400:
                    self._fail(ep.url, f"status_{resp.status}")
                    return None
                ttft: Optional[float] = None
                async for chunk in resp.content.iter_any():
                    if chunk and ttft is None:
                        ttft = time.monotonic() - t0
                        router_metrics.canary_ttft.labels(
                            server=ep.url).observe(ttft)
                if ttft is None:
                    self._fail(ep.url, "empty")
                    return None
                return ttft
        except asyncio.TimeoutError:
            self._fail(ep.url, "timeout")
        except aiohttp.ClientError as e:
            self._fail(ep.url, "connect")
            logger.debug("canary connect error for %s: %s", ep.url, e)
        return None

    def _fail(self, url: str, reason: str) -> None:
        self.failures += 1
        router_metrics.canary_failures.labels(server=url, reason=reason).inc()
        if self.events is not None:
            self.events.record("canary_failure", endpoint=url, reason=reason)
        logger.warning("canary probe failed for %s: %s", url, reason)
