"""Router CLI argument parsing and validation.

Rebuild of reference ``src/vllm_router/parsers/parser.py:118-386`` (~40 flags)
including the dynamic-config-file initial merge (reference ``:47-68``,
``parsers/yaml_utils.py:39-56``).
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

import yaml

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="TPU production-stack router")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8001)
    parser.add_argument(
        "--router-workers", type=int, default=1,
        help="Router worker processes sharing the port via SO_REUSEPORT "
        "pre-fork. Telemetry federates across workers (aggregated "
        "/metrics and /debug/* fan in over per-worker snapshots); KV "
        "claims, token buckets, and circuit breakers stay process-local "
        "— see docs/scale_out.md. 1 (default) keeps the single-process "
        "router byte-identical.")
    # Service discovery
    parser.add_argument(
        "--service-discovery",
        choices=["static", "k8s", "k8s_service_name"], default="static"
    )
    parser.add_argument("--static-backends", type=str, default=None,
                        help="Comma-separated engine URLs")
    parser.add_argument("--static-models", type=str, default=None,
                        help="Comma-separated model names, one per backend")
    parser.add_argument("--static-aliases", type=str, default=None,
                        help="alias:model pairs, comma-separated")
    parser.add_argument("--static-model-labels", type=str, default=None)
    parser.add_argument("--static-model-types", type=str, default=None)
    parser.add_argument("--static-backend-health-checks", action="store_true")
    parser.add_argument("--k8s-namespace", default="default")
    parser.add_argument("--k8s-port", type=int, default=8000)
    parser.add_argument("--k8s-label-selector", default=None)
    # Routing
    parser.add_argument(
        "--routing-logic",
        choices=["roundrobin", "session", "kvaware", "prefixaware",
                 "disaggregated_prefill"],
        default="roundrobin",
    )
    parser.add_argument("--session-key", default="x-user-id")
    parser.add_argument("--kv-aware-threshold", type=int, default=2000)
    parser.add_argument("--prefill-model-labels", type=str, default=None)
    parser.add_argument("--decode-model-labels", type=str, default=None)
    # Stats
    parser.add_argument("--engine-stats-interval", type=float, default=10.0)
    parser.add_argument("--request-stats-window", type=float, default=60.0)
    parser.add_argument("--api-key", default=None,
                        help="require 'Authorization: Bearer <key>' on "
                             "the inference surface (/v1/* and the "
                             "score/rerank/tokenize/detokenize aliases; "
                             "default: VLLM_API_KEY / TPU_STACK_API_KEY "
                             "env)")
    parser.add_argument("--log-stats", action="store_true")
    parser.add_argument("--log-stats-interval", type=float, default=10.0)
    # Batch & files API
    parser.add_argument("--enable-batch-api", action="store_true")
    parser.add_argument("--file-storage-class", default="local_file")
    parser.add_argument("--file-storage-path", default="/tmp/tpu_stack_files")
    parser.add_argument("--batch-processor", default="local")
    # Multi-tenant QoS (production_stack_tpu/qos/)
    parser.add_argument("--qos-tenants-file", type=str, default=None,
                        help="YAML/JSON tenants file (API-key -> tenant, "
                             "weights, token-bucket limits, priority "
                             "class); enables admission control and the "
                             "weighted-fair queue. Hot-reloaded. Unset = "
                             "QoS fully off (today's behavior)")
    parser.add_argument("--qos-max-concurrency", type=int, default=None,
                        help="fair-queue dispatch slots (overrides the "
                             "tenants file's max_concurrency)")
    parser.add_argument("--qos-shed-queue-depth", type=int, default=None,
                        help="queued batch requests before new batch "
                             "traffic is shed with 503 (overrides the "
                             "tenants file's shed_queue_depth)")
    parser.add_argument("--qos-reload-interval", type=float, default=2.0,
                        help="seconds between tenants-file mtime checks")
    # Fault tolerance (production_stack_tpu/router/fault_tolerance.py)
    parser.add_argument("--fault-tolerance", action="store_true",
                        help="enable the fault-tolerant data plane: "
                             "per-endpoint circuit breaker, bounded "
                             "retry with failover to another healthy "
                             "replica (connect errors and 5xx before "
                             "the first streamed byte only), and "
                             "TTFT/inter-chunk streaming deadlines. "
                             "Unset = today's single-attempt behavior, "
                             "byte-identical")
    parser.add_argument("--ft-max-retries", type=int, default=3,
                        help="additional attempts after the first "
                             "(failing over across healthy replicas)")
    parser.add_argument("--ft-backoff-base", type=float, default=0.05,
                        help="exponential backoff base seconds "
                             "(full jitter)")
    parser.add_argument("--ft-backoff-max", type=float, default=2.0,
                        help="backoff ceiling seconds")
    parser.add_argument("--ft-breaker-threshold", type=int, default=5,
                        help="consecutive failures before an endpoint's "
                             "circuit breaker opens")
    parser.add_argument("--ft-breaker-reset", type=float, default=30.0,
                        help="seconds an open breaker waits before a "
                             "half-open probe request")
    parser.add_argument("--ft-ttft-deadline", type=float, default=120.0,
                        help="seconds allowed until the first upstream "
                             "byte (0 disables)")
    parser.add_argument("--ft-inter-chunk-deadline", type=float,
                        default=30.0,
                        help="seconds allowed between streamed chunks "
                             "(0 disables)")
    parser.add_argument("--ft-retry-after", type=int, default=5,
                        help="Retry-After seconds returned with 503 "
                             "when every replica is broken")
    # Fleet cache & autoscaling (production_stack_tpu/kv/fleet.py)
    parser.add_argument("--fleet-cache", action="store_true",
                        help="enable the global prefix cache: when the KV "
                             "controller says another replica (or the L3 "
                             "cache server) holds a long prefix of the "
                             "prompt, the routed replica /kv/pull-s it "
                             "before prefill instead of recomputing. "
                             "Unset = today's per-replica behavior, "
                             "byte-identical")
    parser.add_argument("--fleet-pull-timeout", type=float, default=15.0,
                        help="seconds allowed for the /kv/pull control "
                             "round-trip before falling back to recompute")
    parser.add_argument("--fleet-min-match-chars", type=int, default=256,
                        help="minimum controller prefix match (characters) "
                             "worth a cross-replica pull")
    parser.add_argument("--fleet-l3-url", type=str, default=None,
                        help="shared L3 cache server URL (kv.cache_server); "
                             "spilled evictions stay routable through it")
    # Pull economics & the crossover advisor (kv/economics.py)
    parser.add_argument("--fleet-prefill-tokens-per-s", type=float,
                        default=2000.0,
                        help="recompute-cost floor (prefill tokens/s) the "
                             "pull ledger uses when no measured prefill "
                             "throughput is available")
    parser.add_argument("--fleet-chars-per-token", type=float, default=4.0,
                        help="prompt chars per token for the advisor's "
                             "break-even conversion (the controller trie "
                             "is character-chunked)")
    parser.add_argument("--fleet-auto-min-match", action="store_true",
                        help="apply the crossover advisor's recommended "
                             "--fleet-min-match-chars on a damped "
                             "interval. Unset = the configured threshold "
                             "is never touched (request path "
                             "byte-identical)")
    parser.add_argument("--fleet-auto-min-match-interval", type=float,
                        default=30.0,
                        help="seconds between auto-min-match applications")
    parser.add_argument("--fleet-auto-min-match-damping", type=float,
                        default=0.3,
                        help="per-application step toward the advisor's "
                             "recommendation (new = old + damping * "
                             "(recommended - old)); 1.0 jumps straight "
                             "to it")
    parser.add_argument("--kv-pull-max-concurrency", type=int, default=8,
                        help="router-side cap on concurrent /kv/pull "
                             "orchestrations against ONE holder replica; "
                             "excess requests skip the pull and recompute "
                             "(identical-prefix pulls to the same target "
                             "additionally share one in-flight transfer)")
    # KV claim leases / anti-entropy (crash consistency for the fleet
    # cache: a kill -9'd replica's claims are swept after N missed
    # heartbeats instead of lingering for the full admit TTL).
    parser.add_argument("--kv-heartbeat-interval", type=float, default=10.0,
                        help="expected engine heartbeat cadence (s); an "
                             "instance that registered with a generation "
                             "id expires after --kv-lease-misses missed "
                             "beats and its claims are swept (0 disables "
                             "the lease sweeper; engines that never "
                             "heartbeat are unaffected either way)")
    parser.add_argument("--kv-lease-misses", type=int, default=3,
                        help="missed heartbeats before an instance's "
                             "lease expires")
    parser.add_argument("--autoscale", action="store_true",
                        help="enable the load-predictive autoscale "
                             "recommender: /autoscale/recommendation and "
                             "vllm_router:autoscale_*_replicas gauges fed "
                             "from queue depth, HBM KV pressure, and the "
                             "QoS backlog; /autoscale/scale_in drains and "
                             "deregisters a replica")
    parser.add_argument("--autoscale-min-replicas", type=int, default=1)
    parser.add_argument("--autoscale-max-replicas", type=int, default=8)
    parser.add_argument("--autoscale-queue-depth-target", type=float,
                        default=4.0,
                        help="backlog (waiting + QoS queue) each replica "
                             "is expected to absorb")
    parser.add_argument("--autoscale-hbm-usage-high", type=float,
                        default=0.9,
                        help="mean HBM KV usage fraction above which one "
                             "extra replica is recommended")
    parser.add_argument("--autoscale-drain-timeout", type=float,
                        default=120.0,
                        help="seconds /autoscale/scale_in waits for the "
                             "victim's /drain to quiesce")
    # LoRA adapter plane
    parser.add_argument("--lora-plane", action="store_true",
                        help="enable the adapter control plane: residency "
                             "scraping of each replica's /v1/lora_adapters, "
                             "adapter-affinity routing with single-flight "
                             "on-demand loads, /lora/{load,unload} fan-out, "
                             "GET /debug/lora, and adapter-salted KV keys")
    parser.add_argument("--lora-scrape-interval", type=float, default=10.0,
                        help="seconds between adapter residency scrapes")
    parser.add_argument("--lora-load-timeout", type=float, default=60.0,
                        help="deadline for one on-demand adapter load on "
                             "the request path")
    parser.add_argument("--lora-default-replicas", type=int, default=1,
                        help="replicas /lora/load targets when the request "
                             "body names no count")
    parser.add_argument("--lora-no-affinity", action="store_true",
                        help="disable adapter-affinity pinning (adapter "
                             "requests route like base requests and load "
                             "on-demand wherever they land); A/B baseline, "
                             "not a production setting")
    # Dynamic config
    parser.add_argument("--kv-admit-ttl", type=float, default=600.0,
                        help="seconds a KV admission claim stays routable "
                             "without re-report (0 disables expiry)")
    parser.add_argument("--dynamic-config-json", type=str, default=None)
    parser.add_argument("--dynamic-config-interval", type=float, default=10.0,
                        help="seconds between dynamic-config file polls")
    # Callbacks / rewriter / feature gates
    parser.add_argument("--callbacks", type=str, default=None,
                        help="Import path `module.object` with pre/post_request")
    parser.add_argument("--request-rewriter", default="noop")
    parser.add_argument("--feature-gates", type=str, default="",
                        help="e.g. SemanticCache=true,PIIDetection=true")
    # Semantic cache
    parser.add_argument("--semantic-cache-model", default="all-MiniLM-L6-v2")
    parser.add_argument("--semantic-cache-dir", default=None)
    parser.add_argument("--semantic-cache-threshold", type=float, default=0.95)
    # Logging / tracing
    parser.add_argument("--log-level", default="info",
                        choices=["debug", "info", "warning", "error", "critical"])
    parser.add_argument("--sentry-dsn", default=None,
                        help="enable Sentry error reporting/profiling "
                             "(requires sentry-sdk in the image)")
    parser.add_argument("--sentry-traces-sample-rate", type=float,
                        default=0.1)
    parser.add_argument("--sentry-profile-session-sample-rate", type=float,
                        default=0.1)
    parser.add_argument("--otel-endpoint", default=None,
                        help="OTLP endpoint for request span export "
                             "(alias for an http(s) --trace-export)")
    parser.add_argument("--trace-export", default=None,
                        help="export completed traces as OTLP-JSON: "
                             "'file:/path/traces.jsonl' (one line per trace) "
                             "or an 'http(s)://collector:4318/v1/traces' "
                             "endpoint")
    parser.add_argument("--slow-trace-threshold-s", type=float, default=0.0,
                        help="log one structured JSON line (full span "
                             "timeline) for any request slower than this "
                             "many seconds; 0 disables")
    parser.add_argument("--trace-buffer", type=int, default=512,
                        help="completed traces kept in the in-process "
                             "flight recorder, served at /debug/traces")
    parser.add_argument("--trace-sample-rate", type=float, default=1.0,
                        help="fraction of requests whose traces are "
                             "retained and exported (deterministic by "
                             "trace id, so router and engine keep the "
                             "same requests); stage rollup metrics still "
                             "count every request")
    parser.add_argument("--slow-trace-log-interval-s", type=float,
                        default=0.0,
                        help="emit at most one slow-trace log line per "
                             "this many seconds (suppressed lines still "
                             "count as slow requests); 0 logs every slow "
                             "trace")
    # SLO engine (production_stack_tpu/router/slo.py)
    parser.add_argument("--slo-config", type=str, default=None,
                        help="YAML objectives file (per-tenant/per-model "
                             "TTFT, inter-token, and availability "
                             "targets); enables the request outcome "
                             "classifier behind vllm_router:request_"
                             "outcomes_total and the goodput_ratio "
                             "gauge. Unset = no classification, "
                             "request path byte-identical")
    parser.add_argument("--canary-interval", type=float, default=0.0,
                        help="seconds between synthetic canary probes "
                             "against each healthy replica (0 disables); "
                             "probes bypass QoS, fleet pulls, and the "
                             "prefix-cache trie")
    parser.add_argument("--canary-prompt-tokens", type=int, default=8,
                        help="approximate prompt length of a canary "
                             "probe (words)")
    parser.add_argument("--canary-max-tokens", type=int, default=4,
                        help="max_tokens requested by a canary probe")
    # Event-loop introspection (production_stack_tpu/obs/looplag.py)
    parser.add_argument("--loop-monitor", action="store_true",
                        help="measure event-loop scheduling lag, detect "
                             "blocking calls on the loop (watchdog "
                             "stack sampler), and attribute on-loop "
                             "CPU time per router component; serves "
                             "GET /debug/loop. Off = hot path "
                             "byte-identical")
    parser.add_argument("--loop-stall-threshold-ms", type=float,
                        default=100.0,
                        help="loop lag counted as a stall and sampled "
                             "by the blocking-call watchdog once the "
                             "loop has not ticked for this long")
    # Relay pump tier (production_stack_tpu/router/relay.py)
    parser.add_argument("--relay-off-loop", action="store_true",
                        help="hand committed streamed responses to a "
                             "pool of pump threads that copy upstream "
                             "chunks to the client socket off the "
                             "event loop (coalesced sends, GIL "
                             "released in syscalls); the loop keeps "
                             "control flow only. Off = streaming path "
                             "byte-identical")
    parser.add_argument("--relay-pump-threads", type=int, default=2,
                        help="pump worker threads per router process "
                             "when --relay-off-loop is set")
    return parser


def validate_args(args: argparse.Namespace) -> None:
    """Cross-field validation (reference parser.py:70-116)."""
    if args.service_discovery == "static":
        if args.dynamic_config_json is None and not args.static_backends:
            raise ValueError(
                "--static-backends required with static service discovery"
            )
        if args.dynamic_config_json is None and not args.static_models:
            raise ValueError(
                "--static-models required with static service discovery"
            )
    if args.routing_logic == "disaggregated_prefill" and (
        not args.prefill_model_labels or not args.decode_model_labels
    ):
        raise ValueError(
            "disaggregated_prefill routing requires --prefill-model-labels "
            "and --decode-model-labels"
        )
    if getattr(args, "qos_max_concurrency", None) is not None \
            and args.qos_max_concurrency < 1:
        raise ValueError("--qos-max-concurrency must be >= 1")
    if getattr(args, "qos_shed_queue_depth", None) is not None \
            and args.qos_shed_queue_depth < 0:
        raise ValueError("--qos-shed-queue-depth must be >= 0")
    if getattr(args, "fault_tolerance", False):
        if args.ft_max_retries < 0:
            raise ValueError("--ft-max-retries must be >= 0")
        if args.ft_backoff_base < 0 or args.ft_backoff_max < 0:
            raise ValueError("--ft-backoff-base/--ft-backoff-max must "
                             "be >= 0")
        if args.ft_breaker_threshold < 1:
            raise ValueError("--ft-breaker-threshold must be >= 1")
        if args.ft_breaker_reset <= 0:
            raise ValueError("--ft-breaker-reset must be > 0")
        if args.ft_ttft_deadline < 0 or args.ft_inter_chunk_deadline < 0:
            raise ValueError("--ft-ttft-deadline/--ft-inter-chunk-"
                             "deadline must be >= 0 (0 disables)")
    if getattr(args, "fleet_cache", False):
        if args.fleet_pull_timeout <= 0:
            raise ValueError("--fleet-pull-timeout must be > 0")
        if args.fleet_min_match_chars < 1:
            raise ValueError("--fleet-min-match-chars must be >= 1")
        if args.kv_pull_max_concurrency < 1:
            raise ValueError("--kv-pull-max-concurrency must be >= 1")
        if args.fleet_prefill_tokens_per_s <= 0:
            raise ValueError("--fleet-prefill-tokens-per-s must be > 0")
        if args.fleet_chars_per_token <= 0:
            raise ValueError("--fleet-chars-per-token must be > 0")
        if getattr(args, "fleet_auto_min_match", False):
            if args.fleet_auto_min_match_interval <= 0:
                raise ValueError(
                    "--fleet-auto-min-match-interval must be > 0")
            if not 0.0 < args.fleet_auto_min_match_damping <= 1.0:
                raise ValueError(
                    "--fleet-auto-min-match-damping must be in (0, 1]")
    if getattr(args, "kv_heartbeat_interval", 10.0) < 0:
        raise ValueError("--kv-heartbeat-interval must be >= 0 "
                         "(0 disables the lease sweeper)")
    if getattr(args, "kv_lease_misses", 3) < 1:
        raise ValueError("--kv-lease-misses must be >= 1")
    if getattr(args, "autoscale", False):
        if args.autoscale_min_replicas < 0:
            raise ValueError("--autoscale-min-replicas must be >= 0")
        if args.autoscale_max_replicas < max(args.autoscale_min_replicas, 1):
            raise ValueError("--autoscale-max-replicas must be >= "
                             "max(--autoscale-min-replicas, 1)")
        if args.autoscale_queue_depth_target <= 0:
            raise ValueError("--autoscale-queue-depth-target must be > 0")
        if not 0.0 < args.autoscale_hbm_usage_high <= 1.0:
            raise ValueError("--autoscale-hbm-usage-high must be in (0, 1]")
    if getattr(args, "lora_plane", False):
        if args.lora_scrape_interval <= 0:
            raise ValueError("--lora-scrape-interval must be > 0")
        if args.lora_load_timeout <= 0:
            raise ValueError("--lora-load-timeout must be > 0")
        if args.lora_default_replicas < 1:
            raise ValueError("--lora-default-replicas must be >= 1")
    if not 0.0 <= args.sentry_traces_sample_rate <= 1.0:
        raise ValueError("--sentry-traces-sample-rate must be in [0, 1]")
    if not 0.0 <= args.sentry_profile_session_sample_rate <= 1.0:
        raise ValueError(
            "--sentry-profile-session-sample-rate must be in [0, 1]")
    if not 0.0 <= getattr(args, "trace_sample_rate", 1.0) <= 1.0:
        raise ValueError("--trace-sample-rate must be in [0, 1]")
    if getattr(args, "slow_trace_log_interval_s", 0.0) < 0.0:
        raise ValueError("--slow-trace-log-interval-s must be >= 0")
    if getattr(args, "canary_interval", 0.0) < 0.0:
        raise ValueError("--canary-interval must be >= 0 (0 disables)")
    if getattr(args, "canary_prompt_tokens", 8) < 1:
        raise ValueError("--canary-prompt-tokens must be >= 1")
    if getattr(args, "canary_max_tokens", 4) < 1:
        raise ValueError("--canary-max-tokens must be >= 1")
    if getattr(args, "loop_stall_threshold_ms", 100.0) <= 0.0:
        raise ValueError("--loop-stall-threshold-ms must be > 0")
    if getattr(args, "router_workers", 1) < 1:
        raise ValueError("--router-workers must be >= 1")
    if getattr(args, "relay_pump_threads", 2) < 1:
        raise ValueError("--relay-pump-threads must be >= 1")


def expand_static_models_config(config: dict) -> dict:
    """Expand a structured `static_models` list into flag strings
    (reference parsers/yaml_utils.py:39-56)."""
    static_models = config.pop("static_models", None)
    if not static_models:
        return config
    if not isinstance(static_models, list) or not all(
        isinstance(e, dict) for e in static_models
    ):
        # Plain comma-separated string form (flag style): nothing to expand.
        config["static_models"] = static_models
        return config
    urls, models, labels, types = [], [], [], []
    aliases = {}
    for entry in static_models:
        urls.append(entry["url"])
        models.append(entry["model"])
        labels.append(entry.get("model_label") or "")
        types.append(entry.get("model_type") or "chat")
        for alias in entry.get("aliases", []) or []:
            aliases[alias] = entry["model"]
    config.setdefault("static_backends", ",".join(urls))
    config.setdefault("static_models", ",".join(models))
    if any(labels):
        config.setdefault("static_model_labels", ",".join(labels))
    config.setdefault("static_model_types", ",".join(types))
    if aliases:
        config.setdefault(
            "static_aliases", ",".join(f"{a}:{m}" for a, m in aliases.items())
        )
    return config


def load_initial_config_from_config_file_if_required(
    args: argparse.Namespace,
) -> argparse.Namespace:
    """Merge values from --dynamic-config-json into unset args
    (reference parser.py:47-68)."""
    if not args.dynamic_config_json:
        return args
    with open(args.dynamic_config_json) as f:
        if args.dynamic_config_json.endswith((".yaml", ".yml")):
            config = yaml.safe_load(f)
        else:
            config = json.load(f)
    config = expand_static_models_config(config or {})
    for key, value in config.items():
        attr = key.replace("-", "_")
        if hasattr(args, attr) and getattr(args, attr) in (None, "", False):
            setattr(args, attr, value)
    return args


def parse_args(argv: Optional[list] = None) -> argparse.Namespace:
    parser = build_parser()
    args = parser.parse_args(argv)
    args = load_initial_config_from_config_file_if_required(args)
    validate_args(args)
    return args
