"""Pluggable request body rewriting (reference
src/vllm_router/services/request_service/rewriter.py:29-119)."""

from __future__ import annotations

import abc

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class RequestRewriter(abc.ABC):
    @abc.abstractmethod
    def rewrite(self, body: bytes, endpoint: str) -> bytes:
        """Return the (possibly rewritten) request body."""


class NoopRequestRewriter(RequestRewriter):
    def rewrite(self, body: bytes, endpoint: str) -> bytes:
        return body


def get_request_rewriter(name: str = "noop") -> RequestRewriter:
    if name in (None, "", "noop"):
        return NoopRequestRewriter()
    # Custom rewriter by import path "module:Class".
    import importlib

    module_name, _, attr = name.partition(":")
    cls = getattr(importlib.import_module(module_name), attr)
    return cls()
