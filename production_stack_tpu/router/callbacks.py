"""User-supplied request callbacks (reference
src/vllm_router/services/callbacks_service/callbacks.py:23-32).

``--callbacks module.submodule.object`` loads an object exposing optional
``pre_request(request, request_json, request_id)`` and
``post_request(request_json, response_body, request_id)`` hooks (sync or
async). ``pre_request`` may return a response to short-circuit routing.
"""

from __future__ import annotations

import importlib

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def configure_custom_callbacks(spec: str):
    module_path, _, obj_name = spec.rpartition(".")
    if not module_path:
        raise ValueError(
            f"--callbacks must be `module.object`, got {spec!r}"
        )
    module = importlib.import_module(module_path)
    obj = getattr(module, obj_name)
    logger.info("Loaded custom callbacks from %s", spec)
    return obj
