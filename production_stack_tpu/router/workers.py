"""Multi-worker router plane: SO_REUSEPORT pre-fork + telemetry fan-in.

``--router-workers N`` forks N identical router processes that share the
public TCP port via ``SO_REUSEPORT`` (the kernel load-balances accepted
connections). Each worker additionally listens on a private Unix socket
(``worker-<id>.sock`` in a 0700 tempdir) serving the privileged
``GET /debug/snapshot`` — the federation feed carrying that worker's
registry samples, trace/event/economics rings, SLO outcome counts,
loop-monitor rollups, and shared-state digests.

Aggregation is SYMMETRIC: whichever worker receives ``/metrics`` or a
federated ``/debug/*`` read fans in over every worker's snapshot socket
(its own included — the self-request over the UDS is async and cheap)
and serves the merged view from ``obs/federation.py``. The issue frames
this as "worker 0 aggregates", but under SO_REUSEPORT the kernel picks
the accepting worker, so pinning aggregation to worker 0 would make the
merged view reachable only by luck; making every worker an aggregator
gives the same merged answer on every connection.

What does NOT federate: KV controller claims, tenant token buckets,
circuit breakers, and single-flight pull dedup stay process-local.
Their cross-worker drift is *measured* instead — breaker-view and
trie-digest comparisons surface in ``/debug/workers`` and the
``vllm_router:worker_state_divergence_total`` counter (see
docs/scale_out.md for interpretation).
"""

from __future__ import annotations

import asyncio
import os
import shutil
import signal
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from aiohttp import web

from production_stack_tpu.obs import federation
from production_stack_tpu.router import metrics as metrics_mod
from production_stack_tpu.utils import auth
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

#: /debug/snapshot body sections; ``?sections=metrics,divergence`` lets
#: the aggregated /metrics scrape skip ring payloads it will not use.
SNAPSHOT_SECTIONS = ("metrics", "traces", "events", "slo", "loop",
                     "kv_economics", "divergence")

#: Fan-in budget per snapshot fetch. Generous because the saturation
#: harness reads /debug/workers right after a rung drains, when worker
#: loops may still be catching up.
FANIN_TIMEOUT_S = 15.0


# ---------------------------------------------------------------------------
# Local snapshot (the per-worker federation feed)
# ---------------------------------------------------------------------------


def _refresh_scrape_mirrors(state) -> None:
    # Same scrape-time refresh the single-worker /metrics handler does
    # (app.metrics_handler keeps its own copy so its flag-off byte
    # parity never depends on this module).
    metrics_mod.update_gauges(
        state.service_discovery.get_endpoint_info(),
        state.engine_stats_scraper.get_engine_stats(),
        state.request_stats_monitor.get_request_stats(),
        fault_tolerance=state.fault_tolerance,
    )
    if state.trace_recorder is not None:
        metrics_mod.trace_sampled_out.set(
            state.trace_recorder.sampled_out_total)
        metrics_mod.slow_trace_logs_suppressed.set(
            state.trace_recorder.slow_logs_suppressed_total)
    if state.slo is not None:
        state.slo.refresh_gauges()
    if state.relay is not None:
        metrics_mod.mirror_relay_metrics(state.relay)
    if state.loop_monitor is not None:
        metrics_mod.mirror_loop_metrics(state.loop_monitor)


async def local_snapshot(state, *, sections=None, limit: int = 100,
                         lag_window_s: Optional[float] = None,
                         blockers: int = 10,
                         trace_id: Optional[str] = None,
                         trace_format: Optional[str] = None) -> dict:
    """This worker's federation feed: every store's ``fed_snapshot()``
    plus the registry dump and shared-state divergence digests."""
    want = frozenset(sections) if sections else frozenset(SNAPSHOT_SECTIONS)
    snap: dict = {
        "worker": state.worker_id,
        "workers": state.worker_count,
        "pid": os.getpid(),
        "port": state.worker_port,
        "time_unix": time.time(),
        "sections": sorted(want),
    }
    if "metrics" in want:
        _refresh_scrape_mirrors(state)
        snap["metrics"] = metrics_mod.registry_snapshot()
    if "traces" in want and state.trace_recorder is not None:
        snap["traces"] = state.trace_recorder.fed_snapshot(
            limit=limit, request_id=trace_id)
        if trace_id is not None and trace_format == "otlp":
            tr = state.trace_recorder.get(trace_id)
            snap["traces"]["trace_otlp"] = (
                tr.to_otlp() if tr is not None else None)
    if "events" in want and state.events is not None:
        snap["events"] = state.events.fed_snapshot(limit=limit)
    if "slo" in want and state.slo is not None:
        snap["slo"] = state.slo.fed_snapshot()
    if "loop" in want and state.loop_monitor is not None:
        snap["loop"] = state.loop_monitor.fed_snapshot(
            lag_window_s=lag_window_s, blockers=blockers)
    if "kv_economics" in want and state.fleet is not None:
        snap["kv_economics"] = state.fleet.ledger.fed_snapshot(limit=limit)
    if "divergence" in want:
        snap["divergence"] = {
            "breaker_view": (
                state.fault_tolerance.breaker.snapshot()
                if state.fault_tolerance is not None else {}),
            "trie_digest": await state.kv_controller.fed_digest(),
        }
    return snap


# ---------------------------------------------------------------------------
# Fan-in over the per-worker snapshot sockets
# ---------------------------------------------------------------------------


def _snapshot_query(*, sections=None, limit: Optional[int] = None,
                    lag_window_s: Optional[float] = None,
                    blockers: Optional[int] = None,
                    trace_id: Optional[str] = None,
                    trace_format: Optional[str] = None) -> Dict[str, str]:
    query: Dict[str, str] = {}
    if sections:
        query["sections"] = ",".join(sections)
    if limit is not None:
        query["limit"] = str(int(limit))
    if lag_window_s is not None:
        query["lag_window_s"] = repr(float(lag_window_s))
    if blockers is not None:
        query["blockers"] = str(int(blockers))
    if trace_id is not None:
        query["trace"] = trace_id
    if trace_format is not None:
        query["trace_format"] = trace_format
    return query


async def _fetch_one(wid: int, uds_path: str,
                     query: Dict[str, str]) -> Optional[dict]:
    import aiohttp

    try:
        connector = aiohttp.UnixConnector(path=uds_path)
        timeout = aiohttp.ClientTimeout(total=FANIN_TIMEOUT_S)
        async with aiohttp.ClientSession(connector=connector,
                                         timeout=timeout) as session:
            async with session.get(
                    "http://worker/debug/snapshot", params=query,
                    headers=auth.deployment_auth_headers()) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"snapshot HTTP {resp.status}")
                return await resp.json()
    except Exception as e:  # noqa: BLE001 — a dead worker must not 500 the view
        logger.warning("worker %d snapshot fan-in failed: %s", wid, e)
        metrics_mod.worker_snapshot_errors.labels(worker=str(wid)).inc()
        return None


async def fetch_worker_snapshots(state, **kwargs
                                 ) -> Tuple[List[dict], List[int]]:
    """All workers' snapshots (self included, over its own UDS so every
    worker runs the identical code path). Returns (snapshots, failed
    worker ids); single-worker mode short-circuits to a local call."""
    if state.worker_count <= 1 or not state.worker_uds:
        return [await local_snapshot(state, **kwargs)], []
    query = _snapshot_query(**kwargs)
    results = await asyncio.gather(*(
        _fetch_one(wid, uds_path, query)
        for wid, uds_path in enumerate(state.worker_uds)))
    snaps = [s for s in results if s is not None]
    failed = [wid for wid, s in enumerate(results) if s is None]
    return snaps, failed


def _note_divergence(report: Dict[str, dict]) -> None:
    for kind, entry in report.items():
        if entry.get("diverged"):
            metrics_mod.worker_state_divergence.labels(kind=kind).inc()


# ---------------------------------------------------------------------------
# Query validation (the 400 contract shared with obs/debug.py)
# ---------------------------------------------------------------------------


def _bad(message: str) -> web.Response:
    return web.json_response({"error": message}, status=400)


def _parse_common_query(request: web.Request):
    """(kwargs for fetch/local_snapshot) or an error Response."""
    out: dict = {}
    try:
        out["limit"] = int(request.query.get("limit", 100) or 100)
    except ValueError:
        return _bad("limit must be an integer")
    if out["limit"] < 1:
        return _bad("limit must be >= 1")
    raw_window = request.query.get("lag_window_s")
    if raw_window:
        try:
            out["lag_window_s"] = float(raw_window)
        except ValueError:
            return _bad("lag_window_s must be a number")
        if out["lag_window_s"] <= 0:
            return _bad("lag_window_s must be > 0")
    try:
        out["blockers"] = int(request.query.get("blockers", 10) or 10)
    except ValueError:
        return _bad("blockers must be an integer")
    if out["blockers"] < 1:
        return _bad("blockers must be >= 1")
    return out


def _parse_worker_query(request: web.Request, state):
    """validated ``?worker=`` (None when absent) or an error Response."""
    try:
        return federation.parse_worker_param(
            request.query.get("worker"), range(state.worker_count))
    except ValueError as e:
        return _bad(str(e))


# ---------------------------------------------------------------------------
# Always-registered worker plane routes
# ---------------------------------------------------------------------------


async def debug_snapshot_handler(request: web.Request) -> web.Response:
    """Privileged per-worker federation feed. Local by construction —
    never fans in, so aggregators can call it without recursion."""
    state = request.app["state"]
    kwargs = _parse_common_query(request)
    if isinstance(kwargs, web.Response):
        return kwargs
    raw_sections = request.query.get("sections")
    if raw_sections:
        sections = tuple(s for s in raw_sections.split(",") if s)
        unknown = [s for s in sections if s not in SNAPSHOT_SECTIONS]
        if unknown:
            return _bad(f"unknown sections {unknown} "
                        f"(one of: {', '.join(SNAPSHOT_SECTIONS)})")
        kwargs["sections"] = sections
    trace_id = request.query.get("trace")
    if trace_id:
        kwargs["trace_id"] = trace_id
        trace_format = request.query.get("trace_format")
        if trace_format:
            if trace_format != "otlp":
                return _bad("trace_format must be otlp")
            kwargs["trace_format"] = trace_format
    return web.json_response(await local_snapshot(state, **kwargs))


async def debug_workers_handler(request: web.Request) -> web.Response:
    """Cross-worker topology, per-worker outcome/lag rollups, and the
    shared-state divergence report. Works in single-worker mode too
    (one-entry topology, nothing to diverge from)."""
    state = request.app["state"]
    kwargs = _parse_common_query(request)
    if isinstance(kwargs, web.Response):
        return kwargs
    worker_filter = _parse_worker_query(request, state)
    if isinstance(worker_filter, web.Response):
        return worker_filter
    kwargs["sections"] = ("traces", "events", "slo", "loop", "divergence")
    snaps, failed = await fetch_worker_snapshots(state, **kwargs)
    if not snaps:
        return web.json_response(
            {"error": "no worker snapshots reachable",
             "workers_failed": failed}, status=503)
    merged = federation.merge_worker_snapshots(snaps)
    _note_divergence(merged["divergence"])
    merged["workers_configured"] = state.worker_count
    merged["workers_failed"] = failed
    merged["port"] = state.worker_port
    if worker_filter is not None:
        merged["per_worker"] = [row for row in merged["per_worker"]
                                if row["worker"] == worker_filter]
    return web.json_response(merged)


def add_worker_plane_routes(router, state) -> None:
    """Registered in every mode: single-worker deployments keep the same
    endpoint shapes (local-only snapshot, 1-entry /debug/workers), so
    the auth coverage test and operators see one surface."""
    router.add_get("/debug/snapshot", debug_snapshot_handler)
    router.add_get("/debug/workers", debug_workers_handler)


# ---------------------------------------------------------------------------
# Multi-worker aggregated /metrics and federated /debug views
# ---------------------------------------------------------------------------


async def aggregated_metrics_handler(request: web.Request) -> web.Response:
    """Merged /metrics: fan in every worker's registry snapshot and
    render one exposition (counters summed, gauges per the federation
    semantics maps, per-worker series labeled ``worker=<id>``)."""
    state = request.app["state"]
    snaps, failed = await fetch_worker_snapshots(
        state, sections=("metrics", "divergence"))
    if not snaps:
        return web.json_response(
            {"error": "no worker snapshots reachable",
             "workers_failed": failed}, status=503)
    _note_divergence(federation.divergence_report(snaps))
    families = federation.merge_metric_families(
        {int(s["worker"]): s.get("metrics") or [] for s in snaps})
    return web.Response(body=federation.render_exposition(families),
                        content_type="text/plain", charset="utf-8")


def _ring_by_worker(snaps: List[dict], section: str,
                    key: str) -> Dict[int, list]:
    return {int(s["worker"]): (s.get(section) or {}).get(key) or []
            for s in snaps}


def add_federated_debug_routes(router, state) -> None:
    """Multi-worker replacements for the list-view debug routes: same
    paths and filters as the single-worker handlers in ``obs/debug.py``,
    plus a 400-validated ``?worker=`` filter, with every merged record
    stamped ``worker=<id>`` newest-first. Gating matches single-worker
    registration (loop only with --loop-monitor, economics only with
    --fleet-cache) so flag-off still 404s, never half-renders.

    ``/debug/kv/trie`` is NOT federated on purpose: each worker's trie
    is genuinely different state, and pretending to merge them would
    hide exactly the fragmentation the divergence digests measure."""

    async def list_traces(request: web.Request) -> web.Response:
        kwargs = _parse_common_query(request)
        if isinstance(kwargs, web.Response):
            return kwargs
        worker_filter = _parse_worker_query(request, state)
        if isinstance(worker_filter, web.Response):
            return worker_filter
        try:
            min_duration = float(
                request.query.get("min_duration_s", 0) or 0)
        except ValueError:
            return _bad("min_duration_s must be a number")
        limit = kwargs["limit"]
        snaps, failed = await fetch_worker_snapshots(
            state, sections=("traces",), limit=limit)
        rings = _ring_by_worker(snaps, "traces", "traces")
        if worker_filter is not None:
            rings = {worker_filter: rings.get(worker_filter, [])}
        traces = [t for t in federation.merge_rings(
            rings, time_key="start_unix")
            if t.get("duration_s", 0.0) >= min_duration][:limit]
        return web.json_response({
            "workers": sorted(rings),
            "workers_failed": failed,
            "recorded_total": sum(
                (s.get("traces") or {}).get("recorded_total", 0)
                for s in snaps),
            "slow_requests": sum(
                (s.get("traces") or {}).get("slow_requests", 0)
                for s in snaps),
            "traces": traces,
        })

    async def get_trace(request: web.Request) -> web.Response:
        trace_format = request.query.get("format")
        if trace_format and trace_format != "otlp":
            return _bad("format must be otlp")
        trace_id = request.match_info["request_id"]
        snaps, _failed = await fetch_worker_snapshots(
            state, sections=("traces",), limit=1, trace_id=trace_id,
            trace_format="otlp" if trace_format == "otlp" else None)
        for snap in snaps:
            leg = snap.get("traces") or {}
            if trace_format == "otlp":
                if leg.get("trace_otlp") is not None:
                    return web.json_response(
                        {"resourceSpans": [leg["trace_otlp"]],
                         "worker": int(snap["worker"])})
            elif leg.get("trace") is not None:
                body = dict(leg["trace"])
                body["worker"] = int(snap["worker"])
                return web.json_response(body)
        return web.json_response({"error": "trace not found"}, status=404)

    router.add_get("/debug/traces", list_traces)
    router.add_get("/debug/traces/{request_id}", get_trace)

    async def list_events(request: web.Request) -> web.Response:
        kwargs = _parse_common_query(request)
        if isinstance(kwargs, web.Response):
            return kwargs
        worker_filter = _parse_worker_query(request, state)
        if isinstance(worker_filter, web.Response):
            return worker_filter
        kind = request.query.get("kind") or None
        limit = kwargs["limit"]
        snaps, failed = await fetch_worker_snapshots(
            state, sections=("events",), limit=limit)
        rings = _ring_by_worker(snaps, "events", "events")
        if worker_filter is not None:
            rings = {worker_filter: rings.get(worker_filter, [])}
        events = [ev for ev in federation.merge_rings(
            rings, time_key="time_unix")
            if kind is None or ev.get("kind") == kind][:limit]
        if request.query.get("format") == "grafana":
            out = []
            for ev in events:
                tags = [ev["kind"], f"worker={ev['worker']}"]
                if ev.get("endpoint"):
                    tags.append(ev["endpoint"])
                detail = " ".join(
                    f"{k}={v}"
                    for k, v in sorted(ev["attributes"].items()))
                out.append({
                    "time": int(ev["time_unix"] * 1000),
                    "tags": tags,
                    "text": (ev["kind"] if not detail
                             else f"{ev['kind']}: {detail}"),
                })
            return web.json_response(out)
        return web.json_response({
            "workers": sorted(rings),
            "workers_failed": failed,
            "recorded_total": sum(
                (s.get("events") or {}).get("recorded_total", 0)
                for s in snaps),
            "buffered": sum(
                (s.get("events") or {}).get("buffered", 0)
                for s in snaps),
            "kind_counts": federation.sum_counts(
                (s.get("events") or {}).get("kind_counts")
                for s in snaps),
            "events": events,
        })

    router.add_get("/debug/events", list_events)

    if state.loop_monitor is not None:
        async def loop_health(request: web.Request) -> web.Response:
            kwargs = _parse_common_query(request)
            if isinstance(kwargs, web.Response):
                return kwargs
            worker_filter = _parse_worker_query(request, state)
            if isinstance(worker_filter, web.Response):
                return worker_filter
            snaps, failed = await fetch_worker_snapshots(
                state, sections=("loop",),
                lag_window_s=kwargs.get("lag_window_s"),
                blockers=kwargs["blockers"])
            per_worker = {}
            for snap in snaps:
                wid = int(snap["worker"])
                if worker_filter is not None and wid != worker_filter:
                    continue
                per_worker[str(wid)] = snap.get("loop")
            summaries = [v["summary"] for v in per_worker.values() if v]
            return web.json_response({
                "workers": sorted(int(w) for w in per_worker),
                "workers_failed": failed,
                "per_worker": per_worker,
                "merged": {
                    "samples_total": sum(
                        s.get("samples_total", 0) for s in summaries),
                    "stall_s_measured": round(sum(
                        s.get("stall_s_measured", 0.0)
                        for s in summaries), 6),
                    "stalls": federation.sum_counts(
                        s.get("stalls") for s in summaries),
                    "lag_p99_max": max(
                        ((s.get("lag") or {}).get("p99", 0.0)
                         for s in summaries), default=0.0),
                },
            })

        router.add_get("/debug/loop", loop_health)

    if state.fleet is not None:
        async def economics(request: web.Request) -> web.Response:
            kwargs = _parse_common_query(request)
            if isinstance(kwargs, web.Response):
                return kwargs
            worker_filter = _parse_worker_query(request, state)
            if isinstance(worker_filter, web.Response):
                return worker_filter
            limit = kwargs["limit"]
            snaps, failed = await fetch_worker_snapshots(
                state, sections=("kv_economics",), limit=limit)
            rings = _ring_by_worker(snaps, "kv_economics", "records")
            if worker_filter is not None:
                rings = {worker_filter: rings.get(worker_filter, [])}
            per_worker = {
                str(int(s["worker"])):
                    (s.get("kv_economics") or {}).get("summary")
                for s in snaps}
            summed = {}
            for field in ("recorded_total", "wins", "losses",
                          "net_seconds_saved_total", "bytes_moved_total",
                          "tokens_saved_total", "pull_seconds_total"):
                summed[field] = round(sum(
                    (v or {}).get(field, 0) for v in per_worker.values()
                ), 6)
            return web.json_response({
                "workers": sorted(rings),
                "workers_failed": failed,
                "summary": summed,
                "per_worker": per_worker,
                "records": federation.merge_rings(
                    rings, time_key="t", limit=limit),
            })

        router.add_get("/debug/kv/economics", economics)


# ---------------------------------------------------------------------------
# Pre-fork runner
# ---------------------------------------------------------------------------


async def _serve_worker(args, wid: int, uds_path: str) -> None:
    # Imported here, not at module top: app.py imports this module's
    # handlers, and the runner is only reached from main().
    from production_stack_tpu.router.app import build_app

    app = build_app(args)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, args.host, args.port, reuse_port=True,
                       backlog=4096)
    await site.start()
    uds_site = web.UnixSite(runner, uds_path)
    await uds_site.start()
    logger.info("Router worker %d/%d listening on %s:%d (pid %d, uds %s)",
                wid, args.router_workers, args.host, args.port,
                os.getpid(), uds_path)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    try:
        await stop.wait()
    finally:
        await runner.cleanup()


def _worker_main(args, wid: int, uds_paths: Tuple[str, ...]) -> None:
    # Worker identity rides on private args attributes so build_app /
    # initialize_all stay signature-compatible with every existing
    # caller (tests build apps without going through the runner).
    args._worker_id = wid
    args._worker_uds = uds_paths
    asyncio.run(_serve_worker(args, wid, uds_paths[wid]))


def _terminate_children(pids: List[int], grace_s: float = 5.0) -> None:
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
    deadline = time.monotonic() + grace_s
    remaining = set(pids)
    while remaining and time.monotonic() < deadline:
        for pid in list(remaining):
            try:
                done, _status = os.waitpid(pid, os.WNOHANG)
            except ChildProcessError:
                remaining.discard(pid)
                continue
            if done:
                remaining.discard(pid)
        if remaining:
            time.sleep(0.05)
    for pid in remaining:  # leak-free teardown even for a hung worker
        try:
            os.kill(pid, signal.SIGKILL)
            os.waitpid(pid, 0)
        except (ProcessLookupError, ChildProcessError):
            pass


def run_multi_worker(args) -> None:
    """Fork ``--router-workers`` processes BEFORE any app state exists
    (build_app starts scraper threads and asyncio machinery that must
    not cross a fork), serve until signaled, reap leak-free."""
    workers = int(getattr(args, "router_workers", 1) or 1)
    uds_dir = tempfile.mkdtemp(prefix="tpu-router-workers-")
    uds_paths = tuple(os.path.join(uds_dir, f"worker-{wid}.sock")
                      for wid in range(workers))
    children: List[int] = []
    for wid in range(1, workers):
        pid = os.fork()
        if pid == 0:
            try:
                _worker_main(args, wid, uds_paths)
            finally:
                os._exit(0)
        children.append(pid)
    try:
        _worker_main(args, 0, uds_paths)
    finally:
        _terminate_children(children)
        shutil.rmtree(uds_dir, ignore_errors=True)
