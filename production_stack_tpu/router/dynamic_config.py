"""Dynamic router configuration: hot reload from a watched file.

Rebuild of reference ``src/vllm_router/dynamic_config.py`` (310 LoC):
a thread polls a YAML/JSON config file every N seconds; when the content
changes, service discovery / routing logic / callbacks are reconfigured in
place (reference ``DynamicRouterConfig:43-117``, ``reconfigure_all:236-244``,
``_watch_worker:256-280``). ``/health`` exposes the watcher's liveness and
the current config is served at ``/dynamic_config``.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Optional

import yaml

from production_stack_tpu.router.parser import expand_static_models_config
from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.misc import (
    parse_comma_separated_args,
    parse_static_aliases,
    parse_static_model_types,
    parse_static_urls,
)

logger = init_logger(__name__)

_global_watcher: Optional["DynamicConfigWatcher"] = None


@dataclasses.dataclass
class DynamicRouterConfig:
    """Hot-reloadable subset of router config (reference :43-117)."""

    service_discovery: Optional[str] = None
    static_backends: Optional[str] = None
    static_models: Optional[str] = None
    static_aliases: Optional[str] = None
    static_model_labels: Optional[str] = None
    static_model_types: Optional[str] = None
    routing_logic: Optional[str] = None
    session_key: Optional[str] = None
    prefill_model_labels: Optional[str] = None
    decode_model_labels: Optional[str] = None
    callbacks: Optional[str] = None
    qos_tenants_file: Optional[str] = None

    @staticmethod
    def from_file(path: str) -> "DynamicRouterConfig":
        with open(path) as f:
            if path.endswith((".yaml", ".yml")):
                raw = yaml.safe_load(f) or {}
            else:
                raw = json.load(f)
        raw = expand_static_models_config(raw)
        fields = {f.name for f in dataclasses.fields(DynamicRouterConfig)}
        kwargs = {
            k.replace("-", "_"): v
            for k, v in raw.items()
            if k.replace("-", "_") in fields
        }
        return DynamicRouterConfig(**kwargs)

    def to_json_str(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def reconfigure_service_discovery(config: DynamicRouterConfig, state) -> None:
    from production_stack_tpu.router.service_discovery import (
        ServiceDiscoveryType,
        initialize_service_discovery,
    )

    if config.static_backends is None:
        return
    old = state.service_discovery
    sd = initialize_service_discovery(
        ServiceDiscoveryType.STATIC,
        urls=parse_static_urls(config.static_backends),
        models=parse_comma_separated_args(config.static_models) or [],
        aliases=parse_static_aliases(config.static_aliases or ""),
        model_labels=parse_comma_separated_args(config.static_model_labels),
        model_types=parse_static_model_types(config.static_model_types)
        if config.static_model_types else None,
    )
    state.service_discovery = sd
    if old is not None and old is not sd:
        old.close()


def reconfigure_routing_logic(config: DynamicRouterConfig, state) -> None:
    from production_stack_tpu.router import routing_logic as rl

    if config.routing_logic is None:
        return
    state.router = rl.reconfigure_routing_logic(
        config.routing_logic,
        session_key=config.session_key,
        prefill_model_labels=parse_comma_separated_args(
            config.prefill_model_labels
        ),
        decode_model_labels=parse_comma_separated_args(
            config.decode_model_labels
        ),
    )


def reconfigure_qos(config: DynamicRouterConfig, state) -> None:
    """Point the QoS gate at a (new) tenants file, building one if the
    dynamic config introduces QoS on a router started without it."""
    if config.qos_tenants_file is None:
        return
    if state.qos is not None and \
            state.qos.tenants_file == config.qos_tenants_file:
        state.qos.maybe_reload(force=True)
        return
    from production_stack_tpu.qos import QoSGate

    state.qos = QoSGate(config.qos_tenants_file)
    logger.info("QoS gate (re)configured from dynamic config: tenants=%s",
                state.qos.registry.names())


def reconfigure_all(config: DynamicRouterConfig, state) -> None:
    reconfigure_service_discovery(config, state)
    reconfigure_routing_logic(config, state)
    reconfigure_qos(config, state)
    if config.callbacks:
        from production_stack_tpu.router.callbacks import configure_custom_callbacks

        state.callbacks = configure_custom_callbacks(config.callbacks)


class DynamicConfigWatcher:
    """Polls the config file and hot-applies diffs (reference :120-288)."""

    def __init__(
        self,
        config_path: str,
        state,
        poll_interval: float = 10.0,
    ):
        self.config_path = config_path
        self.state = state
        self.poll_interval = poll_interval
        self._current: Optional[DynamicRouterConfig] = None
        self._running = True
        self._thread = threading.Thread(
            target=self._watch_worker, daemon=True, name="dynamic-config"
        )
        self._thread.start()

    def get_current_config(self) -> Optional[DynamicRouterConfig]:
        return self._current

    def _watch_worker(self) -> None:
        while self._running:
            try:
                config = DynamicRouterConfig.from_file(self.config_path)
                if (
                    self._current is None
                    or config.to_json_str() != self._current.to_json_str()
                ):
                    logger.info(
                        "Dynamic config changed; reconfiguring router"
                    )
                    reconfigure_all(config, self.state)
                    self._current = config
            except FileNotFoundError:
                logger.warning(
                    "Dynamic config file %s missing", self.config_path
                )
            except Exception as e:  # noqa: BLE001
                logger.error("Dynamic config reload failed: %s", e)
            try:
                # The tenants file is watched from the same poll loop: a
                # gate built at startup (--qos-tenants-file) hot-reloads
                # here even when the dynamic config itself never changes.
                if getattr(self.state, "qos", None) is not None:
                    self.state.qos.maybe_reload(force=True)
            except Exception as e:  # noqa: BLE001
                logger.error("QoS tenants reload failed: %s", e)
            for _ in range(int(self.poll_interval * 10)):
                if not self._running:
                    return
                time.sleep(0.1)

    def get_health(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._running = False


def initialize_dynamic_config_watcher(
    config_path: str, state, poll_interval: float = 10.0
) -> DynamicConfigWatcher:
    global _global_watcher
    _global_watcher = DynamicConfigWatcher(config_path, state, poll_interval)
    return _global_watcher


def get_dynamic_config_watcher() -> Optional[DynamicConfigWatcher]:
    return _global_watcher
