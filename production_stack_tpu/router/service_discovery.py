"""Engine service discovery: who are the engines, what do they serve.

Rebuild of reference ``src/vllm_router/service_discovery.py:178-1176``:

- :class:`StaticServiceDiscovery` -- fixed URL list with optional periodic
  real-inference health probes (reference ``:206-342``).
- :class:`K8sPodIPServiceDiscovery` -- watches pods by label selector and
  routes to pod IPs (reference ``:344-760``). The reference uses the
  ``kubernetes`` client; that package is not in this image, so we ship a
  minimal raw K8s API client (service-account token + watch stream) in
  :mod:`production_stack_tpu.router.k8s_client`.

Endpoints carry ``sleep`` status (reference ``:414-496``) so sleeping engines
can be excluded from routing, and prefill/decode model labels for
disaggregated prefill (reference ``:321-341``).
"""

from __future__ import annotations

import abc
import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import requests

from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.misc import ModelType, is_model_healthy

logger = init_logger(__name__)

_global_service_discovery: Optional["ServiceDiscovery"] = None


class ServiceDiscoveryType(enum.Enum):
    STATIC = "static"
    K8S_POD_IP = "k8s"
    K8S_SERVICE_NAME = "k8s_service_name"


@dataclass
class EndpointInfo:
    """One engine endpoint (reference service_discovery.py:178-203)."""

    url: str
    model_names: List[str] = field(default_factory=list)
    added_timestamp: float = field(default_factory=time.time)
    model_label: Optional[str] = None
    model_type: str = "chat"
    sleep: bool = False
    pod_name: Optional[str] = None
    namespace: Optional[str] = None
    lora_adapters: List[str] = field(default_factory=list)
    model_aliases: Dict[str, str] = field(default_factory=dict)

    def serves(self, model: str) -> bool:
        return model in self.model_names or model in self.lora_adapters


class ServiceDiscovery(abc.ABC):
    @abc.abstractmethod
    def get_endpoint_info(self) -> List[EndpointInfo]:
        """Snapshot of currently known endpoints."""

    def get_unhealthy_endpoint_hashes(self) -> List[str]:
        return []

    def get_health(self) -> bool:
        return True

    def get_model_names(self) -> List[str]:
        names: List[str] = []
        for ep in self.get_endpoint_info():
            for m in ep.model_names + ep.lora_adapters:
                if m not in names:
                    names.append(m)
        return names

    def get_endpoints_for_model(
        self, model: str, exclude_sleeping: bool = True
    ) -> List[EndpointInfo]:
        return [
            ep
            for ep in self.get_endpoint_info()
            if ep.serves(model) and not (exclude_sleeping and ep.sleep)
        ]

    def close(self) -> None:  # pragma: no cover - trivial
        pass


def _probe_models(url: str, timeout: float = 5.0) -> List[str]:
    """Ask an engine which models it serves (reference :498-531).
    /v1/models is part of the engines' key-gated surface, so the probe
    authenticates with the deployment key when one is configured."""
    from production_stack_tpu.utils.auth import deployment_auth_headers

    try:
        resp = requests.get(f"{url}/v1/models", timeout=timeout,
                            headers=deployment_auth_headers())
        resp.raise_for_status()
        return [m["id"] for m in resp.json().get("data", [])]
    except Exception as e:  # noqa: BLE001
        logger.debug("Model probe failed for %s: %s", url, e)
        return []


def _probe_sleep(url: str, timeout: float = 3.0) -> bool:
    """Query /is_sleeping (reference :443-460)."""
    try:
        resp = requests.get(f"{url}/is_sleeping", timeout=timeout)
        resp.raise_for_status()
        return bool(resp.json().get("is_sleeping", False))
    except Exception:  # noqa: BLE001
        return False


class StaticServiceDiscovery(ServiceDiscovery):
    """Fixed endpoint list (reference service_discovery.py:206-342)."""

    def __init__(
        self,
        urls: List[str],
        models: List[str],
        aliases: Optional[Dict[str, str]] = None,
        model_labels: Optional[List[str]] = None,
        model_types: Optional[List[str]] = None,
        static_backend_health_checks: bool = False,
        prefill_model_labels: Optional[List[str]] = None,
        decode_model_labels: Optional[List[str]] = None,
        health_check_interval: float = 60.0,
    ):
        if len(urls) != len(models):
            raise ValueError("Number of URLs must match number of models")
        self.aliases = aliases or {}
        self.prefill_model_labels = prefill_model_labels or []
        self.decode_model_labels = decode_model_labels or []
        self._lock = threading.Lock()
        self._endpoints: List[EndpointInfo] = []
        for i, (url, model) in enumerate(zip(urls, models)):
            label = model_labels[i] if model_labels else None
            mtype = model_types[i] if model_types else "chat"
            self._endpoints.append(
                EndpointInfo(
                    url=url,
                    model_names=[model],
                    model_label=label,
                    model_type=mtype,
                    model_aliases=self.aliases,
                )
            )
        self._unhealthy: set = set()
        # URLs the router's circuit breaker tripped OPEN for
        # (fault_tolerance.py). Kept separate from the probe-based set so
        # the periodic health sweep's wholesale replacement of
        # self._unhealthy cannot erase breaker state; surfaced together
        # in get_unhealthy_endpoint_hashes(). Breaker-marked URLs stay in
        # get_endpoint_info() — the half-open probe must remain routable;
        # request-level filtering uses breaker.blocked_urls().
        self._breaker_unhealthy: set = set()
        # URLs whose KV-claim lease expired (missed heartbeats — the
        # process is presumed dead, kill -9 / OOM-kill). Unlike the
        # breaker set, these ARE filtered from get_endpoint_info(): a
        # corpse has no half-open probe to keep routable, and the next
        # generation's /kv/register clears the mark atomically.
        self._lease_unhealthy: set = set()
        self._running = True
        self._hc_thread: Optional[threading.Thread] = None
        if static_backend_health_checks:
            self._hc_interval = health_check_interval
            self._hc_thread = threading.Thread(
                target=self._health_check_loop, daemon=True, name="static-health"
            )
            self._hc_thread.start()

    # -- health checking (reference :252-265, utils.py:188-223) ------------
    def _health_check_loop(self) -> None:
        while self._running:
            self._check_health_once()
            for _ in range(int(self._hc_interval * 10)):
                if not self._running:
                    return
                time.sleep(0.1)

    def _check_health_once(self) -> None:
        with self._lock:
            eps = list(self._endpoints)
        unhealthy = set()
        for ep in eps:
            for model in ep.model_names:
                if not is_model_healthy(ep.url, model, ep.model_type):
                    unhealthy.add(ep.url)
        with self._lock:
            self._unhealthy = unhealthy

    def get_unhealthy_endpoint_hashes(self) -> List[str]:
        with self._lock:
            return sorted(
                self._unhealthy | self._breaker_unhealthy | self._lease_unhealthy
            )

    def mark_unhealthy(self, url: str) -> None:
        """Circuit-breaker mirror: report ``url`` unhealthy."""
        with self._lock:
            self._breaker_unhealthy.add(url)

    def clear_unhealthy(self, url: str) -> None:
        with self._lock:
            self._breaker_unhealthy.discard(url)

    def mark_lease_expired(self, url: str) -> None:
        """KV lease-sweeper mirror: ``url`` missed enough heartbeats that
        the controller expired its claims — stop routing to it."""
        with self._lock:
            self._lease_unhealthy.add(url)

    def clear_lease_expired(self, url: str) -> None:
        with self._lock:
            self._lease_unhealthy.discard(url)

    def set_lora_adapters(self, url: str, adapters: List[str]) -> None:
        """AdapterRegistry scrape mirror: refresh the endpoint's resident
        adapter list so ``serves()`` tracks loads/unloads instead of
        keeping the registration-time value forever (an unloaded adapter
        otherwise keeps attracting requests)."""
        url = url.rstrip("/")
        with self._lock:
            for ep in self._endpoints:
                if ep.url.rstrip("/") == url:
                    ep.lora_adapters = list(adapters)

    def get_endpoint_info(self) -> List[EndpointInfo]:
        with self._lock:
            return [
                ep
                for ep in self._endpoints
                if ep.url not in self._unhealthy
                and ep.url not in self._lease_unhealthy
            ]

    def set_sleep_status(self, url: str, sleep: bool) -> None:
        with self._lock:
            for ep in self._endpoints:
                if ep.url == url:
                    ep.sleep = sleep

    def refresh_sleep_status(self) -> None:
        with self._lock:
            eps = list(self._endpoints)
        for ep in eps:
            ep.sleep = _probe_sleep(ep.url)

    def get_health(self) -> bool:
        return self._hc_thread is None or self._hc_thread.is_alive()

    def close(self) -> None:
        self._running = False


class _K8sWatchDiscoveryBase(ServiceDiscovery):
    """Shared machinery for watch-driven K8s discovery: the retry loop,
    endpoint bookkeeping under a lock, reconnect reconciliation (a SNAPSHOT
    event from the client purges endpoints for objects deleted while the
    watch stream was down), and lifecycle."""

    def __init__(
        self,
        namespace: str = "default",
        port: int = 8000,
        label_selector: Optional[str] = None,
        prefill_model_labels: Optional[List[str]] = None,
        decode_model_labels: Optional[List[str]] = None,
        k8s_client=None,
        thread_name: str = "k8s-watch",
    ):
        from production_stack_tpu.router.k8s_client import K8sClient

        self.namespace = namespace
        self.port = port
        self.label_selector = label_selector
        self.prefill_model_labels = prefill_model_labels or []
        self.decode_model_labels = decode_model_labels or []
        self._k8s = k8s_client or K8sClient()
        self._lock = threading.Lock()
        self._endpoints: Dict[str, EndpointInfo] = {}  # object name -> info
        self._running = True
        self._thread = threading.Thread(
            target=self._watch_engines, daemon=True, name=thread_name
        )
        self._thread.start()

    def _watch_stream(self):
        """Yield watch events for the watched resource."""
        raise NotImplementedError

    def _handle_event(self, event: dict) -> None:
        raise NotImplementedError

    def _watch_engines(self) -> None:
        while self._running:
            try:
                for event in self._watch_stream():
                    if not self._running:
                        return
                    if event.get("type") == "SNAPSHOT":
                        self._reconcile(event.get("names") or [])
                    else:
                        self._handle_event(event)
            except Exception as e:  # noqa: BLE001
                logger.warning("K8s watch error (retrying in 2s): %s", e)
                time.sleep(2)

    def _reconcile(self, live_names: List[str]) -> None:
        """Purge endpoints whose objects disappeared during a stream gap."""
        live = set(live_names)
        with self._lock:
            for stale in [n for n in self._endpoints if n not in live]:
                logger.info(
                    "Engine %s gone after watch reconnect, removed", stale)
                del self._endpoints[stale]

    def get_endpoint_info(self) -> List[EndpointInfo]:
        with self._lock:
            return list(self._endpoints.values())

    def set_lora_adapters(self, url: str, adapters: List[str]) -> None:
        """AdapterRegistry scrape mirror (see StaticServiceDiscovery):
        keyed by URL because the registry does not know object names."""
        url = url.rstrip("/")
        with self._lock:
            for ep in self._endpoints.values():
                if ep.url.rstrip("/") == url:
                    ep.lora_adapters = list(adapters)

    def get_health(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._running = False


class K8sPodIPServiceDiscovery(_K8sWatchDiscoveryBase):
    """Watch engine pods via the K8s API, route to pod IPs.

    Reference service_discovery.py:344-760 (_watch_engines:579-630).
    """

    def _watch_stream(self):
        return self._k8s.watch_pods(self.namespace, self.label_selector)

    def _handle_event(self, event: dict) -> None:
        etype = event.get("type")
        pod = event.get("object", {})
        meta = pod.get("metadata", {})
        status = pod.get("status", {})
        name = meta.get("name")
        if not name:
            return
        pod_ip = status.get("podIP")
        ready = _pod_is_ready(status)
        terminating = meta.get("deletionTimestamp") is not None
        if etype == "DELETED" or terminating or not ready or not pod_ip:
            with self._lock:
                if name in self._endpoints:
                    logger.info("Engine pod %s removed from routing", name)
                    del self._endpoints[name]
            return
        url = f"http://{pod_ip}:{self.port}"
        labels = meta.get("labels", {})
        model_label = labels.get("model")
        sleeping = labels.get("sleeping") == "true" or _probe_sleep(url)
        models = _probe_models(url)
        if not models:
            return
        with self._lock:
            self._endpoints[name] = EndpointInfo(
                url=url,
                model_names=models,
                model_label=model_label,
                sleep=sleeping,
                pod_name=name,
                namespace=self.namespace,
            )


class K8sServiceNameServiceDiscovery(_K8sWatchDiscoveryBase):
    """Watch engine *services* via the K8s API, route to service names.

    Reference ``service_discovery.py:762-1176``. Routing goes through the
    cluster's service DNS (namespace-qualified,
    ``http://<service>.<namespace>.svc:<port>``, so cross-namespace
    discovery resolves; the reference uses bare service names), and
    Kubernetes does the pod-level load balancing; advanced per-pod
    strategies (kvaware, PD) and per-pod metrics need 1:1 service-to-pod
    deployments — same caveat as the reference documents. An engine service
    is routable once its Endpoints object has ready addresses;
    ``sleeping=true`` labels (or a live ``/is_sleeping`` probe) exclude it
    from routing.
    """

    def __init__(
        self,
        namespace: str = "default",
        port: int = 8000,
        label_selector: Optional[str] = None,
        prefill_model_labels: Optional[List[str]] = None,
        decode_model_labels: Optional[List[str]] = None,
        k8s_client=None,
        service_url_for=None,
    ):
        # Resolves a service name to its routing URL (injectable for tests
        # and non-standard DNS setups).
        self._url_for = service_url_for or (
            lambda name: f"http://{name}.{namespace}.svc:{port}"
        )
        # name -> requested sleep state while its label patch is in flight.
        # The entry survives patch *failure* so routing stays correct and a
        # stale persisted label can't re-sleep an awake endpoint.
        self._pending_sleep: Dict[str, bool] = {}
        # Monotonic per-service flip counter: a label-patch thread only
        # writes if its flip is still the newest, so two rapid opposite
        # flips can't land out of order.
        self._sleep_gen: Dict[str, int] = {}
        # Serializes the check-then-patch sequence across patch threads.
        self._label_lock = threading.Lock()
        super().__init__(
            namespace=namespace,
            port=port,
            label_selector=label_selector,
            prefill_model_labels=prefill_model_labels,
            decode_model_labels=decode_model_labels,
            k8s_client=k8s_client,
            thread_name="k8s-svc-watch",
        )

    def _watch_stream(self):
        return self._k8s.watch_services(self.namespace, self.label_selector)

    def _reconcile(self, live_names: List[str]) -> None:
        super()._reconcile(live_names)
        live = set(live_names)
        with self._lock:
            for stale in [n for n in self._pending_sleep if n not in live]:
                del self._pending_sleep[stale]
            for stale in [n for n in self._sleep_gen if n not in live]:
                del self._sleep_gen[stale]

    def _service_ready(self, name: str) -> Optional[bool]:
        """True/False from the service's Endpoints addresses (reference
        ``_check_service_ready``, :829-837); None when the API read itself
        failed — callers must NOT conflate that with "not ready"."""
        try:
            endpoints = self._k8s.read_endpoints(self.namespace, name)
        except Exception as e:  # noqa: BLE001
            logger.debug("Endpoints read failed for %s: %s", name, e)
            return None
        for subset in endpoints.get("subsets") or []:
            if subset.get("addresses"):
                return True
        return False

    def _handle_event(self, event: dict) -> None:
        etype = event.get("type")
        service = event.get("object", {})
        meta = service.get("metadata", {})
        name = meta.get("name")
        if not name:
            return
        if etype == "DELETED" or meta.get("deletionTimestamp") is not None:
            with self._lock:
                if name in self._endpoints:
                    logger.info("Engine service %s removed from routing", name)
                    del self._endpoints[name]
                # A retained pending override (kept after patch failures)
                # belongs to THIS incarnation of the service; a recreated
                # namesake must start from its own label/probe state.
                self._pending_sleep.pop(name, None)
                self._sleep_gen.pop(name, None)
            return
        ready = self._service_ready(name)
        if ready is None:
            # Transient API failure: keep current routing state; the next
            # event or reconnect snapshot reconciles.
            return
        if not ready:
            with self._lock:
                self._endpoints.pop(name, None)
            return
        url = self._url_for(name)
        labels = meta.get("labels", {}) or {}
        selector = (service.get("spec", {}) or {}).get("selector") or {}
        model_label = selector.get("model")
        with self._lock:
            pending_sleep = self._pending_sleep.get(name)
        if pending_sleep is not None:
            # The router just flipped this engine's sleep state and the
            # label patch is still in flight — the event's label/probe view
            # is stale and must not resurrect (or re-sleep) the endpoint.
            sleeping = pending_sleep
        else:
            sleeping = labels.get("sleeping") == "true" or _probe_sleep(url)
        models = _probe_models(url)
        if not models:
            return
        with self._lock:
            self._endpoints[name] = EndpointInfo(
                url=url,
                model_names=models,
                model_label=model_label,
                sleep=sleeping,
                pod_name=name,
                namespace=self.namespace,
            )

    # Sleep labels live on the service (reference :899-933).
    def add_sleep_label(self, name: str) -> bool:
        try:
            self._k8s.patch_service_labels(
                self.namespace, name, {"sleeping": "true"})
            return True
        except Exception as e:  # noqa: BLE001
            logger.error("Could not label service %s sleeping: %s", name, e)
            return False

    def remove_sleep_label(self, name: str) -> bool:
        try:
            self._k8s.patch_service_labels(
                self.namespace, name, {"sleeping": None})
            return True
        except Exception as e:  # noqa: BLE001
            logger.error("Could not unlabel service %s: %s", name, e)
            return False

    def set_sleep_status(self, url: str, sleep: bool) -> None:
        """Router-observed sleep flip: update routing now; persist the label
        on the service from a worker thread (this is called from async
        handlers — a slow API server must not stall the event loop)."""
        with self._lock:
            names = [n for n, ep in self._endpoints.items() if ep.url == url]
            gen = {}
            for n in names:
                self._endpoints[n].sleep = sleep
                self._pending_sleep[n] = sleep
                self._sleep_gen[n] = self._sleep_gen.get(n, 0) + 1
                gen[n] = self._sleep_gen[n]
        if names:
            threading.Thread(
                target=self._apply_sleep_labels, args=(names, sleep, gen),
                daemon=True, name="k8s-sleep-label",
            ).start()

    def _apply_sleep_labels(
        self, names: List[str], sleep: bool, gen: Dict[str, int]
    ) -> None:
        for n in names:
            for attempt in range(3):
                with self._label_lock:
                    with self._lock:
                        if self._sleep_gen.get(n) != gen[n]:
                            # A newer flip superseded this one; it owns the
                            # label (and the pending entry) now.
                            break
                    ok = (self.add_sleep_label(n) if sleep
                          else self.remove_sleep_label(n))
                    if ok:
                        with self._lock:
                            # Label state is authoritative again for this
                            # service — unless a newer flip started.
                            if self._sleep_gen.get(n) == gen[n]:
                                self._pending_sleep.pop(n, None)
                        break
                time.sleep(1.0)
            # After exhausted retries the pending override stays: routing
            # keeps the requested state and the stale persisted label is
            # ignored by _handle_event until a later flip rewrites it.


def _pod_is_ready(status: dict) -> bool:
    if status.get("phase") != "Running":
        return False
    for cond in status.get("conditions", []) or []:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def initialize_service_discovery(
    sd_type: ServiceDiscoveryType, **kwargs
) -> ServiceDiscovery:
    global _global_service_discovery
    if sd_type == ServiceDiscoveryType.STATIC:
        _global_service_discovery = StaticServiceDiscovery(**kwargs)
    elif sd_type == ServiceDiscoveryType.K8S_POD_IP:
        _global_service_discovery = K8sPodIPServiceDiscovery(**kwargs)
    elif sd_type == ServiceDiscoveryType.K8S_SERVICE_NAME:
        _global_service_discovery = K8sServiceNameServiceDiscovery(**kwargs)
    else:
        raise ValueError(f"Unsupported service discovery type: {sd_type}")
    return _global_service_discovery


def get_service_discovery() -> ServiceDiscovery:
    if _global_service_discovery is None:
        raise RuntimeError("Service discovery not initialized")
    return _global_service_discovery


def _set_service_discovery_for_test(sd: Optional[ServiceDiscovery]) -> None:
    global _global_service_discovery
    _global_service_discovery = sd
