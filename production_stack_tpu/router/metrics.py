"""Router /metrics: Prometheus exposition of per-engine stats.

Rebuild of reference ``src/vllm_router/routers/metrics_router.py:57-123`` and
``services/metrics_service/prometheus_gauge.py``: per-engine-URL gauges for
QPS, TTFT, latency, ITL, prefill/decode/finished counts, scraped engine-side
running/waiting/cache-usage, plus router-process CPU/mem/disk via psutil and
a healthy-endpoint count.
"""

from __future__ import annotations

from typing import Dict

import psutil
from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

REGISTRY = CollectorRegistry()

_L = ["server"]

# Distribution histograms backing the dashboard's latency/TTFT/ITL
# distribution panels (the reference dashboard reads
# ``vllm:e2e_request_latency_seconds_bucket`` etc. from vLLM; here the
# router observes them itself at proxy level, so they exist even for
# engines scraped through a service mesh). Buckets mirror vLLM's.
hist_ttft = Histogram(
    "vllm_router:time_to_first_token_seconds",
    "Time to first streamed token (s)", _L,
    buckets=(0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1, 0.25, 0.5,
             0.75, 1.0, 2.5, 5.0, 7.5, 10.0, 20.0, 40.0),
    registry=REGISTRY)
hist_latency = Histogram(
    "vllm_router:e2e_request_latency_seconds",
    "End-to-end request latency (s)", _L,
    buckets=(0.3, 0.5, 0.8, 1.0, 1.5, 2.0, 2.5, 5.0, 10.0, 15.0, 20.0,
             30.0, 40.0, 50.0, 60.0),
    registry=REGISTRY)
hist_itl = Histogram(
    "vllm_router:time_per_output_token_seconds",
    "Inter-token latency (s)", _L,
    buckets=(0.01, 0.025, 0.05, 0.075, 0.1, 0.15, 0.2, 0.3, 0.4, 0.5,
             0.75, 1.0, 2.5),
    registry=REGISTRY)
# Router overhead clock: wall time a request spent INSIDE the router
# (routing pick, QoS admission, fleet pull orchestration, tracing,
# response relay) excluding the upstream engine's own time — root span
# minus upstream span from the request trace. ms-scale buckets: this
# measures event-loop work, not model time.
hist_router_overhead = Histogram(
    "vllm_router:router_overhead_seconds",
    "In-router request time excluding upstream engine time (s)", _L,
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
    registry=REGISTRY)

# Trace head-sampling activity (--trace-sample-rate /
# --slow-trace-log-interval-s). Cumulative recorder counts mirrored as
# gauges at scrape time (the TraceRecorder owns the source of truth);
# the _total suffix keeps rate() usable in the dashboard.
trace_sampled_out = Gauge(
    "vllm_router:trace_sampled_out_total",
    "Traces dropped by head sampling (stage rollups still counted)",
    registry=REGISTRY)
slow_trace_logs_suppressed = Gauge(
    "vllm_router:slow_trace_logs_suppressed_total",
    "Slow-trace log lines suppressed by the rate limit "
    "(slow requests are still counted)",
    registry=REGISTRY)

current_qps = Gauge("vllm_router:current_qps", "Sliding-window QPS", _L, registry=REGISTRY)
avg_ttft = Gauge("vllm_router:avg_ttft", "Average time to first token (s)", _L, registry=REGISTRY)
avg_latency = Gauge("vllm_router:avg_latency", "Average request latency (s)", _L, registry=REGISTRY)
avg_itl = Gauge("vllm_router:avg_itl", "Average inter-token latency (s)", _L, registry=REGISTRY)
avg_decoding_length = Gauge("vllm_router:avg_decoding_length", "Average decode phase duration (s)", _L, registry=REGISTRY)
num_prefill_requests = Gauge("vllm_router:num_prefill_requests", "Requests in prefill", _L, registry=REGISTRY)
num_decoding_requests = Gauge("vllm_router:num_decoding_requests", "Requests in decode", _L, registry=REGISTRY)
num_finished_requests = Gauge("vllm_router:num_finished_requests", "Finished requests", _L, registry=REGISTRY)
num_swapped_requests = Gauge("vllm_router:num_swapped_requests", "Swapped requests", _L, registry=REGISTRY)
num_requests_running = Gauge("vllm_router:num_requests_running", "Engine-reported running requests", _L, registry=REGISTRY)
num_requests_waiting = Gauge("vllm_router:num_requests_waiting", "Engine-reported waiting requests", _L, registry=REGISTRY)
kv_cache_usage = Gauge("vllm_router:gpu_cache_usage_perc", "Engine KV cache usage fraction (TPU HBM)", _L, registry=REGISTRY)
prefix_cache_hit_rate = Gauge("vllm_router:gpu_prefix_cache_hit_rate", "Engine prefix cache hit rate", _L, registry=REGISTRY)
healthy_pods = Gauge("vllm_router:healthy_pods_total", "Healthy engine endpoints", registry=REGISTRY)
router_cpu_pct = Gauge("vllm_router:cpu_usage_pct", "Router process CPU percent", registry=REGISTRY)
router_mem_bytes = Gauge("vllm_router:mem_usage_bytes", "Router process RSS bytes", registry=REGISTRY)
router_disk_pct = Gauge("vllm_router:disk_usage_pct", "Disk usage percent of /", registry=REGISTRY)

# --- Multi-tenant QoS (production_stack_tpu/qos/) -------------------------
# Labeled by tenant name; series appear only once a tenant sends traffic,
# so a QoS-less deployment exports nothing here.
tenant_admitted = Counter(
    "vllm_router:tenant_admitted_total",
    "Requests admitted past the tenant token buckets and dispatched",
    ["tenant"], registry=REGISTRY)
tenant_rejected = Counter(
    "vllm_router:tenant_rejected_total",
    "Requests rejected 429 by a tenant token bucket",
    ["tenant", "reason"], registry=REGISTRY)
tenant_shed = Counter(
    "vllm_router:tenant_shed_total",
    "Batch requests shed 503 at the saturated fair queue",
    ["tenant"], registry=REGISTRY)
tenant_queued = Counter(
    "vllm_router:tenant_queued_total",
    "Requests that entered the weighted-fair dispatch queue",
    ["tenant"], registry=REGISTRY)
tenant_queue_wait = Histogram(
    "vllm_router:tenant_queue_wait_seconds",
    "Time spent waiting for a fair-queue dispatch slot (s)",
    ["tenant"],
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 10.0, 30.0, 60.0),
    registry=REGISTRY)
qos_usage_reconciled = Counter(
    "vllm_router:qos_usage_reconciled_tokens_total",
    "Extra tokens debited post-completion when actual streamed usage "
    "exceeded the admission estimate (tenants understating max_tokens)",
    ["tenant"], registry=REGISTRY)

# --- Fault tolerance (production_stack_tpu/router/fault_tolerance.py) ----
# Series appear only with --fault-tolerance on (the retry/failover layer
# does not exist otherwise).
retries_total = Counter(
    "vllm_router:retries_total",
    "Upstream attempts retried (connect error or 5xx before the first "
    "streamed byte)",
    _L, registry=REGISTRY)
failovers_total = Counter(
    "vllm_router:failovers_total",
    "Requests that completed on a different replica than first routed",
    _L, registry=REGISTRY)
circuit_state = Gauge(
    "vllm_router:circuit_state",
    "Per-endpoint circuit breaker state (0 closed, 1 open, 2 half-open)",
    _L, registry=REGISTRY)
engine_stats_stale = Counter(
    "vllm_router:engine_stats_stale_total",
    "Scrape cycles in which an endpoint's engine stats were marked stale "
    "and excluded from routing",
    _L, registry=REGISTRY)

# --- Fleet cache & autoscaling (production_stack_tpu/kv/fleet.py) --------
# Series appear only with --fleet-cache / the autoscale recommender on.
kv_pull_attempts = Counter(
    "vllm_router:kv_pull_attempts_total",
    "Cross-replica KV pulls orchestrated (target asked to pull the "
    "matched prefix from the holder)",
    _L, registry=REGISTRY)
kv_pull_success = Counter(
    "vllm_router:kv_pull_success_total",
    "Cross-replica KV pulls that injected blocks on the target",
    _L, registry=REGISTRY)
kv_pull_failures = Counter(
    "vllm_router:kv_pull_failures_total",
    "Cross-replica KV pulls that missed or failed (target recomputes)",
    ["server", "reason"], registry=REGISTRY)
kv_pull_bytes = Counter(
    "vllm_router:kv_pull_bytes_total",
    "KV bytes moved by successful cross-replica pulls (from the "
    "target's transfer report)",
    _L, registry=REGISTRY)
kv_pull_tokens_saved = Counter(
    "vllm_router:kv_pull_tokens_saved_total",
    "Prompt tokens the target did not have to re-prefill because a "
    "pull injected their KV blocks",
    _L, registry=REGISTRY)
kv_pull_latency = Histogram(
    "vllm_router:kv_pull_latency_seconds",
    "Latency of the /kv/pull control round-trip (s)", _L,
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 10.0, 30.0),
    registry=REGISTRY)
fleet_l3_pulls = Counter(
    "vllm_router:fleet_l3_pulls_total",
    "Pulls whose holder was the shared L3 cache server",
    registry=REGISTRY)
autoscale_recommended_replicas = Gauge(
    "vllm_router:autoscale_recommended_replicas",
    "Replica count the load-predictive recommender asks for",
    registry=REGISTRY)
autoscale_current_replicas = Gauge(
    "vllm_router:autoscale_current_replicas",
    "Replica count the recommender currently observes",
    registry=REGISTRY)

# --- LoRA adapter plane (production_stack_tpu/lora/registry.py) ----------
# Series appear only once the --lora-plane registry acts (loads, evicts,
# or routes an adapter-addressed request), so a plane-off deployment's
# /metrics surface is byte-identical.
lora_loads = Counter(
    "vllm_router:lora_loads_total",
    "Adapter load operations the router drove against engines (fan-out "
    "distribution plus affinity-miss on-demand loads), by adapter",
    ["adapter"], registry=REGISTRY)
lora_evictions = Counter(
    "vllm_router:lora_evictions_total",
    "Adapters the router unloaded to make room (LRU eviction when a "
    "replica's slots are full) or by operator request, by adapter",
    ["adapter"], registry=REGISTRY)
lora_affinity_hits = Counter(
    "vllm_router:lora_affinity_hits_total",
    "Adapter-addressed requests whose routing pick already had the "
    "adapter resident (no load stall on the request path)",
    ["adapter"], registry=REGISTRY)
lora_affinity_misses = Counter(
    "vllm_router:lora_affinity_misses_total",
    "Adapter-addressed requests that picked a replica without the "
    "adapter resident (single-flight on-demand load before proxying)",
    ["adapter"], registry=REGISTRY)
lora_requests = Counter(
    "vllm_router:lora_requests_total",
    "Adapter-addressed requests routed, by adapter and SLO outcome "
    "(additive companion to request_outcomes — the base label set is "
    "unchanged)",
    ["adapter", "outcome"], registry=REGISTRY)

# --- Crash-consistent fleet state (leases / resync / stampede control) ---
kv_controller_instances = Gauge(
    "vllm_router:kv_controller_instances",
    "KV controller instance records by lease state (live/expired/l3)",
    ["state"], registry=REGISTRY)
kv_claims_swept = Counter(
    "vllm_router:kv_claims_swept_total",
    "Prefix claims swept from the controller trie, by cause: expired "
    "(lease timed out), regenerated (same URL re-registered with a new "
    "generation), resync (anti-entropy digest mismatch healed drift)",
    ["reason"], registry=REGISTRY)
kv_pull_rejected = Counter(
    "vllm_router:kv_pull_rejected_total",
    "Cross-replica pulls the router skipped because the holder rejected "
    "admission (503) or the per-holder in-flight cap was reached "
    "(target recomputes instead)",
    _L, registry=REGISTRY)

# --- KV pull economics (production_stack_tpu/kv/economics.py) ------------
# Classified by the pull ledger: a pull WINS when its estimated recompute
# cost (tokens saved / prefill tokens/s) exceeds its wall time, else it
# LOSES — failed and holder-rejected pulls always lose. All labeled by
# target server, so a fleet-off deployment emits no series.
kv_pull_wins = Counter(
    "vllm_router:kv_pull_wins_total",
    "Cross-replica pulls whose estimated recompute cost exceeded the "
    "pull wall time (net latency win)",
    _L, registry=REGISTRY)
kv_pull_losses = Counter(
    "vllm_router:kv_pull_losses_total",
    "Cross-replica pulls that cost more than the recompute they "
    "replaced — including every failed or rejected pull",
    _L, registry=REGISTRY)
# A Gauge, not a Counter: the running signed sum goes DOWN when a pull
# loses money (net = est_recompute_s - pull_s can be negative).
kv_pull_net_seconds_saved = Gauge(
    "vllm_router:kv_pull_net_seconds_saved_total",
    "Running signed sum of per-pull net latency saved (estimated "
    "recompute seconds minus pull wall seconds); negative contributions "
    "from losing pulls included",
    _L, registry=REGISTRY)

# --- SLO engine (production_stack_tpu/router/slo.py) ---------------------
# All labeled: series appear only once the --slo-config classifier or the
# canary prober (--canary-interval) actually observes something, so a
# flag-off deployment's /metrics surface is byte-identical.
request_outcomes = Counter(
    "vllm_router:request_outcomes_total",
    "Requests by terminal outcome against the SLO objectives: ok, slow "
    "(violated a latency objective), shed (admission control), failed "
    "(upstream error), client_abort (client went away first)",
    ["outcome", "tenant", "model"], registry=REGISTRY)
goodput_ratio = Gauge(
    "vllm_router:goodput_ratio",
    "Share of requests classified ok over the trailing window "
    "(scrape-time refresh from the SLO engine's outcome ring)",
    ["window"], registry=REGISTRY)
canary_probes = Counter(
    "vllm_router:canary_probes_total",
    "Synthetic canary completions issued per replica "
    "(--canary-interval; probes bypass QoS, fleet pulls, and the "
    "prefix-cache trie)",
    _L, registry=REGISTRY)
canary_failures = Counter(
    "vllm_router:canary_failures_total",
    "Canary probes that failed, by reason (connect, timeout, empty, "
    "status_NNN)",
    ["server", "reason"], registry=REGISTRY)
canary_ttft = Histogram(
    "vllm_router:canary_ttft_seconds",
    "Time to first streamed byte of a canary probe (s)", _L,
    buckets=(0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1, 0.25, 0.5,
             0.75, 1.0, 2.5, 5.0, 7.5, 10.0, 20.0, 40.0),
    registry=REGISTRY)

# --- Event-loop introspection (obs/looplag.py, --loop-monitor) -----------
# All labeled (stat / bucket / component): series appear only once the
# monitor mirrors its first rollup at scrape time, so a flag-off
# deployment's /metrics surface stays byte-identical (same convention as
# the SLO block above). Cumulative values are mirrored as gauges with a
# _total-suffixed name so rate() stays usable (trace_sampled_out
# precedent).
event_loop_lag = Gauge(
    "vllm_router:event_loop_lag_seconds",
    "Event-loop scheduling lag of the router process: how late the "
    "monitor's periodic tick fired. stat=sum|count are lifetime "
    "accumulators (rate(sum)/rate(count) = mean lag); stat=p50|p99|max "
    "are rollups over the in-memory ring window",
    ["stat"], registry=REGISTRY)
loop_stalls = Gauge(
    "vllm_router:loop_stalls_total",
    "Event-loop stalls (tick lag >= --loop-stall-threshold-ms) by "
    "severity bucket, a multiple of the threshold (1x/5x/20x, disjoint "
    "— each stall increments the highest bucket it reached)",
    ["bucket"], registry=REGISTRY)
loop_component_seconds = Gauge(
    "vllm_router:loop_component_seconds_total",
    "Cumulative on-loop CPU seconds per instrumented router component "
    "(qos_admission, fleet_pull, kv_controller, streaming_relay, "
    "relay_feed, slo_classify, metrics_scrape): synchronous slices that "
    "actually held the loop, awaited time excluded",
    ["component"], registry=REGISTRY)


# --- Relay pump tier (router/relay.py, --relay-off-loop) -----------------
# All labeled (server / reason / pool): counters first increment, and the
# pool gauges first mirror, only when a RelayPump exists — a flag-off
# deployment's /metrics surface stays byte-identical (same convention as
# the loop block above).
relay_bytes = Counter(
    "vllm_router:relay_bytes_total",
    "Response payload bytes moved to clients by the relay pump tier "
    "(off-loop socket writes; chunked framing overhead excluded), per "
    "backend server the stream came from",
    _L, registry=REGISTRY)
relay_chunks = Counter(
    "vllm_router:relay_chunks_total",
    "Upstream chunks delivered by the relay pump tier, per backend "
    "server (compare with the flag-off path where every one of these "
    "was an await response.write() on the event loop)",
    _L, registry=REGISTRY)
relay_handoff_failures = Counter(
    "vllm_router:relay_handoff_failures_total",
    "Committed streams that could NOT be handed to a pump and fell "
    "back to the on-loop relay, by reason (tls, no_transport, "
    "no_socket, compression, buffer_not_drained, dup_failed, "
    "pump_not_running). The fallback keeps responses byte-identical; "
    "a sustained rate means the flag is on but not paying",
    ["reason"], registry=REGISTRY)
relay_active_pumps = Gauge(
    "vllm_router:relay_active_pumps",
    "Live pump worker threads in this router process "
    "(--relay-pump-threads; mirrored at scrape time while the relay "
    "tier is enabled)",
    ["pool"], registry=REGISTRY)
relay_queue_depth = Gauge(
    "vllm_router:relay_queue_depth",
    "In-flight relay jobs (committed streams currently owned by a pump "
    "thread) across the process's pump pool, mirrored at scrape time",
    ["pool"], registry=REGISTRY)


def mirror_relay_metrics(relay) -> None:
    """Scrape-time mirror of the RelayPump's pool state (counters are
    settled per request by the jobs themselves)."""
    stats = relay.stats()
    relay_active_pumps.labels(pool="router").set(stats["active_pumps"])
    relay_queue_depth.labels(pool="router").set(stats["queue_depth"])


def mirror_loop_metrics(monitor) -> None:
    """Scrape-time mirror of the LoopMonitor's counters and rollups
    (the monitor owns the source of truth; /debug/loop, this exposition,
    and the saturation artifact all read the same numbers)."""
    pct = monitor.percentiles()
    event_loop_lag.labels(stat="sum").set(round(monitor.lag_s_sum, 6))
    event_loop_lag.labels(stat="count").set(monitor.samples_total)
    event_loop_lag.labels(stat="p50").set(pct["p50"])
    event_loop_lag.labels(stat="p99").set(pct["p99"])
    event_loop_lag.labels(stat="max").set(pct["max"])
    for bucket, count in monitor.stalls().items():
        loop_stalls.labels(bucket=bucket).set(count)
    for comp, secs in monitor.components.snapshot().items():
        loop_component_seconds.labels(component=comp).set(round(secs, 6))


_PROCESS = psutil.Process()


def update_gauges(endpoints, engine_stats: Dict, request_stats: Dict,
                  fault_tolerance=None) -> None:
    """Refresh all gauges from the current stat snapshots.

    Called from both the /metrics handler and the periodic stats logger
    (reference log_stats.py re-sets gauges too, :37-115).
    """
    healthy_pods.set(len(endpoints))
    for url, stats in (request_stats or {}).items():
        current_qps.labels(server=url).set(stats.qps)
        avg_ttft.labels(server=url).set(stats.ttft)
        avg_latency.labels(server=url).set(stats.avg_latency)
        avg_itl.labels(server=url).set(stats.avg_itl)
        avg_decoding_length.labels(server=url).set(stats.avg_decoding_length)
        num_prefill_requests.labels(server=url).set(stats.in_prefill_requests)
        num_decoding_requests.labels(server=url).set(stats.in_decoding_requests)
        num_finished_requests.labels(server=url).set(stats.finished_requests)
        num_swapped_requests.labels(server=url).set(stats.num_swapped_requests)
    for url, stats in (engine_stats or {}).items():
        num_requests_running.labels(server=url).set(stats.num_running_requests)
        num_requests_waiting.labels(server=url).set(stats.num_queuing_requests)
        kv_cache_usage.labels(server=url).set(stats.gpu_cache_usage_perc)
        prefix_cache_hit_rate.labels(server=url).set(stats.gpu_prefix_cache_hit_rate)
    if fault_tolerance is not None:
        for url, value in fault_tolerance.breaker.snapshot().items():
            circuit_state.labels(server=url).set(value)
    router_cpu_pct.set(_PROCESS.cpu_percent(interval=None))
    router_mem_bytes.set(_PROCESS.memory_info().rss)
    try:
        router_disk_pct.set(psutil.disk_usage("/").percent)
    except OSError:
        pass


# --- Multi-worker federation (--router-workers, obs/federation.py) -------
# All labeled: series appear only when the pre-fork plane actually runs a
# fan-in, so a single-worker deployment's /metrics surface stays
# byte-identical (flag-off parity, same convention as the SLO and loop
# blocks above).
worker_state_divergence = Counter(
    "vllm_router:worker_state_divergence_total",
    "Fan-in rounds (aggregated /metrics scrape or /debug/workers read) "
    "in which the named shared-state digest differed across router "
    "workers: kind=breaker_view (circuit breaker states) or "
    "kind=trie_digest (KV controller claim sets). Divergence is expected "
    "under --router-workers — each process holds its own copy — this "
    "counter measures how often, as evidence for the state-service "
    "split",
    ["kind"], registry=REGISTRY)
worker_snapshot_errors = Counter(
    "vllm_router:worker_snapshot_errors_total",
    "Per-worker GET /debug/snapshot fan-in fetches that failed (worker "
    "dead, UDS gone, timeout); the merged view is served from the "
    "workers that answered",
    ["worker"], registry=REGISTRY)


def registry_snapshot() -> list:
    """The whole registry as JSON-serializable sample families — the
    metrics leg of a worker's /debug/snapshot body, merged across
    workers by ``obs/federation.py:merge_metric_families`` (which stays
    stdlib-only; prometheus_client is only imported here)."""
    out = []
    for family in REGISTRY.collect():
        out.append({
            "name": family.name,
            "type": family.type,
            "documentation": family.documentation,
            "samples": [[s.name, dict(s.labels), s.value]
                        for s in family.samples],
        })
    return out


def render_metrics() -> bytes:
    return generate_latest(REGISTRY)
