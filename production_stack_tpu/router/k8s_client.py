"""Minimal Kubernetes API client (list/watch pods, patch labels).

The reference router depends on the official ``kubernetes`` Python client
(``src/vllm_router/service_discovery.py:344-760``); that package is not in
this image, so this module speaks the K8s REST API directly: in-cluster
service-account token + CA, or an explicit host for tests. Only the three
operations the stack needs are implemented: list pods, watch pods
(streaming JSON events), and patch pod labels (used to mark ``sleeping``).
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Optional

import requests

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sClient:
    def __init__(
        self,
        host: Optional[str] = None,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
    ):
        if host is None:
            k8s_host = os.environ.get("KUBERNETES_SERVICE_HOST")
            k8s_port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not k8s_host:
                raise RuntimeError(
                    "Not running in a cluster and no K8s host provided"
                )
            host = f"https://{k8s_host}:{k8s_port}"
        self.host = host.rstrip("/")
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
        self.token = token
        if ca_cert is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            ca_cert = f"{SA_DIR}/ca.crt"
        self.verify = ca_cert if ca_cert else False
        self.session = requests.Session()
        if self.token:
            self.session.headers["Authorization"] = f"Bearer {self.token}"

    # -- generic core-v1 resource operations ------------------------------
    def _list(self, namespace: str, plural: str,
              label_selector: Optional[str]) -> dict:
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        resp = self.session.get(
            f"{self.host}/api/v1/namespaces/{namespace}/{plural}",
            params=params,
            verify=self.verify,
            timeout=30,
        )
        resp.raise_for_status()
        return resp.json()

    def _watch(self, namespace: str, plural: str,
               label_selector: Optional[str],
               timeout_seconds: int) -> Iterator[dict]:
        """Stream watch events. Yields a synthetic SNAPSHOT event naming the
        currently live objects first (so consumers can purge state for
        objects deleted while the stream was down), then replays the current
        objects as ADDED, then streams."""
        current = self._list(namespace, plural, label_selector)
        resource_version = current.get("metadata", {}).get("resourceVersion")
        items = current.get("items", [])
        yield {
            "type": "SNAPSHOT",
            "names": [
                o.get("metadata", {}).get("name")
                for o in items
                if o.get("metadata", {}).get("name")
            ],
        }
        for obj in items:
            yield {"type": "ADDED", "object": obj}
        params = {
            "watch": "true",
            "timeoutSeconds": str(timeout_seconds),
        }
        if label_selector:
            params["labelSelector"] = label_selector
        if resource_version:
            params["resourceVersion"] = resource_version
        resp = self.session.get(
            f"{self.host}/api/v1/namespaces/{namespace}/{plural}",
            params=params,
            verify=self.verify,
            stream=True,
            timeout=timeout_seconds + 10,
        )
        resp.raise_for_status()
        for line in resp.iter_lines():
            if line:
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("Malformed watch line: %r", line[:200])

    def _patch_labels(self, namespace: str, plural: str, name: str,
                      labels: dict) -> None:
        resp = self.session.patch(
            f"{self.host}/api/v1/namespaces/{namespace}/{plural}/{name}",
            json={"metadata": {"labels": labels}},
            headers={"Content-Type": "application/merge-patch+json"},
            verify=self.verify,
            timeout=30,
        )
        resp.raise_for_status()

    # -- pods --------------------------------------------------------------
    def list_pods(self, namespace: str, label_selector: Optional[str] = None) -> dict:
        return self._list(namespace, "pods", label_selector)

    def watch_pods(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        timeout_seconds: int = 300,
    ) -> Iterator[dict]:
        """Stream pod watch events. Replays current pods as ADDED first."""
        return self._watch(namespace, "pods", label_selector, timeout_seconds)

    def patch_pod_labels(self, namespace: str, pod_name: str, labels: dict) -> None:
        """Merge-patch labels on a pod (reference labels pods sleeping=true)."""
        self._patch_labels(namespace, "pods", pod_name, labels)

    # -- services / endpoints (service-name discovery) ---------------------
    def list_services(
        self, namespace: str, label_selector: Optional[str] = None
    ) -> dict:
        return self._list(namespace, "services", label_selector)

    def watch_services(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        timeout_seconds: int = 300,
    ) -> Iterator[dict]:
        """Stream service watch events (current services replay as ADDED)."""
        return self._watch(
            namespace, "services", label_selector, timeout_seconds)

    def read_endpoints(self, namespace: str, name: str) -> dict:
        """The Endpoints object backing a service (readiness signal)."""
        resp = self.session.get(
            f"{self.host}/api/v1/namespaces/{namespace}/endpoints/{name}",
            verify=self.verify,
            timeout=30,
        )
        resp.raise_for_status()
        return resp.json()

    def patch_service_labels(
        self, namespace: str, name: str, labels: dict
    ) -> None:
        """Merge-patch labels on a service (sleeping=true marker)."""
        self._patch_labels(namespace, "services", name, labels)
