"""Minimal Kubernetes API client (list/watch pods, patch labels).

The reference router depends on the official ``kubernetes`` Python client
(``src/vllm_router/service_discovery.py:344-760``); that package is not in
this image, so this module speaks the K8s REST API directly: in-cluster
service-account token + CA, or an explicit host for tests. Only the three
operations the stack needs are implemented: list pods, watch pods
(streaming JSON events), and patch pod labels (used to mark ``sleeping``).
"""

from __future__ import annotations

import json
import os
from typing import Iterator, Optional

import requests

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sClient:
    def __init__(
        self,
        host: Optional[str] = None,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
    ):
        if host is None:
            k8s_host = os.environ.get("KUBERNETES_SERVICE_HOST")
            k8s_port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not k8s_host:
                raise RuntimeError(
                    "Not running in a cluster and no K8s host provided"
                )
            host = f"https://{k8s_host}:{k8s_port}"
        self.host = host.rstrip("/")
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
        self.token = token
        if ca_cert is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            ca_cert = f"{SA_DIR}/ca.crt"
        self.verify = ca_cert if ca_cert else False
        self.session = requests.Session()
        if self.token:
            self.session.headers["Authorization"] = f"Bearer {self.token}"

    def list_pods(self, namespace: str, label_selector: Optional[str] = None) -> dict:
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        resp = self.session.get(
            f"{self.host}/api/v1/namespaces/{namespace}/pods",
            params=params,
            verify=self.verify,
            timeout=30,
        )
        resp.raise_for_status()
        return resp.json()

    def watch_pods(
        self,
        namespace: str,
        label_selector: Optional[str] = None,
        timeout_seconds: int = 300,
    ) -> Iterator[dict]:
        """Stream pod watch events. Replays current pods as ADDED first."""
        current = self.list_pods(namespace, label_selector)
        resource_version = current.get("metadata", {}).get("resourceVersion")
        for pod in current.get("items", []):
            yield {"type": "ADDED", "object": pod}
        params = {
            "watch": "true",
            "timeoutSeconds": str(timeout_seconds),
        }
        if label_selector:
            params["labelSelector"] = label_selector
        if resource_version:
            params["resourceVersion"] = resource_version
        resp = self.session.get(
            f"{self.host}/api/v1/namespaces/{namespace}/pods",
            params=params,
            verify=self.verify,
            stream=True,
            timeout=timeout_seconds + 10,
        )
        resp.raise_for_status()
        for line in resp.iter_lines():
            if line:
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("Malformed watch line: %r", line[:200])

    def patch_pod_labels(self, namespace: str, pod_name: str, labels: dict) -> None:
        """Merge-patch labels on a pod (reference labels pods sleeping=true)."""
        resp = self.session.patch(
            f"{self.host}/api/v1/namespaces/{namespace}/pods/{pod_name}",
            json={"metadata": {"labels": labels}},
            headers={"Content-Type": "application/merge-patch+json"},
            verify=self.verify,
            timeout=30,
        )
        resp.raise_for_status()
