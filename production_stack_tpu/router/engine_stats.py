"""Engine-side statistics scraper: polls each engine's /metrics.

Rebuild of reference ``src/vllm_router/stats/engine_stats.py`` (218 LoC):
parses the ``vllm:*`` Prometheus exposition every engine serves —
``num_requests_running`` / ``num_requests_waiting`` / cache usage / prefix
cache hit counters (reference ``EngineStats.from_vllm_scrape:42-85``) — on a
daemon thread (reference ``_scrape_worker:171-182``).

TPU note (SURVEY §5): our engines report **TPU HBM KV usage** as
``vllm:gpu_cache_usage_perc`` for dashboard compatibility and additionally as
``tpu:hbm_kv_usage_perc``; the scraper accepts either name.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

import requests
from prometheus_client.parser import text_string_to_metric_families

from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.misc import SingletonMeta

logger = init_logger(__name__)


@dataclass
class EngineStats:
    num_running_requests: int = 0
    num_queuing_requests: int = 0
    gpu_prefix_cache_hits: int = 0
    gpu_prefix_cache_queries: int = 0
    gpu_cache_usage_perc: float = 0.0  # on TPU: HBM KV pool usage fraction
    gpu_prefix_cache_hit_rate: float = 0.0
    hbm_headroom_bytes: float = -1.0  # free HBM beyond pool+weights; -1 unknown

    @staticmethod
    def from_vllm_scrape(metrics_text: str) -> "EngineStats":
        """Parse a vLLM-compatible /metrics exposition (reference :42-85)."""
        stats = EngineStats()
        hits = queries = 0.0
        for family in text_string_to_metric_families(metrics_text):
            for sample in family.samples:
                name = sample.name
                value = sample.value
                if name == "vllm:num_requests_running":
                    stats.num_running_requests = int(value)
                elif name == "vllm:num_requests_waiting":
                    stats.num_queuing_requests = int(value)
                elif name in (
                    "vllm:gpu_cache_usage_perc",
                    "tpu:hbm_kv_usage_perc",
                ):
                    stats.gpu_cache_usage_perc = float(value)
                elif name == "tpu:hbm_headroom_bytes":
                    # Autoscale signal (kv/fleet.py recommender).
                    stats.hbm_headroom_bytes = float(value)
                elif name in (
                    "vllm:gpu_prefix_cache_hits_total",
                    "tpu:prefix_cache_hits_total",
                ):
                    hits = value
                elif name in (
                    "vllm:gpu_prefix_cache_queries_total",
                    "tpu:prefix_cache_queries_total",
                ):
                    queries = value
        stats.gpu_prefix_cache_hits = int(hits)
        stats.gpu_prefix_cache_queries = int(queries)
        if queries > 0:
            stats.gpu_prefix_cache_hit_rate = hits / queries
        return stats


class EngineStatsScraper(metaclass=SingletonMeta):
    """Daemon thread scraping every engine's /metrics (reference :88-218)."""

    # Consecutive scrape failures before an endpoint's stats are marked
    # stale and withheld from routing decisions. Below the threshold the
    # last-known stats carry forward (one dropped scrape should not make
    # a kvaware/least-loaded router forget a replica); at or above it,
    # stale numbers are worse than none — the routing logic falls back
    # to its no-stats behavior for that replica.
    STALE_AFTER = 3

    def __init__(self, scrape_interval: float = 10.0):
        if hasattr(self, "_initialized"):
            return
        self._initialized = True
        self.scrape_interval = scrape_interval
        self._stats: Dict[str, EngineStats] = {}
        self._lock = threading.Lock()
        self._running = True
        self._fail_counts: Dict[str, int] = {}
        self._stale: set = set()
        self._thread = threading.Thread(
            target=self._scrape_worker, daemon=True, name="engine-stats-scraper"
        )
        self._thread.start()

    def _scrape_worker(self) -> None:
        from production_stack_tpu.router.service_discovery import (
            get_service_discovery,
        )

        while self._running:
            try:
                endpoints = get_service_discovery().get_endpoint_info()
            except RuntimeError:
                endpoints = []
            fresh: Dict[str, EngineStats] = {}
            stale: set = set()
            with self._lock:
                previous = dict(self._stats)
            for ep in endpoints:
                stats = self._scrape_one(ep.url)
                if stats is not None:
                    fresh[ep.url] = stats
                    self._fail_counts[ep.url] = 0
                    continue
                failures = self._fail_counts.get(ep.url, 0) + 1
                self._fail_counts[ep.url] = failures
                if failures < self.STALE_AFTER and ep.url in previous:
                    # Grace window: carry the last-known stats forward.
                    fresh[ep.url] = previous[ep.url]
                else:
                    stale.add(ep.url)
                    self._count_stale(ep.url)
            # Forget counters for endpoints discovery no longer reports.
            live = {ep.url for ep in endpoints}
            for url in [u for u in self._fail_counts if u not in live]:
                del self._fail_counts[url]
            with self._lock:
                self._stats = fresh
                self._stale = stale
            for _ in range(int(self.scrape_interval * 10)):
                if not self._running:
                    return
                time.sleep(0.1)

    @staticmethod
    def _count_stale(url: str) -> None:
        from production_stack_tpu.router import metrics as router_metrics

        router_metrics.engine_stats_stale.labels(server=url).inc()

    def _scrape_one(self, url: str) -> Optional[EngineStats]:
        try:
            resp = requests.get(f"{url}/metrics", timeout=self.scrape_interval)
            resp.raise_for_status()
            return EngineStats.from_vllm_scrape(resp.text)
        except Exception as e:  # noqa: BLE001
            logger.debug("Scrape failed for %s: %s", url, e)
            return None

    def get_engine_stats(self) -> Dict[str, EngineStats]:
        """Routable stats only: endpoints whose scrapes have failed
        STALE_AFTER consecutive cycles are excluded (their numbers are
        stale — routing on them would pile load onto a replica whose
        true state is unknown)."""
        with self._lock:
            return dict(self._stats)

    def get_stale_endpoints(self) -> "set[str]":
        with self._lock:
            return set(self._stale)

    def get_health(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._running = False


def initialize_engine_stats_scraper(scrape_interval: float = 10.0) -> EngineStatsScraper:
    return EngineStatsScraper(scrape_interval)


def get_engine_stats_scraper() -> EngineStatsScraper:
    return EngineStatsScraper()
