"""Router-side fault tolerance: circuit breaker + retry/failover policy.

The reference production-stack leans on Kubernetes (readiness probes,
Service endpoints) to stop routing at broken pods; between probe
intervals every request to a dead replica fails. This module closes
that window inside the router:

- :class:`CircuitBreaker` tracks consecutive failures per endpoint URL.
  After ``failure_threshold`` consecutive failures the breaker OPENs and
  the endpoint is excluded from routing. After ``reset_s`` seconds one
  probe request is let through (HALF_OPEN); success CLOSEs the breaker,
  failure re-OPENs it for another ``reset_s``.
- :class:`FaultToleranceConfig` carries the retry/backoff/deadline knobs
  parsed from ``--ft-*`` flags (router/parser.py).

The retry loop itself lives in request_service.py (it is entangled with
the streaming proxy); the idempotency rule is enforced there: a request
is only ever retried/failed-over BEFORE the first streamed byte reached
the client. See docs/fault_tolerance.md.

Breaker state is exported as ``vllm_router:circuit_state`` (0 CLOSED,
1 OPEN, 2 HALF_OPEN) and mirrored into the service-discovery unhealthy
set so ``/health`` and routing filters see one consistent view.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Breaker states (the values are exported verbatim as the
# vllm_router:circuit_state gauge).
CLOSED = 0
OPEN = 1
HALF_OPEN = 2

_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half_open"}


@dataclass
class FaultToleranceConfig:
    """Knobs for the router's retry / circuit-breaker / deadline layer."""

    enabled: bool = False
    # Bounded retry with exponential backoff + full jitter. max_retries
    # counts ADDITIONAL attempts after the first (3 -> up to 4 tries).
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    # Circuit breaker: consecutive failures before the endpoint trips
    # OPEN, and how long it stays open before a half-open probe.
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 30.0
    # Streaming deadlines replacing the old flat total timeout: the
    # first byte must arrive within ttft_deadline_s of dispatch, and
    # each subsequent chunk within inter_chunk_deadline_s of the
    # previous one. 0 disables the respective deadline.
    ttft_deadline_s: float = 120.0
    inter_chunk_deadline_s: float = 30.0
    # Retry-After hint returned with 503 when every replica is broken.
    retry_after_s: int = 5

    def backoff_s(self, attempt: int, rand: float) -> float:
        """Full-jitter exponential backoff for retry number ``attempt``
        (0-based): uniform in [0, min(base * 2^attempt, max)]."""
        ceiling = min(self.backoff_base_s * (2 ** attempt),
                      self.backoff_max_s)
        return ceiling * rand


class CircuitBreaker:
    """Per-endpoint-URL consecutive-failure circuit breaker.

    Thread-safe: failures are recorded from request handlers on the
    event loop while /metrics and /health read state from other tasks,
    and the service-discovery health thread may consult it.

    When a breaker opens, the URL is also pushed into the service
    discovery module's unhealthy set (when the active discovery class
    supports it) so every consumer of
    ``get_unhealthy_endpoint_hashes()`` — /health, routing filters —
    sees the same exclusion without double bookkeeping.
    """

    def __init__(self, failure_threshold: int = 5, reset_s: float = 30.0,
                 service_discovery: Any = None):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_s = float(reset_s)
        self._lock = threading.Lock()
        # url -> [state, consecutive_failures, opened_at_monotonic]
        self._state: Dict[str, List[float]] = {}
        self._sd = service_discovery
        # Cumulative trip count (exported for observability/tests).
        self.trips_total = 0
        # Called with the URL each time a breaker trips OPEN (after the
        # service-discovery mirror). The KV-aware layer hooks this to
        # deregister the failing instance from the KV controller so the
        # router never routes to — or pulls from — a dead holder.
        self.on_open: Optional[Any] = None

    # -- internal ---------------------------------------------------- #
    def _entry(self, url: str) -> List[float]:
        e = self._state.get(url)
        if e is None:
            e = [CLOSED, 0, 0.0]
            self._state[url] = e
        return e

    def _mark_sd(self, url: str, unhealthy: bool) -> None:
        """Mirror breaker state into the service-discovery unhealthy set
        (best-effort: only StaticServiceDiscovery tracks one today)."""
        sd = self._sd
        if sd is None:
            return
        fn = getattr(sd, "mark_unhealthy" if unhealthy else "clear_unhealthy",
                     None)
        if fn is not None:
            try:
                fn(url)
            except Exception:  # pragma: no cover - defensive
                logger.debug("service discovery unhealthy-mirror failed",
                             exc_info=True)

    # -- queries ----------------------------------------------------- #
    def allow(self, url: str) -> bool:
        """May a request be sent to ``url`` right now? An OPEN breaker
        past its reset window transitions to HALF_OPEN and admits ONE
        probe request."""
        now = time.monotonic()
        with self._lock:
            e = self._entry(url)
            if e[0] == CLOSED:
                return True
            if e[0] == OPEN:
                if now - e[2] >= self.reset_s:
                    e[0] = HALF_OPEN
                    return True
                return False
            # HALF_OPEN: one probe is already in flight; hold the rest
            # back until it reports success/failure.
            return False

    def state_value(self, url: str) -> int:
        with self._lock:
            e = self._state.get(url)
            return int(e[0]) if e is not None else CLOSED

    def state_name(self, url: str) -> str:
        return _STATE_NAMES[self.state_value(url)]

    def blocked_urls(self) -> "set[str]":
        """URLs that would currently be refused by :meth:`allow` —
        WITHOUT consuming the half-open probe slot."""
        now = time.monotonic()
        blocked = set()
        with self._lock:
            for url, e in self._state.items():
                if e[0] == OPEN and now - e[2] < self.reset_s:
                    blocked.add(url)
        return blocked

    def snapshot(self) -> Dict[str, int]:
        """url -> state value, for the circuit_state gauge."""
        with self._lock:
            return {url: int(e[0]) for url, e in self._state.items()}

    # -- transitions -------------------------------------------------- #
    def record_success(self, url: str) -> None:
        clear = False
        with self._lock:
            e = self._entry(url)
            if e[0] != CLOSED:
                clear = True
            e[0] = CLOSED
            e[1] = 0
        if clear:
            logger.info("circuit breaker CLOSED for %s", url)
            self._mark_sd(url, unhealthy=False)

    def record_failure(self, url: str) -> None:
        tripped = False
        with self._lock:
            e = self._entry(url)
            if e[0] == HALF_OPEN:
                # Probe failed: straight back to OPEN for another window.
                e[0] = OPEN
                e[2] = time.monotonic()
                tripped = True
            else:
                e[1] += 1
                if e[1] >= self.failure_threshold and e[0] == CLOSED:
                    e[0] = OPEN
                    e[2] = time.monotonic()
                    tripped = True
            if tripped:
                self.trips_total += 1
        if tripped:
            logger.warning(
                "circuit breaker OPEN for %s (%d consecutive failures; "
                "half-open probe in %.0fs)", url,
                self.failure_threshold, self.reset_s)
            self._mark_sd(url, unhealthy=True)
            if self.on_open is not None:
                try:
                    self.on_open(url)
                except Exception:  # pragma: no cover - defensive
                    logger.debug("breaker on_open hook failed",
                                 exc_info=True)


class FaultTolerance:
    """The router's fault-tolerance state bundle (config + breaker),
    hung off RouterState as ``state.fault_tolerance``."""

    def __init__(self, config: FaultToleranceConfig,
                 service_discovery: Any = None):
        self.config = config
        self.breaker = CircuitBreaker(
            failure_threshold=config.breaker_failure_threshold,
            reset_s=config.breaker_reset_s,
            service_discovery=service_discovery,
        )


def initialize_fault_tolerance(args,
                               service_discovery: Any = None,
                               ) -> Optional[FaultTolerance]:
    """Build the FaultTolerance bundle from parsed router args (None
    when --fault-tolerance is off: request_service then runs the exact
    pre-existing single-attempt code path)."""
    if not getattr(args, "fault_tolerance", False):
        return None
    cfg = FaultToleranceConfig(
        enabled=True,
        max_retries=args.ft_max_retries,
        backoff_base_s=args.ft_backoff_base,
        backoff_max_s=args.ft_backoff_max,
        breaker_failure_threshold=args.ft_breaker_threshold,
        breaker_reset_s=args.ft_breaker_reset,
        ttft_deadline_s=args.ft_ttft_deadline,
        inter_chunk_deadline_s=args.ft_inter_chunk_deadline,
        retry_after_s=args.ft_retry_after,
    )
    return FaultTolerance(cfg, service_discovery=service_discovery)
