"""Request proxying: the router's hot path.

Rebuild of reference ``src/vllm_router/services/request_service/request.py``
(689 LoC):

- :func:`process_request` -- streamed POST to the chosen backend with the
  stats hook trio around it (reference ``:55-137``; hot loop ``:109-119``).
- :func:`route_general_request` -- body parse, model alias rewrite, endpoint
  filtering (model + not-sleeping), routing decision, streaming response
  (reference ``:140-302``).
- :func:`route_disaggregated_prefill_request` -- two-phase prefill→decode
  flow (reference ``:339-431``).
- :func:`route_sleep_wakeup_request` -- engine sleep/wake control
  (reference ``:434-510``).
- :func:`route_general_transcriptions` -- multipart audio proxy
  (reference ``:513-689``).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import random
import sys
import time
import uuid
from typing import AsyncGenerator, Optional, Tuple

import aiohttp
from aiohttp import web

from production_stack_tpu.obs.trace import format_traceparent
from production_stack_tpu.router.httpclient import get_client_session
from production_stack_tpu.router.relay import (
    StreamTap, install_tap, remove_tap,
    seal_response as relay_seal_response)
from production_stack_tpu.structured.api import (
    StructuredError, compile_char_dfa, parse_structured)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

HOP_BY_HOP = {
    "connection", "keep-alive", "proxy-authenticate", "proxy-authorization",
    "te", "trailers", "transfer-encoding", "upgrade", "host", "content-length",
}


# Hostile-input bound for JSON request bodies (mirrors the engine-side
# bound in production_stack_tpu/engine/server.py): big enough for any
# real OpenAI payload, small enough that one request cannot balloon the
# router's memory via full-body buffering.
MAX_BODY_BYTES = 32 << 20


# Identity headers are asserted by the router (QoS admission writes the
# authenticated tenant and effective priority), never trusted from the
# client: forwarding a client-supplied X-Tenant / X-Priority would let
# anyone spoof tenant accounting and preemption class engine-side.
_ROUTER_ASSERTED = {"x-tenant", "x-priority"}


def _loop_measure(state, component: str):
    """On-loop attribution for a synchronous section (--loop-monitor).
    A no-op context when the monitor is off."""
    monitor = getattr(state, "loop_monitor", None)
    if monitor is None:
        return contextlib.nullcontext()
    return monitor.components.measure(component)


def _loop_wrap(state, component: str, coro):
    """On-loop attribution for an awaited coroutine (--loop-monitor):
    only the synchronous resume slices count, awaited time does not.
    Returns the coroutine untouched when the monitor is off."""
    monitor = getattr(state, "loop_monitor", None)
    if monitor is None:
        return coro
    return monitor.components.wrap(component, coro)


def _forward_headers(request: web.Request) -> dict:
    return {
        k: v for k, v in request.headers.items()
        if k.lower() not in HOP_BY_HOP
        and k.lower() not in _ROUTER_ASSERTED
    }


class _RelayDetach:
    """Handoff slot connecting route_general_request (which owns the
    relay job and the client-side bookkeeping) to process_request
    (which owns the upstream response object the handler never sees).

    The handler arms it (``job`` + ``on_chunk``) right after a
    successful pump handoff; at its next resume the generator detaches
    the upstream ``StreamReader`` onto a :class:`StreamTap` and PARKS in
    ``RelayJob.wait_done()`` — from then on each upstream payload costs
    one sync hook (SLO stamp, QoS buffer, engine token accounting, pump
    feed) instead of a four-frame coroutine resumption chain plus an
    aiohttp write. Never armed when --relay-off-loop is unset."""

    __slots__ = ("job", "on_chunk", "tap", "content")

    def __init__(self):
        self.job = None
        self.on_chunk = None
        self.tap = None
        self.content = None


def _begin_detach(detach: _RelayDetach, resp, monitor,
                  backend_url: str, request_id: str) -> bool:
    """Switch a committed stream to detached mode. Synchronous — no
    await between the checks, the tap install, and the buffered-payload
    drain, so no upstream byte can slip past the tap. False (tap not
    installed) falls back to the per-chunk feed path."""
    content = resp.content
    if content.exception() is not None:
        return False
    handler_cb = detach.on_chunk

    def on_chunk(data, now):
        monitor.on_token(backend_url, request_id, now)
        if handler_cb is not None:
            handler_cb(data, now)

    tap = StreamTap(detach.job, on_chunk,
                    getattr(content, "_protocol", None))
    if not install_tap(content, tap):
        return False
    detach.tap = tap
    detach.content = content
    # Payloads the parser delivered before the swap sit in the reader's
    # buffer; route them through the same hook path, then replay a
    # pre-swap EOF (the tapped feed_eof will never fire for it).
    try:
        buffered = content.read_nowait(-1)
    except Exception:
        buffered = b""
    if buffered:
        tap.on_data(buffered)
    if content.is_eof():
        remove_tap(content)
        tap.on_eof()
    return True


async def process_request(
    state,
    request_id: str,
    backend_url: str,
    endpoint: str,
    body: bytes,
    headers: dict,
    method: str = "POST",
    ttft_deadline: Optional[float] = None,
    inter_chunk_deadline: Optional[float] = None,
    detach: Optional[_RelayDetach] = None,
) -> AsyncGenerator[Tuple[str, object], None]:
    """Stream a backend request; yields ("headers", (status, hdrs)) then
    ("chunk", bytes)... — mirroring reference request.py:55-137.

    With both deadlines None (fault tolerance off) this is the exact
    historical single-attempt path. With --fault-tolerance on, the flat
    upstream timeout is replaced by a TTFT deadline (dispatch -> first
    body byte, connect and response headers included) and an inter-chunk
    deadline (each subsequent read), so a hung engine raises
    ``asyncio.TimeoutError`` instead of wedging the stream.
    """
    monitor = state.request_stats_monitor
    monitor.on_new_request(backend_url, request_id, time.time())
    session = get_client_session()
    first = True
    try:
        if ttft_deadline is None and inter_chunk_deadline is None:
            async with session.request(
                method, f"{backend_url}{endpoint}", data=body, headers=headers
            ) as resp:
                yield "headers", (resp.status, dict(resp.headers))
                async for chunk in resp.content.iter_any():
                    now = time.time()
                    if first:
                        monitor.on_request_response(backend_url, request_id, now)
                        first = False
                    else:
                        monitor.on_token(backend_url, request_id, now)
                    yield "chunk", chunk
                    if detach is not None and detach.job is not None \
                            and _begin_detach(detach, resp, monitor,
                                              backend_url, request_id):
                        await detach.job.wait_done()
                        return
            return
        t0 = time.monotonic()
        req = session.request(
            method, f"{backend_url}{endpoint}", data=body, headers=headers
        )
        if ttft_deadline:
            resp = await asyncio.wait_for(req, ttft_deadline)
        else:
            resp = await req
        async with resp:
            yield "headers", (resp.status, dict(resp.headers))
            while True:
                if first and ttft_deadline:
                    budget = max(0.001,
                                 ttft_deadline - (time.monotonic() - t0))
                elif not first and inter_chunk_deadline:
                    budget = inter_chunk_deadline
                else:
                    budget = None
                read = resp.content.readany()
                chunk = (await asyncio.wait_for(read, budget)
                         if budget is not None else await read)
                if not chunk:
                    break
                now = time.time()
                if first:
                    monitor.on_request_response(backend_url, request_id, now)
                    first = False
                else:
                    monitor.on_token(backend_url, request_id, now)
                yield "chunk", chunk
                if detach is not None and detach.job is not None \
                        and _begin_detach(detach, resp, monitor,
                                          backend_url, request_id):
                    # Parked: the pump enforces the inter-chunk
                    # deadline (job.deadline_s) and wait_done raises
                    # the same asyncio.TimeoutError wait_for() did.
                    await detach.job.wait_done()
                    return
    finally:
        if detach is not None and detach.content is not None:
            remove_tap(detach.content)
        monitor.on_request_complete(backend_url, request_id, time.time())


async def _stream_with_failover(
    state,
    ft,
    request_id: str,
    server_url: str,
    candidate_urls,
    endpoint: str,
    body: bytes,
    headers: dict,
    detach: Optional[_RelayDetach] = None,
) -> AsyncGenerator[Tuple[str, object], None]:
    """Retry/failover wrapper around :func:`process_request`.

    Yields the same ("headers", ...)/("chunk", ...) events, plus
    ("attempt", url) before each upstream try and ("failed", message) if
    every attempt is exhausted (caller turns that into 503 +
    Retry-After).

    The idempotency rule: headers are BUFFERED until the first body byte
    arrives, so a connect error, a 5xx response, or a TTFT-deadline
    expiry — all strictly before the first streamed byte — can fail over
    to another replica. Once the first chunk is yielded downstream the
    request is committed: any later fault records a breaker failure and
    propagates; it is never retried (the client already saw bytes).
    """
    from production_stack_tpu.router import metrics as router_metrics

    cfg = ft.config
    breaker = ft.breaker
    # The routed URL leads; remaining healthy replicas are failover
    # targets, cycled if retries outnumber candidates.
    ordered = [server_url] + [u for u in candidate_urls if u != server_url]
    attempts = cfg.max_retries + 1
    last_error = "no healthy replica"
    committed = False
    for attempt in range(attempts):
        url = ordered[attempt % len(ordered)]
        if not breaker.allow(url):
            last_error = f"circuit open for {url}"
            continue
        if attempt > 0:
            router_metrics.retries_total.labels(server=url).inc()
            await asyncio.sleep(cfg.backoff_s(attempt - 1, random.random()))
        yield "attempt", url
        pending_headers = None
        try:
            stream = process_request(
                state, request_id, url, endpoint, body, headers,
                ttft_deadline=cfg.ttft_deadline_s or None,
                inter_chunk_deadline=cfg.inter_chunk_deadline_s or None,
                detach=detach,
            )
            async for kind, payload in stream:
                if kind == "headers":
                    status, _hdrs = payload
                    if status >= 500:
                        # 5xx before any body byte: retryable per the
                        # idempotency rule.
                        last_error = f"{url} answered {status}"
                        breaker.record_failure(url)
                        await stream.aclose()
                        pending_headers = None
                        break
                    pending_headers = payload
                else:
                    if pending_headers is not None:
                        committed = True
                        if url != server_url:
                            router_metrics.failovers_total.labels(
                                server=url).inc()
                        yield "headers", pending_headers
                        pending_headers = None
                    yield kind, payload
            else:
                # Clean upstream EOF. Flush still-buffered headers
                # (empty-body response, e.g. 204 or HEAD-ish).
                if pending_headers is not None:
                    if url != server_url:
                        router_metrics.failovers_total.labels(
                            server=url).inc()
                    yield "headers", pending_headers
                breaker.record_success(url)
                return
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            breaker.record_failure(url)
            if committed:
                # Bytes already reached the client: NEVER retried.
                raise
            last_error = f"{url}: {type(e).__name__}: {e}"
            logger.warning(
                "Attempt %d/%d for %s on %s failed before first byte: %s",
                attempt + 1, attempts, request_id, url, last_error)
            continue
    yield "failed", last_error


async def route_general_request(
    request: web.Request, endpoint: str
) -> web.StreamResponse:
    """Parse, route, and stream one OpenAI-API request (reference :140-302)."""
    state = request.app["state"]
    in_router_time = time.time()
    body = await request.read()
    request_id = request.headers.get("X-Request-Id") or str(uuid.uuid4())

    if len(body) > MAX_BODY_BYTES:
        return web.json_response(
            {"error": "Request body too large."}, status=413)
    try:
        request_json = json.loads(body) if body else {}
    except (ValueError, RecursionError):
        # ValueError covers JSONDecodeError and UnicodeDecodeError;
        # RecursionError is a nesting bomb blowing the C scanner's
        # stack.  Either way: hostile input, clean 400, never a 500.
        return web.json_response(
            {"error": "Request body is not JSON parsable."}, status=400
        )
    if not isinstance(request_json, dict):
        # A non-object top level (e.g. `[]` or a bare string) would
        # 500 later at request_json.get(...); reject it up front.
        return web.json_response(
            {"error": "Request body must be a JSON object."}, status=400)

    # Structured-output constraints (guided_json / guided_regex /
    # response_format) are validated — and their DFA compiled, memoized
    # process-wide — at the router so an uncompilable schema is a 400
    # here instead of an engine round-trip that fails after admission
    # and routing already ran.
    try:
        spec = parse_structured(request_json)
        if spec is not None:
            compile_char_dfa(spec)
    except StructuredError as exc:
        return web.json_response(
            {"error": {"message": str(exc),
                       "type": "BadRequestError"}}, status=400)

    # Multi-tenant QoS admission (production_stack_tpu/qos/): resolve the
    # caller's tenant from its bearer key and run the token buckets.  With
    # no tenants file configured state.qos is None and the path below is
    # untouched (today's behavior, byte-identical streams).
    qos = getattr(state, "qos", None)
    # SLO outcome classifier (--slo-config): every request that reaches
    # this point terminates as exactly one of ok/slow/shed/failed/
    # client_abort. None when the flag is off — no classification code
    # runs and the path below is byte-identical.
    slo = getattr(state, "slo", None)
    tenant = priority = None
    qos_headers: dict = {}
    if qos is not None:
        from production_stack_tpu.router import metrics as router_metrics

        with _loop_measure(state, "qos_admission"):
            qos.maybe_reload()
            tenant = qos.resolve(request.headers.get("Authorization"))
            priority = qos.request_priority(
                tenant, request.headers.get("X-Priority"))
            verdict = qos.admit(tenant, request_json)
        qos_headers = dict(verdict.headers)
        qos_headers["x-tenant"] = tenant.name
        if not verdict.admitted:
            router_metrics.tenant_rejected.labels(
                tenant=tenant.name, reason=verdict.reason).inc()
            if slo is not None:
                slo.observe("shed", tenant.name, request_json.get("model"))
            reject_headers = dict(qos_headers)
            reject_headers["Retry-After"] = str(int(verdict.retry_after) + 1)
            return web.json_response(
                {"error": {
                    "message": (
                        f"Rate limit exceeded for tenant {tenant.name!r}"
                        f" ({verdict.reason}/s); retry after"
                        f" {verdict.retry_after:.2f}s."),
                    "type": "RateLimitError"}},
                status=429, headers=reject_headers)

    # Optional user callbacks (reference :174-180).
    if state.callbacks and hasattr(state.callbacks, "pre_request"):
        result = await _maybe_await(
            state.callbacks.pre_request(request, request_json, request_id)
        )
        if isinstance(result, web.StreamResponse):
            return result

    # PII detection (reference experimental/pii/middleware.py).
    if state.pii_detector is not None:
        hit = await state.pii_detector.check_request(request_json)
        if hit:
            return web.json_response(
                {"error": f"Request blocked: detected PII ({hit})"}, status=400
            )

    # Model alias rewrite (reference :182-214).
    requested_model = request_json.get("model")
    aliases = getattr(state.service_discovery, "aliases", None) or {}
    if requested_model in aliases:
        requested_model = aliases[requested_model]
        request_json["model"] = requested_model
        body = json.dumps(request_json).encode()

    # Request rewriting hook (reference rewriter.py).
    if state.request_rewriter is not None:
        body = state.request_rewriter.rewrite(body, endpoint)

    # Disaggregated prefill two-phase flow (reference :158-162).
    from production_stack_tpu.router.routing_logic import DisaggregatedPrefillRouter

    if isinstance(state.router, DisaggregatedPrefillRouter):
        return await route_disaggregated_prefill_request(
            request, endpoint, request_json, request_id
        )

    recorder = getattr(state, "trace_recorder", None)
    trace = root = None
    if recorder is not None:
        trace = recorder.begin(request_id, request.headers.get("traceparent"))
        root = trace.start_span(
            "router.request", start=in_router_time,
            endpoint=endpoint, model=requested_model or "",
        )

    endpoints = state.service_discovery.get_endpoint_info()

    # Adapter identification (--lora-plane) runs against the UNFILTERED
    # endpoint list: an adapter request may legitimately target a
    # replica that does not hold the adapter yet (on-demand load), which
    # the serves() filter below would hide.
    lora = getattr(state, "lora", None)
    lora_adapter: Optional[str] = None
    lora_base: Optional[str] = None
    if lora is not None and requested_model:
        base_models = {m for ep in endpoints for m in ep.model_names}
        is_adapter = requested_model not in base_models and (
            requested_model in lora.known_adapters()
            or any(requested_model in (ep.lora_adapters or ())
                   for ep in endpoints))
        if is_adapter:
            lora_adapter = requested_model
            lora_base = lora.base_model_of(lora_adapter)

    if lora_adapter is not None:
        # Candidates: replicas already holding the adapter plus every
        # replica serving its base model (loadable on demand).
        endpoints = [
            ep for ep in endpoints
            if not ep.sleep and (
                ep.serves(lora_adapter)
                or lora_base is None
                or lora_base in ep.model_names)
        ]
    elif requested_model is not None:
        endpoints = [
            ep for ep in endpoints
            if ep.serves(requested_model) and not ep.sleep
        ]
    else:
        endpoints = [ep for ep in endpoints if not ep.sleep]
    if not endpoints:
        # With the adapter plane on, a model nobody serves is most
        # likely an unknown adapter name: return a clean OpenAI-style
        # 404 (matching the engine's own unknown-model reply) instead
        # of the generic 400 — and never fall back to the base model.
        not_found = (getattr(state, "lora", None) is not None
                     and requested_model is not None)
        if trace is not None:
            root.finish(status=404 if not_found else 400,
                        error="no_endpoints")
            recorder.record(trace)
        if slo is not None:
            slo.observe("failed", tenant.name if tenant else None,
                        requested_model)
        if not_found:
            return web.json_response(
                {"error": {"message": f"model {requested_model!r} not found",
                           "type": "NotFoundError"}},
                status=404,
            )
        return web.json_response(
            {"error": f"Model {requested_model} not found or all engines sleeping."},
            status=400,
        )

    # Circuit breaker: endpoints with an OPEN breaker are excluded from
    # routing. If that leaves nothing, every replica is broken — tell
    # the client when to come back instead of burning a doomed attempt.
    ft = getattr(state, "fault_tolerance", None)
    if ft is not None:
        blocked = ft.breaker.blocked_urls()
        if blocked:
            healthy = [ep for ep in endpoints if ep.url not in blocked]
            if not healthy:
                if trace is not None:
                    root.finish(status=503, error="all_circuits_open")
                    recorder.record(trace)
                if slo is not None:
                    slo.observe("failed", tenant.name if tenant else None,
                                requested_model)
                return web.json_response(
                    {"error": {
                        "message": "All replicas are failing "
                                   "(circuit breakers open); retry later.",
                        "type": "ServiceUnavailable"}},
                    status=503,
                    headers={"Retry-After": str(ft.config.retry_after_s),
                             **qos_headers},
                )
            endpoints = healthy

    # Adapter-affinity (--lora-plane): a request naming a resident LoRA
    # adapter pins to the replicas that hold it — soft pinning: when no
    # replica has it resident, any pick stands and the miss path below
    # loads it on demand (single-flight, breaker-aware).
    if lora_adapter is not None and lora.config.affinity:
        resident = {u.rstrip("/")
                    for u in lora.resident_urls(lora_adapter)}
        pinned = [ep for ep in endpoints
                  if ep.url.rstrip("/") in resident]
        if pinned:
            endpoints = pinned

    # Weighted-fair dispatch: wait for a slot before picking a backend so
    # the routing decision sees fresh stats.  The lease is held for the
    # whole upstream exchange (streaming included) and released in the
    # outer finally, so concurrency accounting survives client aborts.
    lease = None
    if qos is not None:
        from production_stack_tpu.qos import ShedError
        from production_stack_tpu.router import metrics as router_metrics

        router_metrics.tenant_queued.labels(tenant=tenant.name).inc()
        queue_t0 = time.time()
        try:
            lease = await _loop_wrap(
                state, "qos_admission",
                qos.lease(tenant, priority, request_json))
        except ShedError as e:
            router_metrics.tenant_shed.labels(tenant=tenant.name).inc()
            if trace is not None:
                root.finish(status=503, error="qos_shed")
                recorder.record(trace)
            if slo is not None:
                slo.observe("shed", tenant.name, requested_model,
                            adapter=lora_adapter)
            events = getattr(state, "events", None)
            if events is not None:
                events.record(
                    "qos_shed", tenant=tenant.name,
                    trace_id=trace.trace_id if trace else None)
            shed_headers = dict(qos_headers)
            shed_headers["Retry-After"] = str(max(1, int(e.retry_after)))
            return web.json_response(
                {"error": {
                    "message": ("Saturated: batch traffic is being shed;"
                                " retry later."),
                    "type": "ServerOverloadedError"}},
                status=503, headers=shed_headers)
        router_metrics.tenant_queue_wait.labels(
            tenant=tenant.name).observe(lease.wait_s)
        router_metrics.tenant_admitted.labels(tenant=tenant.name).inc()
        if trace is not None and lease.wait_s > 0:
            trace.add_span(
                "router.qos_queue", queue_t0, queue_t0 + lease.wait_s,
                parent=root, tenant=tenant.name, priority=priority)

    full_response = bytearray()
    # SLO bookkeeping (no-ops when --slo-config is off): terminal paths
    # set slo_outcome; None at the outer finally means the handler
    # unwound via an exception (client abort or a pre-stream failure).
    slo_outcome: Optional[str] = None
    slo_first_chunk = slo_last_chunk = 0.0
    slo_chunks = 0
    try:
        engine_stats = state.engine_stats_scraper.get_engine_stats()
        request_stats = state.request_stats_monitor.get_request_stats()

        import inspect

        routing_span = trace.start_span("router.routing") if trace else None
        route_result = state.router.route_request(
            endpoints, engine_stats, request_stats, dict(request.headers), request_json
        )
        server_url = (
            await route_result if inspect.isawaitable(route_result) else route_result
        )
        if routing_span is not None:
            routing_span.finish(
                engine=server_url,
                logic=type(state.router).__name__,
                candidates=len(endpoints),
            )

        logger.info(
            "Routing request %s for model %s to %s at %.3f (took %.1f ms)",
            request_id, requested_model, server_url,
            in_router_time, (time.time() - in_router_time) * 1e3,
        )

        # Adapter-affinity outcome: a pick that already has the adapter
        # resident is a hit; a miss triggers a single-flight on-demand
        # load on the picked replica (bounded by --lora-load-timeout).
        # A failed load reroutes to a resident replica when one exists,
        # else the request fails cleanly — never a silent base-model
        # fallback.
        if lora_adapter is not None:
            if lora.is_resident(server_url, lora_adapter):
                lora.record_affinity(lora_adapter, hit=True)
            else:
                lora.record_affinity(lora_adapter, hit=False)
                loaded = await _loop_wrap(
                    state, "lora_load",
                    lora.ensure_resident(server_url, lora_adapter))
                if not loaded:
                    fallback = next(
                        (ep.url for ep in endpoints
                         if ep.url != server_url
                         and lora.is_resident(ep.url, lora_adapter)),
                        None)
                    if fallback is None:
                        slo_outcome = "failed"
                        if trace is not None:
                            root.finish(status=503,
                                        error="lora_load_failed")
                            recorder.record(trace)
                        return web.json_response(
                            {"error": {
                                "message": (
                                    f"adapter {lora_adapter!r} could not "
                                    "be loaded on any replica"),
                                "type": "ServiceUnavailable"}},
                            status=503, headers=qos_headers)
                    logger.info(
                        "lora: rerouting %s from %s to resident %s",
                        request_id, server_url, fallback)
                    server_url = fallback
            lora.touch(server_url, lora_adapter)

        # Global prefix cache (--fleet-cache): if another replica or the
        # L3 holds a long prefix of this prompt, have the picked replica
        # pull it before prefill. Strictly best-effort — any failure
        # means the engine recomputes, exactly as without the flag.
        fleet = getattr(state, "fleet", None)
        if fleet is not None and request_json is not None:
            from production_stack_tpu.router.routing_logic import (
                _adapter_salt,
                _extract_prompt,
            )

            pull_span = (
                trace.start_span("router.kv_pull") if trace else None)
            pull = await _loop_wrap(
                state, "fleet_pull",
                fleet.maybe_pull(
                    server_url, _extract_prompt(request_json) or "",
                    request_json, request_id,
                    salt=_adapter_salt(request_json, endpoints)))
            if pull_span is not None:
                if pull is None:
                    pull_span.finish(outcome="skip")
                else:
                    pull_span.finish(
                        holder=pull["holder_url"],
                        outcome=pull["outcome"],
                        injected_blocks=pull["injected_blocks"],
                        matched_chars=pull["matched_chars"])

        headers = _forward_headers(request)
        headers["X-Request-Id"] = request_id
        if qos is not None:
            # Priority travels to the engine scheduler; the tenant name
            # rides along for per-tenant engine-side accounting.
            headers["X-Priority"] = priority
            headers["X-Tenant"] = tenant.name
        upstream = None
        if trace is not None:
            # The upstream span is the engine-side parent: its id travels in
            # the traceparent header so engine spans link under it.
            upstream = trace.start_span("router.upstream", engine=server_url)
            headers["traceparent"] = format_traceparent(
                trace.trace_id, upstream.span_id)

        routed_url, attempt_no = server_url, 0
        # Relay pump tier (--relay-off-loop): after the first chunk has
        # gone out through the normal aiohttp writer (the response is
        # then COMMITTED — failover window closed), the client socket
        # is handed to a pump thread, the upstream StreamReader is
        # detached onto a StreamTap, and the handler parks until EOF —
        # subsequent chunks never resume a coroutine or touch the
        # aiohttp writer. relay is None when the flag is off and none
        # of this changes the byte stream.
        relay = getattr(state, "relay", None)
        relay_job = None
        relay_tried = False
        relay_detach = _RelayDetach() if relay is not None else None
        if ft is not None:
            stream = _stream_with_failover(
                state, ft, request_id, server_url,
                [ep.url for ep in endpoints], endpoint, body, headers,
                detach=relay_detach,
            )
        else:
            stream = process_request(
                state, request_id, server_url, endpoint, body, headers,
                detach=relay_detach,
            )
        response: Optional[web.StreamResponse] = None
        got_first_chunk = False
        try:
            try:
                async for kind, payload in stream:
                    if kind == "attempt":
                        # Retry/failover become span events on the
                        # upstream span so a slow trace shows the
                        # attempt timeline, not just the final URL.
                        if upstream is not None:
                            if attempt_no > 0:
                                upstream.add_event(
                                    "retry", url=payload,
                                    attempt=attempt_no)
                            if payload != routed_url:
                                upstream.add_event("failover", url=payload)
                        if payload != routed_url and \
                                getattr(state, "events", None) is not None:
                            state.events.record(
                                "failover", endpoint=payload,
                                from_url=routed_url,
                                trace_id=trace.trace_id if trace else None)
                        attempt_no += 1
                        server_url = payload
                        continue
                    if kind == "failed":
                        logger.error(
                            "All upstream attempts failed for %s: %s",
                            request_id, payload)
                        if upstream is not None:
                            upstream.finish(error=str(payload))
                        slo_outcome = "failed"
                        if getattr(state, "events", None) is not None:
                            state.events.record(
                                "retry_exhausted", endpoint=server_url,
                                error=str(payload),
                                trace_id=trace.trace_id if trace else None)
                        return web.json_response(
                            {"error": {
                                "message": f"All replicas failed: {payload}",
                                "type": "ServiceUnavailable"}},
                            status=503,
                            headers={
                                "Retry-After": str(ft.config.retry_after_s),
                                **qos_headers},
                        )
                    if kind == "headers":
                        status, hdrs = payload
                        response = web.StreamResponse(status=status)
                        ct = hdrs.get("Content-Type")
                        if ct:
                            response.content_type = ct.split(";")[0]
                            if "charset=" in ct:
                                response.charset = ct.split("charset=")[-1]
                        response.headers["X-Request-Id"] = request_id
                        for k, v in qos_headers.items():
                            response.headers[k] = v
                        await response.prepare(request)
                    else:
                        if trace is not None and not got_first_chunk:
                            got_first_chunk = True
                            trace.add_span(
                                "router.first_chunk", upstream.start, time.time(),
                                parent=upstream,
                            )
                        if slo is not None:
                            slo_last_chunk = time.time()
                            if not slo_chunks:
                                slo_first_chunk = slo_last_chunk
                            slo_chunks += 1
                        full_response.extend(payload)
                        assert response is not None
                        if relay_job is not None:
                            # Pump-side disconnects surface here as the
                            # same ClientConnectionResetError the write
                            # below raises, into the same except arm.
                            # Sync fast path; awaits only at HIGH_WATER.
                            waiter = relay_job.feed_nowait(payload)
                            if waiter is not None:
                                await waiter
                        else:
                            await response.write(payload)
                            if relay is not None and not relay_tried:
                                relay_tried = True
                                relay_job = await relay.try_handoff(
                                    request, response,
                                    server_url=server_url)
                                if relay_job is not None:
                                    if ft is not None:
                                        relay_job.deadline_s = (
                                            ft.config.inter_chunk_deadline_s
                                            or None)

                                    def _relay_chunk_cb(data, now):
                                        # Loop-side, from the upstream
                                        # protocol's data_received while
                                        # the handler is parked: the
                                        # exact bookkeeping the per-
                                        # chunk loop above does.
                                        nonlocal slo_chunks, \
                                            slo_last_chunk
                                        if slo is not None:
                                            slo_last_chunk = now
                                            slo_chunks += 1
                                        full_response.extend(data)

                                    relay_detach.on_chunk = \
                                        _relay_chunk_cb
                                    # Arm LAST: the generator detaches
                                    # at its next resume once job is
                                    # non-None.
                                    relay_detach.job = relay_job
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                if upstream is not None:
                    upstream.finish(error=str(e))
                # A reset means the *client's* transport closed under
                # our write (aiohttp raises it as a ConnectionResetError
                # subclass) — the engine did nothing wrong. Anything
                # else is the upstream breaking: before any byte it's a
                # clean 502, after bytes the raise tears the stream
                # down.
                if isinstance(e, ConnectionResetError):
                    logger.info("Client went away mid-stream for %s: %s",
                                request_id, e)
                    slo_outcome = "client_abort"
                else:
                    logger.error("Backend %s failed for %s: %s",
                                 server_url, request_id, e)
                    slo_outcome = "failed"
                if response is None:
                    return web.json_response(
                        {"error": f"Backend connection failed: {e}"}, status=502
                    )
                raise
            if response is None:
                slo_outcome = "failed"
                return web.json_response({"error": "Empty backend response"}, status=502)
            if relay_job is not None:
                # Pump flushes everything (terminal chunk included) and
                # the response is sealed so aiohttp's own write_eof
                # becomes a no-op; keep-alive proceeds normally.
                await relay_job.finish()
                relay_seal_response(response)
            else:
                await response.write_eof()
            if slo is not None:
                if response.status >= 400:
                    slo_outcome = "failed"
                else:
                    # Client-perceived TTFT (router entry -> first byte
                    # out) and a mean inter-chunk estimate stand in for
                    # per-token timing the proxy cannot see.
                    ttft_s = (slo_first_chunk - in_router_time
                              if slo_first_chunk else None)
                    inter_s = None
                    if slo_chunks > 1:
                        inter_s = ((slo_last_chunk - slo_first_chunk)
                                   / (slo_chunks - 1))
                    with _loop_measure(state, "slo_classify"):
                        slo_outcome = slo.latency_outcome(
                            tenant.name if tenant else None,
                            requested_model,
                            ttft_s=ttft_s, inter_token_s=inter_s,
                            base_model=lora_base)

            # Post-request hooks: semantic cache store + callbacks (reference :129-137).
            if state.semantic_cache is not None and endpoint.endswith("chat/completions"):
                await state.semantic_cache.maybe_store(request_json, bytes(full_response))
            if state.callbacks and hasattr(state.callbacks, "post_request"):
                await _maybe_await(
                    state.callbacks.post_request(request_json, bytes(full_response), request_id)
                )
            return response
        finally:
            if relay_job is not None:
                # Exception/cancellation unwind: abort the pump (dup
                # closes without the terminal chunk — same truncated
                # stream the on-loop path leaves), then account the
                # job's byte/chunk totals once.
                relay_job.ensure_closed()
                relay_job.settle()
            if trace is not None:
                status = response.status if response is not None else 0
                upstream.finish(status=status, bytes=len(full_response))
                # Router overhead: wall time spent inside the router minus
                # the upstream engine exchange. This is the per-request cost
                # of routing + QoS + KV-pull + proxying, the quantity the
                # storm/chaos harnesses report as router_overhead_p99.
                overhead = max(
                    0.0, (time.time() - root.start) - upstream.duration_s)
                from production_stack_tpu.router import metrics as router_metrics
                router_metrics.hist_router_overhead.labels(
                    server=server_url).observe(overhead)
                root.finish(status=status, overhead_s=round(overhead, 6))
                recorder.record(trace)
    finally:
        if slo is not None:
            outcome = slo_outcome
            if outcome is None:
                # No terminal path classified this request: the handler
                # is unwinding via an exception. A cancelled task or a
                # reset transport is the client going away; anything
                # else is our failure.
                exc = sys.exc_info()[1]
                if isinstance(exc, (asyncio.CancelledError,
                                    ConnectionResetError)):
                    outcome = "client_abort"
                else:
                    outcome = "failed"
            with _loop_measure(state, "slo_classify"):
                slo.observe(outcome, tenant.name if tenant else None,
                            requested_model, adapter=lora_adapter)
        if lease is not None:
            lease.release()
        if qos is not None and tenant is not None:
            # Usage reconciliation: the admission estimate trusted the
            # client's max_tokens; debit the bucket with what actually
            # streamed (runs on client aborts too — partial output was
            # still generated) so understating max_tokens cannot buy
            # sustained free throughput.
            from production_stack_tpu.router import metrics as router_metrics
            try:
                extra = qos.reconcile(
                    tenant, request_json, bytes(full_response))
            except Exception:
                logger.exception(
                    "QoS usage reconciliation failed for %s", request_id)
                extra = 0.0
            if extra > 0:
                router_metrics.qos_usage_reconciled.labels(
                    tenant=tenant.name).inc(extra)


async def send_request_to_prefiller(
    session: aiohttp.ClientSession, url: str, endpoint: str, body: dict, headers: dict
) -> dict:
    """Fire the prefill phase (max_tokens=1) — reference request.py:305-321."""
    async with session.post(
        f"{url}{endpoint}", json=body, headers=headers
    ) as resp:
        resp.raise_for_status()
        return await resp.json()


async def route_disaggregated_prefill_request(
    request: web.Request, endpoint: str, request_json: dict, request_id: str
) -> web.StreamResponse:
    """Two-phase prefill→decode flow (reference request.py:339-431).

    Phase 1 sends the request with ``max_tokens=1`` (and ``max_completion_tokens``
    for chat) to a prefill engine; the KV it produces moves to the decode
    engine out-of-band over the KV transfer fabric
    (:mod:`production_stack_tpu.kv.transfer`). Phase 2 streams the real
    request from a decode engine.
    """
    state = request.app["state"]
    session = get_client_session()
    endpoints = state.service_discovery.get_endpoint_info()
    router = state.router

    recorder = getattr(state, "trace_recorder", None)
    trace = root = None
    if recorder is not None:
        trace = recorder.begin(request_id, request.headers.get("traceparent"))
        root = trace.start_span(
            "router.request", endpoint=endpoint, disaggregated=True,
            model=request_json.get("model") or "",
        )

    prefill_url = router.pick(endpoints, "prefill")
    decode_url = router.pick(endpoints, "decode")
    if trace is not None:
        trace.start_span("router.routing").finish(
            engine=decode_url, prefill_engine=prefill_url,
            logic=type(router).__name__,
        )

    saved = {
        k: request_json.get(k) for k in ("max_tokens", "max_completion_tokens")
    }
    prefill_json = dict(request_json)
    prefill_json["max_tokens"] = 1
    if "max_completion_tokens" in prefill_json:
        prefill_json["max_completion_tokens"] = 1
    prefill_json["stream"] = False
    headers = _forward_headers(request)
    headers["X-Request-Id"] = request_id
    headers.pop("Content-Type", None)

    monitor = state.request_stats_monitor
    monitor.on_new_request(prefill_url, request_id, time.time())
    prefill_span = None
    if trace is not None:
        prefill_span = trace.start_span(
            "router.disagg_prefill", engine=prefill_url)
        headers["traceparent"] = format_traceparent(
            trace.trace_id, prefill_span.span_id)
    t0 = time.time()
    try:
        await send_request_to_prefiller(
            session, prefill_url, endpoint, prefill_json, headers
        )
    except aiohttp.ClientError as e:
        monitor.on_request_complete(prefill_url, request_id, time.time())
        if trace is not None:
            prefill_span.finish(error=str(e))
            root.finish(status=502)
            recorder.record(trace)
        return web.json_response({"error": f"Prefill failed: {e}"}, status=502)
    ttft = time.time() - t0
    if prefill_span is not None:
        prefill_span.finish()
    monitor.on_request_response(prefill_url, request_id, time.time())
    monitor.on_request_complete(prefill_url, request_id, time.time())
    logger.info("Disagg prefill for %s took %.3f s (TTFT)", request_id, ttft)

    # Tell the decode engine to pull the prefilled KV from the prefill
    # engine (data moves engine-to-engine; this is only the control
    # message — the reference's out-of-band NIXL transfer equivalent).
    # Failure is non-fatal: decode recomputes the prefix.
    if prefill_url != decode_url:
        pull_span = None
        if trace is not None:
            pull_span = trace.start_span(
                "router.kv_pull", source=prefill_url, target=decode_url)
            headers["traceparent"] = format_traceparent(
                trace.trace_id, pull_span.span_id)
        # The pull is a control+transfer exchange, not a token stream:
        # a total deadline fits. With fault tolerance on, the TTFT
        # deadline governs it instead of the historical flat 60s.
        ft = getattr(state, "fault_tolerance", None)
        pull_timeout = 60.0
        if ft is not None and ft.config.ttft_deadline_s:
            pull_timeout = ft.config.ttft_deadline_s
        try:
            async with session.post(
                f"{decode_url}/kv/pull",
                json={"source_url": prefill_url, "request": request_json},
                headers={k: headers[k] for k in ("X-Request-Id", "traceparent")
                         if k in headers},
                timeout=aiohttp.ClientTimeout(total=pull_timeout),
            ) as pull_resp:
                pull = await pull_resp.json()
                logger.info(
                    "Disagg KV pull for %s: %s", request_id, pull)
            if pull_span is not None:
                pull_span.finish(status="ok")
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            if pull_span is not None:
                pull_span.finish(error=str(e))
            logger.warning(
                "Disagg KV pull failed for %s (decode will recompute): %s",
                request_id, e)

    decode_json = dict(request_json)
    for k, v in saved.items():
        if v is not None:
            decode_json[k] = v
    body = json.dumps(decode_json).encode()
    headers["Content-Type"] = "application/json"

    upstream = None
    if trace is not None:
        upstream = trace.start_span("router.upstream", engine=decode_url)
        headers["traceparent"] = format_traceparent(
            trace.trace_id, upstream.span_id)

    stream = process_request(
        state, request_id, decode_url, endpoint, body, headers
    )
    response: Optional[web.StreamResponse] = None
    got_first_chunk = False
    relay = getattr(state, "relay", None)
    relay_job = None
    relay_tried = False
    try:
        async for kind, payload in stream:
            if kind == "headers":
                status, hdrs = payload
                response = web.StreamResponse(status=status)
                ct = hdrs.get("Content-Type")
                if ct:
                    response.content_type = ct.split(";")[0]
                response.headers["X-Request-Id"] = request_id
                await response.prepare(request)
            else:
                if trace is not None and not got_first_chunk:
                    got_first_chunk = True
                    trace.add_span(
                        "router.first_chunk", upstream.start, time.time(),
                        parent=upstream,
                    )
                assert response is not None
                if relay_job is not None:
                    waiter = relay_job.feed_nowait(payload)
                    if waiter is not None:
                        await waiter
                else:
                    await response.write(payload)
                    if relay is not None and not relay_tried:
                        relay_tried = True
                        relay_job = await relay.try_handoff(
                            request, response, server_url=decode_url)
        if response is None:
            return web.json_response({"error": "Empty decode response"}, status=502)
        if relay_job is not None:
            await relay_job.finish()
            relay_seal_response(response)
        else:
            await response.write_eof()
        return response
    finally:
        if relay_job is not None:
            relay_job.ensure_closed()
            relay_job.settle()
        if trace is not None:
            status = response.status if response is not None else 0
            upstream.finish(status=status)
            # Overhead excludes both engine phases (prefill + decode); the
            # KV pull stays counted — it is router-orchestrated transfer.
            engine_s = upstream.duration_s
            if prefill_span is not None:
                engine_s += prefill_span.duration_s
            overhead = max(0.0, (time.time() - root.start) - engine_s)
            from production_stack_tpu.router import metrics as router_metrics
            router_metrics.hist_router_overhead.labels(
                server=decode_url).observe(overhead)
            root.finish(status=status, overhead_s=round(overhead, 6))
            recorder.record(trace)


async def route_sleep_wakeup_request(
    request: web.Request, action: str
) -> web.Response:
    """Proxy /sleep, /wake_up, /is_sleeping to a specific engine
    (reference request.py:434-510). Engine chosen by ``url`` query param or
    model name; discovery sleep status is refreshed after the call."""
    state = request.app["state"]
    session = get_client_session()
    target_url = request.query.get("url")
    model = request.query.get("model")
    endpoints = state.service_discovery.get_endpoint_info()
    if target_url:
        matches = [ep for ep in endpoints if ep.url == target_url]
    elif model:
        matches = [ep for ep in endpoints if ep.serves(model)]
    else:
        matches = list(endpoints)
    if not matches:
        return web.json_response({"error": "No matching engine"}, status=404)
    results = {}
    for ep in matches:
        try:
            if action == "is_sleeping":
                async with session.get(f"{ep.url}/is_sleeping") as resp:
                    results[ep.url] = await resp.json()
            else:
                params = dict(request.query)
                params.pop("url", None)
                params.pop("model", None)
                async with session.post(
                    f"{ep.url}/{action}", params=params
                ) as resp:
                    results[ep.url] = {"status": resp.status}
                if hasattr(state.service_discovery, "set_sleep_status"):
                    state.service_discovery.set_sleep_status(
                        ep.url, action == "sleep"
                    )
        except aiohttp.ClientError as e:
            results[ep.url] = {"error": str(e)}
    return web.json_response(results)


async def route_general_transcriptions(request: web.Request) -> web.StreamResponse:
    """Proxy multipart audio transcription requests (reference :513-689)."""
    state = request.app["state"]
    request_id = request.headers.get("X-Request-Id") or str(uuid.uuid4())
    reader = await request.multipart()
    form = aiohttp.FormData()
    model = None
    while True:
        part = await reader.next()
        if part is None:
            break
        if part.name == "file":
            payload = await part.read(decode=False)
            form.add_field(
                "file", payload,
                filename=part.filename or "audio.wav",
                content_type=part.headers.get("Content-Type", "audio/wav"),
            )
        else:
            value = (await part.read(decode=False)).decode()
            if part.name == "model":
                model = value
            form.add_field(part.name, value)
    endpoints = [
        ep for ep in state.service_discovery.get_endpoint_info()
        if not ep.sleep and (model is None or ep.serves(model))
    ]
    if not endpoints:
        return web.json_response(
            {"error": f"Model {model} not found"}, status=400
        )
    engine_stats = state.engine_stats_scraper.get_engine_stats()
    request_stats = state.request_stats_monitor.get_request_stats()
    import inspect

    route_result = state.router.route_request(
        endpoints, engine_stats, request_stats, dict(request.headers), None
    )
    url = await route_result if inspect.isawaitable(route_result) else route_result
    monitor = state.request_stats_monitor
    monitor.on_new_request(url, request_id, time.time())
    session = get_client_session()
    try:
        async with session.post(
            f"{url}/v1/audio/transcriptions", data=form
        ) as resp:
            monitor.on_request_response(url, request_id, time.time())
            data = await resp.read()
            return web.Response(
                body=data, status=resp.status,
                content_type=resp.headers.get("Content-Type", "application/json").split(";")[0],
            )
    finally:
        monitor.on_request_complete(url, request_id, time.time())


async def _maybe_await(value):
    import inspect

    if inspect.isawaitable(value):
        return await value
    return value
