"""Per-engine request statistics: sliding-window QPS, TTFT, latency.

Rebuild of reference ``src/vllm_router/stats/request_stats.py`` (314 LoC):
:class:`MovingAverageMonitor` (reference ``:58-103``) and
:class:`RequestStatsMonitor` with the ``on_new_request`` /
``on_request_response`` / ``on_request_complete`` hook trio the request
service calls around every proxied request (reference ``:145-236``), and
``get_request_stats`` producing the per-URL snapshot that feeds both the
session-router QPS fallback and ``/metrics`` (reference ``:238-306``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Tuple

from production_stack_tpu.router import metrics
from production_stack_tpu.utils.misc import SingletonMeta


@dataclass
class RequestStats:
    """Snapshot of one engine's request statistics (reference :31-55)."""

    qps: float = 0.0
    ttft: float = -1.0
    in_prefill_requests: int = 0
    in_decoding_requests: int = 0
    finished_requests: int = 0
    uncomputed_latency_requests: int = 0
    avg_decoding_length: float = -1.0
    avg_latency: float = -1.0
    avg_itl: float = -1.0
    num_swapped_requests: int = 0


class MovingAverageMonitor:
    """Sliding time-window average (reference :58-103)."""

    def __init__(self, sliding_window_size: float):
        self.window = sliding_window_size
        self.timestamps: Deque[float] = deque()
        self.values: Deque[float] = deque()
        self._sum = 0.0

    def update(self, timestamp: float, value: float) -> None:
        self.timestamps.append(timestamp)
        self.values.append(value)
        self._sum += value
        self._expire(timestamp)

    def update_no_value(self, timestamp: float) -> None:
        self.update(timestamp, 0.0)

    def _expire(self, now: float) -> None:
        while self.timestamps and now - self.timestamps[0] > self.window:
            self.timestamps.popleft()
            self._sum -= self.values.popleft()

    def get_average(self) -> float:
        if not self.values:
            return -1.0
        return self._sum / len(self.values)

    def get_sum(self) -> float:
        return self._sum

    def get_count(self) -> int:
        return len(self.values)


class RequestStatsMonitor(metaclass=SingletonMeta):
    """Tracks per-engine request lifecycle statistics (reference :106-306)."""

    def __init__(self, sliding_window_size: float = 60.0):
        if hasattr(self, "_initialized"):
            return
        self._initialized = True
        self.sliding_window_size = sliding_window_size
        self._lock = threading.Lock()
        self.qps_monitors: Dict[str, MovingAverageMonitor] = {}
        self.ttft_monitors: Dict[str, MovingAverageMonitor] = {}
        self.latency_monitors: Dict[str, MovingAverageMonitor] = {}
        self.decoding_length_monitors: Dict[str, MovingAverageMonitor] = {}
        self.itl_monitors: Dict[str, MovingAverageMonitor] = {}
        # (engine_url, request_id) -> timestamps
        self.request_start_time: Dict[Tuple[str, str], float] = {}
        self.first_token_time: Dict[Tuple[str, str], float] = {}
        self.last_token_time: Dict[Tuple[str, str], float] = {}
        self.tokens_seen: Dict[Tuple[str, str], int] = {}
        self.in_prefill: Dict[str, int] = {}
        self.in_decoding: Dict[str, int] = {}
        self.finished: Dict[str, int] = {}
        self.swapped: Dict[str, int] = {}
        # Cached histogram children: labels() takes the metric-wide lock
        # and rebuilds the label tuple; on_token runs per streamed token,
        # so resolve each engine's child once.
        self._hists: Dict[str, Tuple] = {}

    def _hist(self, engine_url: str) -> Tuple:
        h = self._hists.get(engine_url)
        if h is None:
            h = (metrics.hist_ttft.labels(server=engine_url),
                 metrics.hist_latency.labels(server=engine_url),
                 metrics.hist_itl.labels(server=engine_url))
            self._hists[engine_url] = h
        return h

    # -- lifecycle hooks ----------------------------------------------------
    def on_new_request(self, engine_url: str, request_id: str, timestamp: float) -> None:
        with self._lock:
            self.request_start_time[(engine_url, request_id)] = timestamp
            self.in_prefill[engine_url] = self.in_prefill.get(engine_url, 0) + 1
            mon = self.qps_monitors.setdefault(
                engine_url, MovingAverageMonitor(self.sliding_window_size)
            )
            mon.update_no_value(timestamp)

    def on_request_response(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """First stream chunk received → TTFT; request moves prefill→decode."""
        with self._lock:
            key = (engine_url, request_id)
            if key not in self.request_start_time:
                return
            ttft = timestamp - self.request_start_time[key]
            self.first_token_time[key] = timestamp
            self.last_token_time[key] = timestamp
            self.tokens_seen[key] = 1
            self.ttft_monitors.setdefault(
                engine_url, MovingAverageMonitor(self.sliding_window_size)
            ).update(timestamp, ttft)
            self._hist(engine_url)[0].observe(ttft)
            self.in_prefill[engine_url] = max(
                0, self.in_prefill.get(engine_url, 0) - 1
            )
            self.in_decoding[engine_url] = self.in_decoding.get(engine_url, 0) + 1

    def on_token(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """Optional per-chunk hook: feeds inter-token latency."""
        with self._lock:
            key = (engine_url, request_id)
            last = self.last_token_time.get(key)
            if last is not None:
                self.itl_monitors.setdefault(
                    engine_url, MovingAverageMonitor(self.sliding_window_size)
                ).update(timestamp, timestamp - last)
                self._hist(engine_url)[2].observe(timestamp - last)
            self.last_token_time[key] = timestamp
            self.tokens_seen[key] = self.tokens_seen.get(key, 0) + 1

    def on_request_complete(self, engine_url: str, request_id: str, timestamp: float) -> None:
        with self._lock:
            key = (engine_url, request_id)
            start = self.request_start_time.pop(key, None)
            first = self.first_token_time.pop(key, None)
            self.last_token_time.pop(key, None)
            ntokens = self.tokens_seen.pop(key, 0)
            if first is not None:
                self.in_decoding[engine_url] = max(
                    0, self.in_decoding.get(engine_url, 0) - 1
                )
                self.decoding_length_monitors.setdefault(
                    engine_url, MovingAverageMonitor(self.sliding_window_size)
                ).update(timestamp, timestamp - first)
            else:
                self.in_prefill[engine_url] = max(
                    0, self.in_prefill.get(engine_url, 0) - 1
                )
            if start is not None:
                self.latency_monitors.setdefault(
                    engine_url, MovingAverageMonitor(self.sliding_window_size)
                ).update(timestamp, timestamp - start)
                self._hist(engine_url)[1].observe(timestamp - start)
            self.finished[engine_url] = self.finished.get(engine_url, 0) + 1

    def on_request_swapped(self, engine_url: str, request_id: str, timestamp: float) -> None:
        with self._lock:
            self.swapped[engine_url] = self.swapped.get(engine_url, 0) + 1

    # -- snapshot ----------------------------------------------------------
    def get_request_stats(self, current_time: Optional[float] = None) -> Dict[str, RequestStats]:
        now = current_time if current_time is not None else time.time()
        out: Dict[str, RequestStats] = {}
        with self._lock:
            urls = (
                set(self.qps_monitors)
                | set(self.in_prefill)
                | set(self.in_decoding)
                | set(self.finished)
            )
            for url in urls:
                qps_mon = self.qps_monitors.get(url)
                if qps_mon is not None:
                    qps_mon._expire(now)
                    qps = qps_mon.get_count() / self.sliding_window_size
                else:
                    qps = 0.0
                ttft_mon = self.ttft_monitors.get(url)
                lat_mon = self.latency_monitors.get(url)
                dec_mon = self.decoding_length_monitors.get(url)
                itl_mon = self.itl_monitors.get(url)
                out[url] = RequestStats(
                    qps=qps,
                    ttft=ttft_mon.get_average() if ttft_mon else -1.0,
                    in_prefill_requests=self.in_prefill.get(url, 0),
                    in_decoding_requests=self.in_decoding.get(url, 0),
                    finished_requests=self.finished.get(url, 0),
                    uncomputed_latency_requests=len(
                        [k for k in self.request_start_time if k[0] == url]
                    ),
                    avg_decoding_length=dec_mon.get_average() if dec_mon else -1.0,
                    avg_latency=lat_mon.get_average() if lat_mon else -1.0,
                    avg_itl=itl_mon.get_average() if itl_mon else -1.0,
                    num_swapped_requests=self.swapped.get(url, 0),
                )
        return out


def initialize_request_stats_monitor(sliding_window_size: float = 60.0) -> RequestStatsMonitor:
    return RequestStatsMonitor(sliding_window_size)


def get_request_stats_monitor() -> RequestStatsMonitor:
    return RequestStatsMonitor()
