"""OpenAI-compatible request router (the stack's data plane).

TPU-native rebuild of the reference's ``src/vllm_router/`` package: service
discovery, routing algorithms (roundrobin / session / prefix-aware /
kv-aware / disaggregated-prefill), streaming request proxy, stats, metrics,
dynamic config, files/batch APIs and experimental features — served by
aiohttp (the reference uses FastAPI/uvicorn; aiohttp gives us a single
event-loop data plane with no ASGI layer in the hot path).
"""
