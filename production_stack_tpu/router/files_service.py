"""OpenAI Files API backed by local disk.

Rebuild of reference ``src/vllm_router/services/files_service/``
(``file_storage.py:27-136``, ``storage.py``): `Storage` ABC + `FileStorage`
storing file bytes and metadata under a root directory, addressed by
``file-<uuid>`` ids.
"""

from __future__ import annotations

import abc
import asyncio
import json
import os
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

try:
    import aiofiles
except ImportError:  # serving image pins deps; fall back to the executor
    aiofiles = None

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class _ThreadFile:
    """``aiofiles.open`` stand-in: sync I/O pushed to the default executor
    so the event loop never blocks on disk."""

    def __init__(self, path: str, mode: str):
        self._path = path
        self._mode = mode
        self._f = None

    async def __aenter__(self) -> "_ThreadFile":
        loop = asyncio.get_running_loop()
        self._f = await loop.run_in_executor(None, open, self._path, self._mode)
        return self

    async def __aexit__(self, *exc) -> None:
        await asyncio.get_running_loop().run_in_executor(None, self._f.close)

    async def read(self):
        return await asyncio.get_running_loop().run_in_executor(
            None, self._f.read)

    async def write(self, data):
        return await asyncio.get_running_loop().run_in_executor(
            None, self._f.write, data)


def _aopen(path: str, mode: str = "r"):
    if aiofiles is not None:
        return aiofiles.open(path, mode)
    return _ThreadFile(path, mode)


@dataclass
class FileInfo:
    id: str
    object: str = "file"
    bytes: int = 0
    created_at: int = field(default_factory=lambda: int(time.time()))
    filename: str = ""
    purpose: str = "batch"

    def metadata(self) -> dict:
        return asdict(self)


class Storage(abc.ABC):
    @abc.abstractmethod
    async def save_file(self, filename: str, content: bytes, purpose: str) -> FileInfo: ...

    @abc.abstractmethod
    async def get_file(self, file_id: str) -> FileInfo: ...

    @abc.abstractmethod
    async def get_file_content(self, file_id: str) -> bytes: ...

    @abc.abstractmethod
    async def list_files(self) -> List[FileInfo]: ...

    @abc.abstractmethod
    async def delete_file(self, file_id: str) -> None: ...


class FileStorage(Storage):
    """Local-disk file storage (reference file_storage.py:27-136)."""

    def __init__(self, base_path: str = "/tmp/tpu_stack_files"):
        self.base_path = base_path
        os.makedirs(base_path, exist_ok=True)

    def _dir(self, file_id: str) -> str:
        return os.path.join(self.base_path, file_id)

    async def save_file(
        self, filename: str, content: bytes, purpose: str = "batch",
        file_id: Optional[str] = None,
    ) -> FileInfo:
        file_id = file_id or f"file-{uuid.uuid4().hex}"
        info = FileInfo(
            id=file_id, bytes=len(content), filename=filename, purpose=purpose
        )
        os.makedirs(self._dir(file_id), exist_ok=True)
        async with _aopen(
            os.path.join(self._dir(file_id), filename), "wb"
        ) as f:
            await f.write(content)
        async with _aopen(
            os.path.join(self._dir(file_id), "metadata.json"), "w"
        ) as f:
            await f.write(json.dumps(info.metadata()))
        return info

    async def get_file(self, file_id: str) -> FileInfo:
        path = os.path.join(self._dir(file_id), "metadata.json")
        try:
            async with _aopen(path) as f:
                return FileInfo(**json.loads(await f.read()))
        except FileNotFoundError:
            raise FileNotFoundError(f"File {file_id} not found")

    async def get_file_content(self, file_id: str) -> bytes:
        info = await self.get_file(file_id)
        async with _aopen(
            os.path.join(self._dir(file_id), info.filename), "rb"
        ) as f:
            return await f.read()

    async def list_files(self) -> List[FileInfo]:
        out = []
        for name in sorted(os.listdir(self.base_path)):
            if name.startswith("file-"):
                try:
                    out.append(await self.get_file(name))
                except FileNotFoundError:
                    continue
        return out

    async def delete_file(self, file_id: str) -> None:
        import shutil

        shutil.rmtree(self._dir(file_id), ignore_errors=True)


_storages: Dict[str, Storage] = {}


def initialize_storage(storage_class: str = "local_file", base_path: str = "/tmp/tpu_stack_files") -> Storage:
    if storage_class != "local_file":
        raise ValueError(f"Unsupported storage class {storage_class}")
    storage = FileStorage(base_path)
    _storages["default"] = storage
    return storage


def get_storage() -> Storage:
    if "default" not in _storages:
        raise RuntimeError("Storage not initialized")
    return _storages["default"]
