"""Routing algorithms: which engine gets a request.

Rebuild of reference ``src/vllm_router/routers/routing_logic.py`` (526 LoC):

- :class:`RoundRobinRouter` (reference ``:139-167``)
- :class:`SessionRouter` -- consistent-hash ring on a session header with
  lowest-QPS fallback (reference ``:185-219``; the reference uses the
  ``uhashring`` package — we implement the ring natively).
- :class:`PrefixAwareRouter` -- xxhash chunk trie longest-prefix match
  (reference ``:363-423``).
- :class:`KvawareRouter` -- asks the KV controller which engine already holds
  the longest token-prefix of the request (reference ``:264-344``; LMCache
  controller is replaced by :mod:`production_stack_tpu.kv.controller`).
- :class:`DisaggregatedPrefillRouter` -- splits engines into prefill/decode
  pools by model label (reference ``:437-466``).
"""

from __future__ import annotations

import abc
import bisect
import enum
import hashlib
import random
import threading
from typing import Dict, List, Optional

import xxhash

from production_stack_tpu.router.hashtrie import HashTrie
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.misc import SingletonABCMeta

logger = init_logger(__name__)

_global_router: Optional["RoutingInterface"] = None


class RoutingLogic(enum.Enum):
    ROUND_ROBIN = "roundrobin"
    SESSION_BASED = "session"
    KVAWARE = "kvaware"
    PREFIXAWARE = "prefixaware"
    DISAGGREGATED_PREFILL = "disaggregated_prefill"


class RoutingInterface(metaclass=SingletonABCMeta):
    @abc.abstractmethod
    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats: Optional[Dict[str, "EngineStats"]],
        request_stats: Optional[Dict[str, "RequestStats"]],
        request_headers: Dict[str, str],
        request_json: Optional[dict] = None,
    ) -> str:
        """Return the URL of the engine to send this request to."""


class RoundRobinRouter(RoutingInterface):
    """Cycle through endpoints sorted by URL (reference :139-167)."""

    def __init__(self):
        self.req_id = 0
        self._lock = threading.Lock()

    def route_request(
        self, endpoints, engine_stats, request_stats, request_headers,
        request_json=None,
    ) -> str:
        if not endpoints:
            raise ValueError("No available endpoints")
        chosen = sorted(endpoints, key=lambda e: e.url)
        with self._lock:
            url = chosen[self.req_id % len(chosen)].url
            self.req_id += 1
        return url


class HashRing:
    """Consistent-hash ring with virtual nodes (replaces uhashring)."""

    def __init__(self, nodes: List[str], vnodes: int = 100):
        self.vnodes = vnodes
        self._ring: List[int] = []
        self._map: Dict[int, str] = {}
        self._nodes: List[str] = []
        self.rebuild(nodes)

    def rebuild(self, nodes: List[str]) -> None:
        self._nodes = sorted(nodes)
        self._ring = []
        self._map = {}
        for node in self._nodes:
            for v in range(self.vnodes):
                h = int(hashlib.md5(f"{node}#{v}".encode()).hexdigest()[:16], 16)
                self._map[h] = node
                self._ring.append(h)
        self._ring.sort()

    @property
    def nodes(self) -> List[str]:
        return list(self._nodes)

    def get_node(self, key: str) -> str:
        if not self._ring:
            raise ValueError("Empty hash ring")
        h = int(hashlib.md5(key.encode()).hexdigest()[:16], 16)
        idx = bisect.bisect(self._ring, h) % len(self._ring)
        return self._map[self._ring[idx]]


class SessionRouter(RoutingInterface):
    """Sticky sessions on a header key; lowest-QPS fallback (reference :185-219)."""

    def __init__(self, session_key: str = "x-user-id"):
        self.session_key = session_key.lower()
        self._ring = HashRing([])
        self._lock = threading.Lock()

    def _qps_fallback(self, endpoints, request_stats) -> str:
        if not request_stats:
            return random.choice(endpoints).url
        best_url, best_qps = None, float("inf")
        for ep in endpoints:
            stats = request_stats.get(ep.url)
            qps = stats.qps if stats is not None else 0.0
            if qps < best_qps:
                best_url, best_qps = ep.url, qps
        return best_url or endpoints[0].url

    def route_request(
        self, endpoints, engine_stats, request_stats, request_headers,
        request_json=None,
    ) -> str:
        if not endpoints:
            raise ValueError("No available endpoints")
        urls = sorted(e.url for e in endpoints)
        headers = {k.lower(): v for k, v in (request_headers or {}).items()}
        session_id = headers.get(self.session_key)
        if session_id is None:
            return self._qps_fallback(endpoints, request_stats)
        with self._lock:
            if self._ring.nodes != urls:
                self._ring.rebuild(urls)
            return self._ring.get_node(str(session_id))


def _extract_prompt(request_json: Optional[dict]) -> str:
    if not request_json:
        return ""
    if "prompt" in request_json:
        p = request_json["prompt"]
        return p if isinstance(p, str) else str(p)
    if "messages" in request_json:
        parts = []
        for m in request_json["messages"]:
            c = m.get("content")
            if isinstance(c, str):
                parts.append(c)
            elif isinstance(c, list):
                parts.extend(
                    seg.get("text", "") for seg in c if isinstance(seg, dict)
                )
        return "\n".join(parts)
    return ""


def _adapter_salt(request_json: Optional[dict],
                  endpoints: List[EndpointInfo]) -> Optional[str]:
    """LoRA adapter salt for prefix/KV keying: the request's model name iff
    it names an adapter resident on some endpoint (rather than a base
    model). Base-model requests return None, keeping today's hash keys
    byte-identical when no adapters are configured."""
    if not request_json:
        return None
    model = request_json.get("model")
    if not model:
        return None
    for ep in endpoints:
        if model in (ep.lora_adapters or ()):
            return model
    return None


class PrefixAwareRouter(RoutingInterface):
    """Longest-prefix-match over a hash trie (reference :363-423).

    Same-prefix requests land on the same engine so its KV prefix cache hits;
    ties broken randomly; the chosen (prompt, endpoint) pair is inserted back
    into the trie after the pick.

    When the native (C++) picker library is built, the trie lives there —
    the compiled-router path that the reference provides as a Go gateway
    plugin (``prefix_aware_picker.go``). Hash chunking is identical
    (xxhash64 over 128-char chunks), so the two backends route alike.
    """

    def __init__(self, chunk_size: int = 128, use_native: bool = True):
        self.trie = HashTrie(chunk_size=chunk_size)
        self._native = None
        if use_native:
            try:
                from production_stack_tpu import native

                if native.available():
                    self._native = native.NativePicker()
                    logger.info(
                        "PrefixAwareRouter using native C++ picker")
            except Exception:  # noqa: BLE001 - fall back to Python trie
                self._native = None

    async def route_request(
        self, endpoints, engine_stats, request_stats, request_headers,
        request_json=None,
    ) -> str:
        if not endpoints:
            raise ValueError("No available endpoints")
        prompt = _extract_prompt(request_json)
        available = {e.url for e in endpoints}
        if not prompt:
            return random.choice(sorted(available))
        salt = _adapter_salt(request_json, endpoints)
        if self._native is not None and salt is None:
            # The native picker has no salt support — adapter-salted
            # requests fall through to the Python trie.
            self._native.set_endpoints(sorted(available))
            url = self._native.pick_prefix(prompt)
            if url:
                return url
            return random.choice(sorted(available))
        matched, candidates = await self.trie.longest_prefix_match(
            prompt, available, salt=salt
        )
        url = random.choice(sorted(candidates))
        await self.trie.insert(prompt, url, salt=salt)
        return url


class KvawareRouter(RoutingInterface):
    """KV-controller-backed routing (reference :264-344).

    Tokenizes the prompt (chunk-hash granularity — the controller indexes
    chunk hashes, not raw tokens) and asks the KV controller which engine
    holds the longest stored prefix. If the match is shorter than
    ``len - threshold`` tokens, falls back to session routing.
    """

    def __init__(
        self,
        kv_controller=None,
        threshold: int = 2000,
        session_key: str = "x-user-id",
    ):
        from production_stack_tpu.kv.controller import get_kv_controller

        self.kv_controller = kv_controller or get_kv_controller()
        self.threshold = threshold
        self._fallback = SessionRouter.__new__(SessionRouter)
        self._fallback.__init__(session_key)  # bypass singleton cache

    async def route_request(
        self, endpoints, engine_stats, request_stats, request_headers,
        request_json=None,
    ) -> str:
        if not endpoints:
            raise ValueError("No available endpoints")
        prompt = _extract_prompt(request_json)
        if prompt and self.kv_controller is not None:
            try:
                salt = _adapter_salt(request_json, endpoints)
                match = await self.kv_controller.lookup(prompt, salt=salt)
                if match is not None:
                    matched_len, instance_id = match
                    if matched_len >= max(len(prompt) - self.threshold, 1):
                        url = await self.kv_controller.instance_url(instance_id)
                        if url and url in {e.url for e in endpoints}:
                            return url
            except Exception as e:  # noqa: BLE001
                logger.warning("KV controller lookup failed: %s", e)
        return self._fallback.route_request(
            endpoints, engine_stats, request_stats, request_headers, request_json
        )


class DisaggregatedPrefillRouter(RoutingInterface):
    """Split endpoints into prefill/decode pools by model label (reference :437-466).

    The request service drives the actual two-phase flow; this router exposes
    the pool membership test and per-pool round-robin pick.
    """

    def __init__(
        self,
        prefill_model_labels: List[str],
        decode_model_labels: List[str],
    ):
        self.prefill_model_labels = prefill_model_labels
        self.decode_model_labels = decode_model_labels
        self._counters = {"prefill": 0, "decode": 0}
        self._lock = threading.Lock()

    def pool(self, endpoints: List[EndpointInfo], role: str) -> List[EndpointInfo]:
        labels = (
            self.prefill_model_labels if role == "prefill"
            else self.decode_model_labels
        )
        return [e for e in endpoints if e.model_label in labels]

    def pick(self, endpoints: List[EndpointInfo], role: str) -> str:
        pool = sorted(self.pool(endpoints, role), key=lambda e: e.url)
        if not pool:
            raise ValueError(f"No available {role} endpoints")
        with self._lock:
            url = pool[self._counters[role] % len(pool)].url
            self._counters[role] += 1
        return url

    def route_request(
        self, endpoints, engine_stats, request_stats, request_headers,
        request_json=None,
    ) -> str:
        return self.pick(endpoints, "decode")


def initialize_routing_logic(
    routing_logic: "RoutingLogic | str", **kwargs
) -> RoutingInterface:
    """Build and register the global router (reference :470-497)."""
    global _global_router
    if isinstance(routing_logic, str):
        routing_logic = RoutingLogic(routing_logic)
    if routing_logic == RoutingLogic.ROUND_ROBIN:
        _global_router = RoundRobinRouter()
    elif routing_logic == RoutingLogic.SESSION_BASED:
        _global_router = SessionRouter(kwargs.get("session_key") or "x-user-id")
    elif routing_logic == RoutingLogic.PREFIXAWARE:
        _global_router = PrefixAwareRouter()
    elif routing_logic == RoutingLogic.KVAWARE:
        _global_router = KvawareRouter(
            kv_controller=kwargs.get("kv_controller"),
            threshold=kwargs.get("kv_aware_threshold") or 2000,
            session_key=kwargs.get("session_key") or "x-user-id",
        )
    elif routing_logic == RoutingLogic.DISAGGREGATED_PREFILL:
        _global_router = DisaggregatedPrefillRouter(
            kwargs.get("prefill_model_labels") or [],
            kwargs.get("decode_model_labels") or [],
        )
    else:
        raise ValueError(f"Invalid routing logic {routing_logic}")
    logger.info("Routing logic initialized: %s", routing_logic.value)
    return _global_router


def get_routing_logic() -> RoutingInterface:
    if _global_router is None:
        raise RuntimeError("Routing logic not initialized")
    return _global_router


def reconfigure_routing_logic(routing_logic, **kwargs) -> RoutingInterface:
    """Hot-swap the routing logic (used by the dynamic config watcher)."""
    for cls in (
        RoundRobinRouter, SessionRouter, PrefixAwareRouter,
        KvawareRouter, DisaggregatedPrefillRouter,
    ):
        SingletonABCMeta._reset_instance(cls)
    return initialize_routing_logic(routing_logic, **kwargs)
