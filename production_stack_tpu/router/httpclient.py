"""Shared aiohttp client session (reference src/vllm_router/aiohttp_client.py:21-48)."""

from __future__ import annotations

from typing import Optional

import aiohttp

from production_stack_tpu.utils.misc import SingletonMeta


class AiohttpClientWrapper(metaclass=SingletonMeta):
    """Singleton wrapper; session created lazily on the running loop."""

    def __init__(self):
        if hasattr(self, "_initialized"):
            return
        self._initialized = True
        self._session: Optional[aiohttp.ClientSession] = None

    def session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            # No total timeout at the session level: a flat total cap
            # kills legitimate long generations. Per-request liveness is
            # enforced by the fault-tolerance layer's TTFT and
            # inter-chunk deadlines (request_service.process_request);
            # sock_connect bounds only the TCP handshake.
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0),
                timeout=aiohttp.ClientTimeout(total=None, sock_connect=30),
            )
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()
        self._session = None


def get_client_session() -> aiohttp.ClientSession:
    return AiohttpClientWrapper().session()
