"""Async prefix hash trie for prefix-aware routing.

Rebuild of reference ``src/vllm_router/prefix/hashtrie.py:24-103``: prompts
are split into fixed-size character chunks, each chunk hashed with xxhash64,
and the hash sequence inserted into a trie whose nodes record which endpoints
have seen that prefix. ``longest_prefix_match`` walks the trie intersecting
node endpoint-sets with the currently-available endpoints.

Differences from the reference: one asyncio lock per *trie* rather than per
node. The router is single-event-loop, so per-node locks buy nothing, and a
single lock makes eviction (which the reference lacks) race-free. We also add
LRU-ish eviction to bound memory over long uptimes.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Set, Tuple

import xxhash

import asyncio


class TrieNode:
    __slots__ = ("children", "endpoints", "last_access")

    def __init__(self):
        self.children: Dict[int, "TrieNode"] = {}
        self.endpoints: Set[str] = set()
        self.last_access: float = time.monotonic()


class HashTrie:
    def __init__(self, chunk_size: int = 128, max_nodes: int = 1_000_000):
        self.chunk_size = chunk_size
        self.max_nodes = max_nodes
        self.root = TrieNode()
        self.node_count = 0
        self._lock = asyncio.Lock()

    def _chunk_hashes(self, text: str, salt: Optional[str] = None):
        # ``salt`` partitions the hash space (LoRA adapter isolation —
        # salted chunks never collide with base-model ones). Chunk
        # boundaries are unchanged; None/"" is byte-identical to today.
        if salt:
            prefix = f"{salt}\x00"
            for i in range(0, len(text), self.chunk_size):
                yield xxhash.xxh64_intdigest(
                    prefix + text[i : i + self.chunk_size])
            return
        for i in range(0, len(text), self.chunk_size):
            yield xxhash.xxh64_intdigest(text[i : i + self.chunk_size])

    async def insert(self, text: str, endpoint: str,
                     salt: Optional[str] = None) -> None:
        async with self._lock:
            hashes = list(self._chunk_hashes(text, salt=salt))
            if not hashes:
                return
            now = time.monotonic()
            restarted = False
            while True:
                node = self.root
                top: Optional[TrieNode] = None
                detached = False
                for h in hashes:
                    nxt = node.children.get(h)
                    if nxt is None:
                        if self.node_count >= self.max_nodes:
                            # Eviction drops whole top-level subtrees.
                            # If it drops the one THIS insert is standing
                            # in, ``node`` is detached and every later
                            # chunk (plus its node_count increment) would
                            # land on an unreachable subtree, so
                            # node_count could never drain back down.
                            # First pass: evict freely but restart the
                            # walk if our subtree was the victim; on the
                            # retry pin it with ``exclude`` so the loop
                            # terminates (at worst overshooting
                            # max_nodes by one path length).
                            self._evict_oldest_locked(
                                exclude=hashes[0] if restarted else None)
                            if (top is not None
                                    and self.root.children.get(hashes[0])
                                    is not top):
                                detached = True
                                break
                        nxt = TrieNode()
                        node.children[h] = nxt
                        self.node_count += 1
                    nxt.last_access = now
                    nxt.endpoints.add(endpoint)
                    node = nxt
                    if top is None:
                        top = node
                if not detached:
                    return
                restarted = True

    async def longest_prefix_match(
        self, text: str, available_endpoints: Set[str],
        salt: Optional[str] = None,
    ) -> Tuple[int, Set[str]]:
        """Return (matched_chunk_count, endpoint set at the deepest match).

        The returned endpoints are always a subset of ``available_endpoints``;
        if nothing matches, (0, available_endpoints) is returned so callers
        can fall back to any endpoint (reference hashtrie.py:75-103).
        """
        async with self._lock:
            node = self.root
            matched = 0
            selected: Set[str] = set(available_endpoints)
            now = time.monotonic()
            for h in self._chunk_hashes(text, salt=salt):
                nxt = node.children.get(h)
                if nxt is None:
                    break
                live = nxt.endpoints & available_endpoints
                if not live:
                    break
                nxt.last_access = now
                selected = live
                matched += 1
                node = nxt
            return matched, selected

    async def remove_endpoint(self, endpoint: str) -> None:
        """Drop a dead endpoint from every node (cheap full walk)."""
        async with self._lock:
            stack = [self.root]
            while stack:
                node = stack.pop()
                node.endpoints.discard(endpoint)
                stack.extend(node.children.values())

    def _evict_oldest_locked(
        self, fraction: float = 0.1, exclude: Optional[int] = None
    ) -> None:
        """Evict the oldest-accessed top-level subtrees to free space.

        ``exclude`` names the top-level child a restarted insert is
        walking through; it is never evicted (see ``insert``). If it is
        the only subtree, nothing is evicted this round.
        """
        items = sorted(
            (kv for kv in self.root.children.items() if kv[0] != exclude),
            key=lambda kv: kv[1].last_access,
        )
        if not items:
            return
        n_evict = max(1, int(len(items) * fraction))
        for h, child in items[:n_evict]:
            self.node_count -= _count_nodes(child)
            del self.root.children[h]


def _count_nodes(node: TrieNode) -> int:
    total = 1
    stack = list(node.children.values())
    while stack:
        n = stack.pop()
        total += 1
        stack.extend(n.children.values())
    return total
