"""Router application: wiring, routes, lifespan, entrypoint.

Rebuild of reference ``src/vllm_router/app.py`` (304 LoC: ``initialize_all``
``:112-272``, ``lifespan``, ``main``) plus the OpenAI route table from
``routers/main_router.py:50-246`` and files/batches routers — served by
aiohttp instead of FastAPI/uvicorn.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from aiohttp import web

import production_stack_tpu
from production_stack_tpu.protocols import ModelCard, ModelList
from production_stack_tpu.router import metrics as metrics_mod
from production_stack_tpu.router import request_service
from production_stack_tpu.router.engine_stats import (
    EngineStatsScraper,
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.parser import parse_args
from production_stack_tpu.router.request_stats import (
    RequestStatsMonitor,
    initialize_request_stats_monitor,
)
from production_stack_tpu.router.routing_logic import initialize_routing_logic
from production_stack_tpu.router.service_discovery import (
    ServiceDiscoveryType,
    initialize_service_discovery,
)
from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.misc import (
    parse_comma_separated_args,
    parse_static_aliases,
    parse_static_model_types,
    parse_static_urls,
    set_ulimit,
)

logger = init_logger(__name__)


@dataclass
class RouterState:
    """Singletons attached to the aiohttp app (reference app.state, :268-272)."""

    service_discovery: Any = None
    router: Any = None
    engine_stats_scraper: Optional[EngineStatsScraper] = None
    request_stats_monitor: Optional[RequestStatsMonitor] = None
    request_rewriter: Any = None
    callbacks: Any = None
    feature_gates: Any = None
    semantic_cache: Any = None
    pii_detector: Any = None
    kv_controller: Any = None
    batch_queue: Any = None
    batch_processor: Any = None
    file_storage: Any = None
    dynamic_config_watcher: Any = None
    log_stats_thread: Optional[threading.Thread] = None
    trace_recorder: Any = None
    qos: Any = None  # QoSGate when --qos-tenants-file is set, else None
    fleet: Any = None  # FleetCache when --fleet-cache is set, else None
    autoscaler: Any = None  # AutoscaleRecommender when --autoscale is set
    # FaultTolerance bundle (circuit breaker + retry/deadline config)
    # when --fault-tolerance is set, else None (single-attempt path).
    fault_tolerance: Any = None
    slo: Any = None  # SLOEngine when --slo-config is set, else None
    lora: Any = None  # AdapterRegistry when --lora-plane is set, else None
    canary: Any = None  # CanaryProber when --canary-interval > 0
    events: Any = None  # EventJournal (always on; bounded ring is cheap)
    loop_monitor: Any = None  # LoopMonitor when --loop-monitor is set
    relay: Any = None  # RelayPump when --relay-off-loop is set, else None
    # Multi-worker plane (--router-workers; router/workers.py). Defaults
    # describe the single-process router: worker 0 of 1, no snapshot
    # sockets — /debug/snapshot and /debug/workers then serve local-only
    # views without any fan-in.
    worker_id: int = 0
    worker_count: int = 1
    worker_uds: tuple = ()
    worker_port: int = 0
    extra: dict = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Route handlers (reference routers/main_router.py:50-246)
# ---------------------------------------------------------------------------


def _proxy(endpoint: str):
    async def handler(request: web.Request) -> web.StreamResponse:
        state = request.app["state"]
        if state.semantic_cache is not None and endpoint == "/v1/chat/completions":
            hit = await state.semantic_cache.check(await request.json())
            if hit is not None:
                return web.json_response(hit)
        if state.loop_monitor is not None:
            # On-loop time of the whole proxied request, dominated by
            # the chunk-relay loop. The finer-grained components
            # (qos_admission, fleet_pull, slo_classify) are slices of
            # this same handler, so component totals are not disjoint.
            # With the relay pump on, the byte copy leaves the loop and
            # the residual control-plane cost is attributed under
            # "relay_feed" instead — so streaming_relay collapsing to
            # ~0 is a real measurement, not a relabeling.
            component = ("relay_feed" if state.relay is not None
                         else "streaming_relay")
            return await state.loop_monitor.components.wrap(
                component,
                request_service.route_general_request(request, endpoint))
        return await request_service.route_general_request(request, endpoint)

    return handler


async def show_models(request: web.Request) -> web.Response:
    state = request.app["state"]
    cards = [ModelCard(id=m) for m in state.service_discovery.get_model_names()]
    aliases = getattr(state.service_discovery, "aliases", None) or {}
    cards += [ModelCard(id=a, root=m) for a, m in aliases.items()]
    return web.json_response(ModelList(data=cards).model_dump())


async def show_engines(request: web.Request) -> web.Response:
    state = request.app["state"]
    engine_stats = state.engine_stats_scraper.get_engine_stats()
    request_stats = state.request_stats_monitor.get_request_stats()
    out = {}
    for ep in state.service_discovery.get_endpoint_info():
        es = engine_stats.get(ep.url)
        rs = request_stats.get(ep.url)
        out[ep.url] = {
            "model_names": ep.model_names,
            "model_label": ep.model_label,
            "sleep": ep.sleep,
            "engine_stats": es.__dict__ if es else None,
            "request_stats": rs.__dict__ if rs else None,
        }
    return web.json_response(out)


async def health(request: web.Request) -> web.Response:
    """Reference main_router.py:201-236: check threads are alive."""
    state = request.app["state"]
    # 503s carry Retry-After for client-backoff consistency with the
    # engine tier's kv-capacity 503 (engine/server.py).
    if not state.service_discovery.get_health():
        return web.json_response(
            {"status": "unhealthy", "reason": "service discovery down"},
            status=503, headers={"Retry-After": "1"}
        )
    if state.engine_stats_scraper and not state.engine_stats_scraper.get_health():
        return web.json_response(
            {"status": "unhealthy", "reason": "engine stats scraper down"},
            status=503, headers={"Retry-After": "1"},
        )
    if (
        state.dynamic_config_watcher is not None
        and not state.dynamic_config_watcher.get_health()
    ):
        return web.json_response(
            {"status": "unhealthy", "reason": "dynamic config watcher down"},
            status=503, headers={"Retry-After": "1"},
        )
    return web.json_response({"status": "healthy"})


async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": production_stack_tpu.__version__})


async def metrics_handler(request: web.Request) -> web.Response:
    state = request.app["state"]
    metrics_mod.update_gauges(
        state.service_discovery.get_endpoint_info(),
        state.engine_stats_scraper.get_engine_stats(),
        state.request_stats_monitor.get_request_stats(),
        fault_tolerance=state.fault_tolerance,
    )
    if state.trace_recorder is not None:
        metrics_mod.trace_sampled_out.set(
            state.trace_recorder.sampled_out_total)
        metrics_mod.slow_trace_logs_suppressed.set(
            state.trace_recorder.slow_logs_suppressed_total)
    if state.slo is not None:
        state.slo.refresh_gauges()
    if state.relay is not None:
        metrics_mod.mirror_relay_metrics(state.relay)
    if state.loop_monitor is not None:
        # Rendering /metrics is itself synchronous on-loop work worth
        # attributing (big registries serialize in milliseconds).
        with state.loop_monitor.components.measure("metrics_scrape"):
            metrics_mod.mirror_loop_metrics(state.loop_monitor)
            body = metrics_mod.render_metrics()
        return web.Response(
            body=body, content_type="text/plain", charset="utf-8")
    return web.Response(
        body=metrics_mod.render_metrics(),
        content_type="text/plain",
        charset="utf-8",
    )


async def dynamic_config_handler(request: web.Request) -> web.Response:
    state = request.app["state"]
    watcher = state.dynamic_config_watcher
    if watcher is None or watcher.get_current_config() is None:
        return web.json_response({"error": "dynamic config not enabled"}, status=404)
    return web.json_response(
        __import__("json").loads(watcher.get_current_config().to_json_str())
    )


# -- files & batches (reference routers/files_router.py, batches_router.py) --


async def upload_file(request: web.Request) -> web.Response:
    state = request.app["state"]
    reader = await request.multipart()
    filename, content, purpose = "upload", b"", "batch"
    while True:
        part = await reader.next()
        if part is None:
            break
        if part.name == "file":
            filename = part.filename or "upload"
            content = await part.read(decode=False)
        elif part.name == "purpose":
            purpose = (await part.read(decode=False)).decode()
    info = await state.file_storage.save_file(filename, content, purpose)
    return web.json_response(info.metadata())


async def get_file(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        info = await state.file_storage.get_file(request.match_info["file_id"])
    except FileNotFoundError:
        return web.json_response({"error": "file not found"}, status=404)
    return web.json_response(info.metadata())


async def list_files(request: web.Request) -> web.Response:
    state = request.app["state"]
    files = await state.file_storage.list_files()
    return web.json_response(
        {"object": "list", "data": [f.metadata() for f in files]}
    )


async def get_file_content(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        content = await state.file_storage.get_file_content(
            request.match_info["file_id"]
        )
    except FileNotFoundError:
        return web.json_response({"error": "file not found"}, status=404)
    return web.Response(body=content, content_type="application/octet-stream")


async def create_batch_handler(request: web.Request) -> web.Response:
    from production_stack_tpu.router.batch_service import create_batch

    state = request.app["state"]
    if state.batch_queue is None:
        return web.json_response({"error": "batch API not enabled"}, status=501)
    body = await request.json()
    batch = await create_batch(
        state.batch_queue,
        input_file_id=body["input_file_id"],
        endpoint=body.get("endpoint", "/v1/chat/completions"),
        completion_window=body.get("completion_window", "24h"),
        metadata=body.get("metadata"),
    )
    return web.json_response(batch.to_dict())


async def get_batch(request: web.Request) -> web.Response:
    state = request.app["state"]
    if state.batch_queue is None:
        return web.json_response({"error": "batch API not enabled"}, status=501)
    batch = await state.batch_queue.get(request.match_info["batch_id"])
    if batch is None:
        return web.json_response({"error": "batch not found"}, status=404)
    return web.json_response(batch.to_dict())


async def list_batches(request: web.Request) -> web.Response:
    state = request.app["state"]
    if state.batch_queue is None:
        return web.json_response({"error": "batch API not enabled"}, status=501)
    batches = await state.batch_queue.list()
    return web.json_response(
        {"object": "list", "data": [b.to_dict() for b in batches]}
    )


async def cancel_batch(request: web.Request) -> web.Response:
    from production_stack_tpu.router.batch_service import BatchStatus

    state = request.app["state"]
    batch = await state.batch_queue.get(request.match_info["batch_id"])
    if batch is None:
        return web.json_response({"error": "batch not found"}, status=404)
    if batch.status in (BatchStatus.VALIDATING, BatchStatus.IN_PROGRESS):
        batch.status = BatchStatus.CANCELLED
        await state.batch_queue.put(batch)
    return web.json_response(batch.to_dict())


# -- KV controller endpoints (LMCache controller↔worker channel equivalent) --


async def kv_register(request: web.Request) -> web.Response:
    state = request.app["state"]
    body = await request.json()
    result = await state.kv_controller.register_instance(
        body["instance_id"], body["url"],
        generation=body.get("generation"),
        heartbeat_interval=body.get("heartbeat_interval"),
    )
    swept = result.get("swept", 0)
    if swept:
        # A same-URL re-register with a new generation swept the old
        # incarnation's claims (crashed-and-restarted replica).
        metrics_mod.kv_claims_swept.labels(reason="regenerated").inc(swept)
    clear = getattr(state.service_discovery, "clear_lease_expired", None)
    if clear is not None:
        clear(body["url"])
    return web.json_response({"status": "ok", **result})


async def kv_heartbeat(request: web.Request) -> web.Response:
    """Lease renewal. ``known=False`` tells the engine to re-register
    (controller restarted, or the record was superseded); ``revived=True``
    tells it its lease HAD expired and claims were swept, so it should
    resync its admitted state."""
    state = request.app["state"]
    body = await request.json()
    result = await state.kv_controller.heartbeat(
        body["instance_id"],
        generation=body.get("generation"),
        heartbeat_interval=body.get("heartbeat_interval"),
    )
    if result.get("known") and body.get("url"):
        clear = getattr(state.service_discovery, "clear_lease_expired", None)
        if clear is not None:
            clear(body["url"])
    return web.json_response(result)


async def kv_resync(request: web.Request) -> web.Response:
    """Anti-entropy phase 1: compare the engine's claim digest (count +
    xor of root-anchored path keys) against the controller's view. A
    mismatch means timeout-swallowed admit/evict reports drifted the trie;
    the engine follows up with its full state on /kv/resync_state."""
    state = request.app["state"]
    body = await request.json()
    result = await state.kv_controller.resync_check(
        body["instance_id"], int(body.get("count", 0)), int(body.get("xor", 0))
    )
    return web.json_response(result)


async def kv_resync_state(request: web.Request) -> web.Response:
    """Anti-entropy phase 2: replace the instance's claims with the
    engine-reported truth (list of root-anchored chunk-hash paths)."""
    state = request.app["state"]
    body = await request.json()
    result = await state.kv_controller.resync_replace(
        body["instance_id"], body.get("paths") or []
    )
    swept = result.get("swept", 0)
    if swept:
        metrics_mod.kv_claims_swept.labels(reason="resync").inc(swept)
        if state.events is not None:
            state.events.record("kv_resync",
                                instance_id=body.get("instance_id"),
                                swept=swept)
    return web.json_response(result)


async def kv_instances(request: web.Request) -> web.Response:
    """Controller instance table: lease state, generation, claim counts.
    ``expired_urls`` is the health view external pickers (EPP gateway)
    poll to exclude heartbeat-expired endpoints."""
    state = request.app["state"]
    snap = await state.kv_controller.instances_snapshot()
    expired_urls = sorted(
        {rec["url"] for rec in snap
         if rec.get("state") == "expired" and rec.get("url")}
    )
    return web.json_response({"instances": snap, "expired_urls": expired_urls})


async def kv_admit(request: web.Request) -> web.Response:
    state = request.app["state"]
    body = await request.json()
    if "hashes" in body:
        await state.kv_controller.admit(body["instance_id"], body["hashes"])
    else:
        # "salt": LoRA adapter name for adapter-scoped admissions —
        # absent/None for base-model reports (byte-identical keys).
        await state.kv_controller.admit_text(
            body["instance_id"], body["text"], salt=body.get("salt"))
    return web.json_response({"status": "ok"})


async def kv_evict(request: web.Request) -> web.Response:
    state = request.app["state"]
    body = await request.json()
    # "hashes": one root-anchored chunk path; "paths": several (an engine
    # evicting a block shared by multiple admitted prompts). "spilled":
    # the caller CONFIRMED the evicted blocks reached the shared L3, so
    # the claims transfer to the L3 pseudo-instance instead of vanishing
    # (fleet pull path: peer → L3 → recompute). Engines whose offload
    # tier still serves the blocks keep their claims and don't report.
    paths = body.get("paths")
    if paths is None:
        paths = [body.get("hashes", [])]
    spilled = bool(body.get("spilled", False))
    for path in paths:
        await state.kv_controller.evict(body["instance_id"], path,
                                        spilled=spilled)
    return web.json_response({"status": "ok"})


async def kv_deregister(request: web.Request) -> web.Response:
    """An engine announcing departure (drain/shutdown): drop its instance
    registration and sweep every trie claim so no routing decision or
    cross-replica pull targets it again."""
    state = request.app["state"]
    body = await request.json()
    instance_id = body.get("instance_id")
    if instance_id:
        await state.kv_controller.deregister_instance(instance_id)
    elif body.get("url"):
        await state.kv_controller.deregister_url(body["url"])
    else:
        return web.json_response(
            {"error": "instance_id or url required"}, status=400)
    return web.json_response({"status": "ok"})


async def kv_lookup(request: web.Request) -> web.Response:
    state = request.app["state"]
    body = await request.json()
    match = await state.kv_controller.lookup(body.get("text", ""),
                                             salt=body.get("salt"))
    if match is None:
        return web.json_response({"matched": 0, "instance_id": None})
    return web.json_response({"matched": match[0], "instance_id": match[1]})


async def lease_sweep_once(state) -> list:
    """One lease-sweeper pass: expire stale instances, mirror them into
    service discovery's unhealthy view, refresh the instance-state gauge.
    Module-level so tests and the chaos harness can drive it with a fast
    clock instead of waiting out the background task."""
    expired = await state.kv_controller.expire_stale_leases()
    events = getattr(state, "events", None)
    for rec in expired:
        url = rec.get("url")
        mark = getattr(state.service_discovery, "mark_lease_expired", None)
        if url and mark is not None:
            mark(url)
        if rec.get("swept"):
            metrics_mod.kv_claims_swept.labels(reason="expired").inc(
                rec["swept"]
            )
        if events is not None:
            events.record("lease_sweep", endpoint=url,
                          instance_id=rec.get("instance_id"),
                          swept=rec.get("swept", 0))
    snap = await state.kv_controller.instances_snapshot()
    counts: dict = {}
    for rec in snap:
        counts[rec["state"]] = counts.get(rec["state"], 0) + 1
    for state_name in ("live", "expired", "l3"):
        metrics_mod.kv_controller_instances.labels(state=state_name).set(
            counts.get(state_name, 0)
        )
    return expired


# -- autoscale recommender (production_stack_tpu/kv/fleet.py) ---------------


async def autoscale_recommendation(request: web.Request) -> web.Response:
    state = request.app["state"]
    if state.autoscaler is None:
        return web.json_response(
            {"error": "autoscale recommender not enabled "
                      "(--autoscale)"}, status=404)
    endpoints = state.service_discovery.get_endpoint_info()
    rec = state.autoscaler.recommend(
        endpoints, state.engine_stats_scraper.get_engine_stats(),
        qos=state.qos)
    return web.json_response(rec)


async def autoscale_scale_in(request: web.Request) -> web.Response:
    """Data-plane half of scale-in: pick (or accept) a victim replica,
    evict it from the KV controller, then drive its /drain hook. The
    orchestrator (HPA/KEDA + preStop) deletes the pod afterwards."""
    state = request.app["state"]
    if state.autoscaler is None:
        return web.json_response(
            {"error": "autoscale recommender not enabled "
                      "(--autoscale)"}, status=404)
    try:
        body = await request.json()
    except Exception:  # noqa: BLE001 - empty body = auto-pick victim
        body = {}
    url = body.get("url")
    if not url:
        url = state.autoscaler.pick_scale_in_victim(
            state.service_discovery.get_endpoint_info(),
            state.engine_stats_scraper.get_engine_stats(),
            state.request_stats_monitor.get_request_stats())
    if not url:
        return web.json_response(
            {"error": "no replica available to scale in"}, status=409)
    result = await state.autoscaler.scale_in(url)
    if state.events is not None:
        state.events.record("scale_in", endpoint=url,
                            drained=result.get("drained"))
    return web.json_response(result)


# -- LoRA adapter plane (production_stack_tpu/lora/registry.py) -------------


async def lora_debug(request: web.Request) -> web.Response:
    state = request.app["state"]
    if state.lora is None:
        return web.json_response(
            {"error": "LoRA adapter plane not enabled "
                      "(--lora-plane)"}, status=404)
    return web.json_response(state.lora.snapshot())


async def lora_load(request: web.Request) -> web.Response:
    """Fan-out distribution: make an adapter resident on N replicas."""
    state = request.app["state"]
    if state.lora is None:
        return web.json_response(
            {"error": "LoRA adapter plane not enabled "
                      "(--lora-plane)"}, status=404)
    try:
        body = await request.json()
    except Exception:  # noqa: BLE001 - malformed body is a client error
        body = {}
    adapter = body.get("lora_name") or body.get("adapter")
    if not adapter:
        return web.json_response({"error": "lora_name required"}, status=400)
    urls = body.get("urls") or [
        ep.url for ep in state.service_discovery.get_endpoint_info()]
    result = await state.lora.load_adapter(
        adapter, urls, replicas=body.get("replicas"))
    if state.events is not None:
        state.events.record("lora_load", adapter=adapter,
                            loaded=len(result.get("loaded", [])),
                            failed=len(result.get("failed", [])))
    status = 200 if result.get("loaded") else 502
    return web.json_response(result, status=status)


async def lora_unload(request: web.Request) -> web.Response:
    """Fan-out retraction: unload an adapter wherever it is resident."""
    state = request.app["state"]
    if state.lora is None:
        return web.json_response(
            {"error": "LoRA adapter plane not enabled "
                      "(--lora-plane)"}, status=404)
    try:
        body = await request.json()
    except Exception:  # noqa: BLE001 - malformed body is a client error
        body = {}
    adapter = body.get("lora_name") or body.get("adapter")
    if not adapter:
        return web.json_response({"error": "lora_name required"}, status=400)
    urls = body.get("urls") or [
        ep.url for ep in state.service_discovery.get_endpoint_info()]
    result = await state.lora.unload_adapter(adapter, urls)
    if state.events is not None:
        state.events.record("lora_unload", adapter=adapter,
                            unloaded=len(result.get("unloaded", [])))
    return web.json_response(result)


# ---------------------------------------------------------------------------
# Assembly
# ---------------------------------------------------------------------------


def build_app(args) -> web.Application:
    # Edge auth (reference tutorial 11 "secure vLLM serve"): with an API
    # key configured, the inference surface (/v1/* + aliases; see
    # utils/auth.py) requires `Authorization: Bearer <key>`. The header
    # is forwarded to backends, so engines sharing the deployment key
    # verify it too; calls the ROUTER itself originates toward engines
    # (model probes, batch replays) attach the key via
    # deployment_auth_headers().
    from production_stack_tpu.utils import auth

    api_keys = auth.resolve_api_keys(getattr(args, "api_key", None))
    auth.set_deployment_key(api_keys[0] if api_keys else None)

    @web.middleware
    async def auth_middleware(request: web.Request, handler):
        # Privileged control-plane paths (/autoscale/*, /kv/deregister)
        # are gated alongside the inference surface: they can drain or
        # deregister replicas, and engines attach the shared deployment
        # key to the /kv/deregister they send at drain time (an
        # edge-only-key topology loses that report and falls back to
        # the admit TTL + the breaker-open mirror).
        if api_keys and (auth.is_gated(request.path)
                         or auth.is_privileged(request.path)) and \
                not auth.check_bearer(
                    request.headers.get("Authorization"), api_keys):
            return auth.unauthorized_response()
        return await handler(request)

    app = web.Application(client_max_size=1024**3,
                          middlewares=[auth_middleware])
    state = initialize_all(args)
    app["state"] = state

    openai_passthrough = [
        "/v1/chat/completions",
        "/v1/completions",
        "/v1/embeddings",
        "/v1/rerank",
        "/rerank",
        "/v1/score",
        "/score",
        "/tokenize",
        "/detokenize",
    ]
    for ep in openai_passthrough:
        app.router.add_post(ep, _proxy(ep))
    app.router.add_post(
        "/v1/audio/transcriptions", request_service.route_general_transcriptions
    )
    app.router.add_get("/v1/models", show_models)
    app.router.add_get("/models", show_models)
    app.router.add_get("/engines", show_engines)
    from production_stack_tpu.router import workers as workers_mod

    app.router.add_get("/health", health)
    app.router.add_get("/version", version)
    # Multi-worker mode swaps in the aggregated scrape (fan-in over every
    # worker's /debug/snapshot, merged by obs/federation.py); the
    # single-worker handler below stays byte-identical to before.
    if state.worker_count > 1:
        app.router.add_get(
            "/metrics", workers_mod.aggregated_metrics_handler)
    else:
        app.router.add_get("/metrics", metrics_handler)
    app.router.add_get("/dynamic_config", dynamic_config_handler)
    async def _sleep(r):
        return await request_service.route_sleep_wakeup_request(r, "sleep")

    async def _wake(r):
        return await request_service.route_sleep_wakeup_request(r, "wake_up")

    async def _is_sleeping(r):
        return await request_service.route_sleep_wakeup_request(r, "is_sleeping")

    app.router.add_post("/sleep", _sleep)
    app.router.add_post("/wake_up", _wake)
    app.router.add_get("/is_sleeping", _is_sleeping)
    # Files API
    app.router.add_post("/v1/files", upload_file)
    app.router.add_get("/v1/files", list_files)
    app.router.add_get("/v1/files/{file_id}", get_file)
    app.router.add_get("/v1/files/{file_id}/content", get_file_content)
    # Batch API
    app.router.add_post("/v1/batches", create_batch_handler)
    app.router.add_get("/v1/batches", list_batches)
    app.router.add_get("/v1/batches/{batch_id}", get_batch)
    app.router.add_post("/v1/batches/{batch_id}/cancel", cancel_batch)
    # KV controller channel. With the loop monitor on, each handler's
    # on-loop time is attributed to the kv_controller component (trie
    # walks and resync-state replacement are synchronous loop work).
    def _kv(handler):
        if state.loop_monitor is None:
            return handler
        timers = state.loop_monitor.components

        async def timed(request: web.Request) -> web.StreamResponse:
            return await timers.wrap("kv_controller", handler(request))

        return timed

    app.router.add_post("/kv/register", _kv(kv_register))
    app.router.add_post("/kv/admit", _kv(kv_admit))
    app.router.add_post("/kv/evict", _kv(kv_evict))
    app.router.add_post("/kv/lookup", _kv(kv_lookup))
    app.router.add_post("/kv/deregister", _kv(kv_deregister))
    app.router.add_post("/kv/heartbeat", _kv(kv_heartbeat))
    app.router.add_post("/kv/resync", _kv(kv_resync))
    app.router.add_post("/kv/resync_state", _kv(kv_resync_state))
    app.router.add_get("/kv/instances", _kv(kv_instances))
    # Autoscale recommender (404 unless --autoscale)
    app.router.add_get("/autoscale/recommendation", autoscale_recommendation)
    app.router.add_post("/autoscale/scale_in", autoscale_scale_in)
    # LoRA adapter plane (404 unless --lora-plane); all privileged.
    app.router.add_get("/debug/lora", lora_debug)
    app.router.add_post("/lora/load", lora_load)
    app.router.add_post("/lora/unload", lora_unload)
    if state.worker_count > 1:
        # Multi-worker: the list-view debug routes fan in over every
        # worker's /debug/snapshot and serve merged, worker=<id>-stamped
        # views at the same paths with the same filters (plus ?worker=).
        # Registration gating matches the single-worker branch below.
        workers_mod.add_federated_debug_routes(app.router, state)
    else:
        # Flight recorder (router-side spans of every proxied request).
        if state.trace_recorder is not None:
            from production_stack_tpu.obs.debug import add_debug_routes

            add_debug_routes(app.router, state.trace_recorder)
        # Fleet event journal (privileged: /debug/events is in
        # _PRIVILEGED_EXACT, so the auth middleware gates it when a
        # deployment key is configured).
        if state.events is not None:
            from production_stack_tpu.obs.debug import (
                add_event_debug_routes)

            add_event_debug_routes(app.router, state.events)
        # Event-loop health (privileged: /debug/loop is in
        # _PRIVILEGED_EXACT).
        if state.loop_monitor is not None:
            from production_stack_tpu.obs.debug import (
                add_loop_debug_routes)

            add_loop_debug_routes(app.router, state.loop_monitor)
        if state.fleet is not None:
            from production_stack_tpu.obs.debug import (
                add_kv_economics_debug_routes)

            add_kv_economics_debug_routes(app.router, state.fleet)
    # KV trie introspection (privileged via the /debug/kv/ prefix); the
    # pull-economics ledger rides only with --fleet-cache — without it
    # there is no ledger, and authenticated callers see 404, never 401.
    # The trie stays a LOCAL view in every mode: each worker's trie is
    # genuinely different state; /debug/workers reports the divergence.
    from production_stack_tpu.obs.debug import add_kv_trie_debug_routes

    add_kv_trie_debug_routes(app.router, state.kv_controller)
    # Worker federation plane, every mode: /debug/snapshot (this
    # process's telemetry feed) and /debug/workers (topology + shared-
    # state divergence). Both privileged (utils/auth.py).
    workers_mod.add_worker_plane_routes(app.router, state)

    async def on_startup(app: web.Application):
        st = app["state"]
        if st.loop_monitor is not None:
            st.loop_monitor.start()
        if st.relay is not None:
            st.relay.start()
        if st.batch_processor is not None:
            st.batch_processor.start()
        # Canary prober: tiny synthetic completions straight at each
        # healthy replica (--canary-interval > 0; off by default).
        canary_interval = float(getattr(args, "canary_interval", 0.0) or 0.0)
        if canary_interval > 0:
            from production_stack_tpu.router.slo import CanaryProber

            st.canary = CanaryProber(
                st, canary_interval,
                prompt_tokens=getattr(args, "canary_prompt_tokens", 8),
                max_tokens=getattr(args, "canary_max_tokens", 4),
                events=st.events,
            )
            app["_canary"] = asyncio.get_running_loop().create_task(
                st.canary.run()
            )
            logger.info(
                "Canary prober enabled: interval=%.1fs prompt_tokens=%d "
                "max_tokens=%d", canary_interval,
                st.canary.prompt_tokens, st.canary.max_tokens)
        # Lease sweeper: expire instances that missed N heartbeats and
        # mirror them into service discovery so routing + EPP stop
        # picking corpses. Runs at the heartbeat interval (0 disables).
        interval = float(getattr(args, "kv_heartbeat_interval", 10.0) or 0.0)
        if interval > 0:

            async def _sweeper():
                while True:
                    await asyncio.sleep(interval)
                    try:
                        await lease_sweep_once(st)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001
                        logger.debug("lease sweep failed: %s", e)

            app["_lease_sweeper"] = asyncio.get_running_loop().create_task(
                _sweeper()
            )
        # Crossover advisor applier: with --fleet-auto-min-match, nudge
        # the live min-match threshold toward the ledger's measured
        # break-even on a damped interval. Flag off = no task, and
        # min_match_chars is never written after init (parity).
        if st.fleet is not None and st.fleet.config.auto_min_match:
            apply_interval = st.fleet.config.auto_min_match_interval_s

            async def _auto_min_match():
                while True:
                    await asyncio.sleep(apply_interval)
                    try:
                        st.fleet.apply_auto_min_match()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001
                        logger.debug("auto-min-match step failed: %s", e)

            app["_auto_min_match"] = \
                asyncio.get_running_loop().create_task(_auto_min_match())
            logger.info(
                "Fleet auto-min-match enabled: interval=%.1fs damping=%.2f",
                apply_interval, st.fleet.config.auto_min_match_damping)
        # Adapter residency scraper: with --lora-plane, refresh each
        # replica's resident-adapter view (and the service-discovery
        # mirror) on the configured interval. Flag off = no task.
        if st.lora is not None:
            app["_lora_scraper"] = asyncio.get_running_loop().create_task(
                st.lora.scrape_loop())
            logger.info(
                "LoRA adapter plane enabled: scrape_interval=%.1fs "
                "load_timeout=%.1fs", st.lora.config.scrape_interval_s,
                st.lora.config.load_timeout_s)

    async def on_cleanup(app: web.Application):
        from production_stack_tpu.router.httpclient import AiohttpClientWrapper

        for task_key in ("_lease_sweeper", "_canary", "_auto_min_match",
                         "_lora_scraper"):
            task = app.get(task_key)
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        st = app["state"]
        if st.relay is not None:
            st.relay.stop()
        if st.loop_monitor is not None:
            st.loop_monitor.stop()
        for closable in (
            st.service_discovery, st.engine_stats_scraper,
            st.dynamic_config_watcher, st.batch_processor,
        ):
            if closable is not None and hasattr(closable, "close"):
                result = closable.close()
                if asyncio.iscoroutine(result):
                    await result
        if st.trace_recorder is not None:
            st.trace_recorder.close()
        await AiohttpClientWrapper().close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def _init_sentry(args) -> None:
    """Error reporting/profiling (reference app.py:123-130). sentry-sdk is
    an optional dependency of the serving image; a DSN without the SDK
    warns instead of crashing the router."""
    if not getattr(args, "sentry_dsn", None):
        return
    try:
        import sentry_sdk
    except ImportError:
        logger.warning(
            "--sentry-dsn was given but sentry-sdk is not installed; "
            "error reporting disabled")
        return
    try:
        sentry_sdk.init(
            dsn=args.sentry_dsn,
            send_default_pii=True,
            profile_lifecycle="trace",
            traces_sample_rate=args.sentry_traces_sample_rate,
            profile_session_sample_rate=args.sentry_profile_session_sample_rate,
        )
    except TypeError:
        # Older SDKs (< 2.24) reject the profiling options; error
        # reporting still beats crashing the router at startup.
        sentry_sdk.init(
            dsn=args.sentry_dsn,
            send_default_pii=True,
            traces_sample_rate=args.sentry_traces_sample_rate,
        )
    logger.info("Sentry initialized")


def initialize_all(args) -> RouterState:
    """Wire all singletons (reference app.py:112-272)."""
    state = RouterState()
    _init_sentry(args)

    # Multi-worker identity (--router-workers; router/workers.py sets the
    # private attrs before build_app in each forked process). Defaults
    # reproduce the single-process router exactly.
    state.worker_id = int(getattr(args, "_worker_id", 0) or 0)
    state.worker_count = int(getattr(args, "router_workers", 1) or 1)
    state.worker_uds = tuple(getattr(args, "_worker_uds", ()) or ())
    state.worker_port = int(getattr(args, "port", 0) or 0)

    # Tracing flight recorder (always on: a bounded ring buffer is cheap;
    # export + slow-trace logging are opt-in flags).
    from production_stack_tpu.obs.trace import TraceRecorder

    state.trace_recorder = TraceRecorder(
        "tpu-stack-router",
        capacity=getattr(args, "trace_buffer", 512),
        slow_threshold_s=getattr(args, "slow_trace_threshold_s", 0.0),
        export=getattr(args, "trace_export", None)
        or getattr(args, "otel_endpoint", None),
        sample_rate=getattr(args, "trace_sample_rate", 1.0),
        slow_log_interval_s=getattr(
            args, "slow_trace_log_interval_s", 0.0),
    )

    # Fleet event journal (always on, like the trace recorder: a bounded
    # ring of small dicts; served at the privileged /debug/events).
    from production_stack_tpu.obs.events import EventJournal

    state.events = EventJournal("tpu-stack-router")

    # SLO engine: outcome classifier + goodput window, only when an
    # objectives file is configured — without one state.slo is None and
    # the request path carries no classification code at all.
    if getattr(args, "slo_config", None):
        from production_stack_tpu.router.slo import SLOEngine

        state.slo = SLOEngine.from_file(args.slo_config)
        logger.info(
            "SLO engine enabled: default=%s tenants=%s models=%s",
            state.slo.default, sorted(state.slo.tenants),
            sorted(state.slo.models))

    # Event-loop introspection: lag monitor + blocking-call watchdog +
    # per-component on-loop attribution, only behind --loop-monitor —
    # without it state.loop_monitor is None and the hot path carries no
    # instrumentation code at all.
    if getattr(args, "loop_monitor", False):
        from production_stack_tpu.obs.looplag import LoopMonitor

        threshold_ms = float(
            getattr(args, "loop_stall_threshold_ms", 100.0) or 100.0)
        state.loop_monitor = LoopMonitor(
            "tpu-stack-router",
            stall_threshold_s=threshold_ms / 1000.0,
        )
        logger.info(
            "Event-loop monitor enabled: stall_threshold=%.0fms "
            "tick=%.0fms watchdog_poll=%.0fms", threshold_ms,
            state.loop_monitor.interval_s * 1000.0,
            state.loop_monitor.detector.poll_s * 1000.0)

    # Relay pump tier: committed streamed responses copied to the
    # client socket by pump threads instead of await response.write()
    # (--relay-off-loop; router/relay.py). Flag off = state.relay is
    # None and the streaming path is byte-identical.
    if getattr(args, "relay_off_loop", False):
        from production_stack_tpu.router.relay import RelayPump

        state.relay = RelayPump(
            threads=int(getattr(args, "relay_pump_threads", 2) or 2),
            name=f"w{state.worker_id}",
        )
        logger.info("Relay pump tier enabled: pump_threads=%d",
                    state.relay.thread_count)

    # Service discovery.
    if args.service_discovery == "static":
        state.service_discovery = initialize_service_discovery(
            ServiceDiscoveryType.STATIC,
            urls=parse_static_urls(args.static_backends or ""),
            models=parse_comma_separated_args(args.static_models) or [],
            aliases=parse_static_aliases(args.static_aliases or ""),
            model_labels=parse_comma_separated_args(args.static_model_labels),
            model_types=parse_static_model_types(args.static_model_types)
            if args.static_model_types else None,
            static_backend_health_checks=bool(
                getattr(args, "static_backend_health_checks", False)
            ),
            prefill_model_labels=parse_comma_separated_args(
                args.prefill_model_labels
            ),
            decode_model_labels=parse_comma_separated_args(
                args.decode_model_labels
            ),
        )
    else:
        sd_type = (
            ServiceDiscoveryType.K8S_SERVICE_NAME
            if args.service_discovery == "k8s_service_name"
            else ServiceDiscoveryType.K8S_POD_IP
        )
        state.service_discovery = initialize_service_discovery(
            sd_type,
            namespace=args.k8s_namespace,
            port=args.k8s_port,
            label_selector=args.k8s_label_selector,
            prefill_model_labels=parse_comma_separated_args(
                args.prefill_model_labels
            ),
            decode_model_labels=parse_comma_separated_args(
                args.decode_model_labels
            ),
        )

    # Stats.
    state.engine_stats_scraper = initialize_engine_stats_scraper(
        args.engine_stats_interval
    )
    state.request_stats_monitor = initialize_request_stats_monitor(
        args.request_stats_window
    )

    # KV controller (in-process, as the reference embeds LMCache's).
    from production_stack_tpu.kv.controller import initialize_kv_controller

    state.kv_controller = initialize_kv_controller(
        admit_ttl=getattr(args, "kv_admit_ttl", 600.0),
        lease_misses=getattr(args, "kv_lease_misses", 3),
        heartbeat_interval=getattr(args, "kv_heartbeat_interval", 10.0),
    )

    # Routing.
    state.router = initialize_routing_logic(
        args.routing_logic,
        session_key=args.session_key,
        kv_aware_threshold=args.kv_aware_threshold,
        kv_controller=state.kv_controller,
        prefill_model_labels=parse_comma_separated_args(args.prefill_model_labels),
        decode_model_labels=parse_comma_separated_args(args.decode_model_labels),
    )

    # Rewriter / callbacks.
    from production_stack_tpu.router.rewriter import get_request_rewriter

    state.request_rewriter = get_request_rewriter(
        getattr(args, "request_rewriter", "noop")
    )
    if getattr(args, "callbacks", None):
        from production_stack_tpu.router.callbacks import configure_custom_callbacks

        state.callbacks = configure_custom_callbacks(args.callbacks)

    # Feature gates + experimental features.
    from production_stack_tpu.router.feature_gates import initialize_feature_gates

    state.feature_gates = initialize_feature_gates(
        getattr(args, "feature_gates", "")
    )
    if state.feature_gates.is_enabled("SemanticCache"):
        from production_stack_tpu.experimental.semantic_cache import SemanticCache

        state.semantic_cache = SemanticCache(
            model_name=args.semantic_cache_model,
            cache_dir=args.semantic_cache_dir,
            threshold=args.semantic_cache_threshold,
        )
    if state.feature_gates.is_enabled("PIIDetection"):
        from production_stack_tpu.experimental.pii import PIIDetector

        state.pii_detector = PIIDetector()

    # Files + batch API.
    if getattr(args, "enable_batch_api", False):
        from production_stack_tpu.router.batch_service import (
            BatchQueue,
            LocalBatchProcessor,
        )
        from production_stack_tpu.router.files_service import initialize_storage

        state.file_storage = initialize_storage(
            args.file_storage_class, args.file_storage_path
        )
        state.batch_queue = BatchQueue(
            db_path=f"{args.file_storage_path}/batches.db"
        )
        state.batch_processor = LocalBatchProcessor(
            state.file_storage, state.batch_queue, state
        )
    else:
        from production_stack_tpu.router.files_service import initialize_storage

        state.file_storage = initialize_storage(
            "local_file", getattr(args, "file_storage_path", "/tmp/tpu_stack_files")
        )

    # Multi-tenant QoS gate (production_stack_tpu/qos/): built only when a
    # tenants file is configured — without one the request path carries no
    # QoS code at all.
    if getattr(args, "qos_tenants_file", None):
        from production_stack_tpu.qos import QoSGate

        state.qos = QoSGate(
            args.qos_tenants_file,
            max_concurrency=getattr(args, "qos_max_concurrency", None),
            shed_queue_depth=getattr(args, "qos_shed_queue_depth", None),
            reload_interval_s=getattr(args, "qos_reload_interval", 2.0),
        )
        logger.info("QoS gate enabled: tenants=%s max_concurrency=%d "
                    "shed_queue_depth=%d", state.qos.registry.names(),
                    state.qos.queue.max_concurrency,
                    state.qos.queue.shed_queue_depth)

    # Fault-tolerance layer (production_stack_tpu/router/fault_tolerance):
    # circuit breaker + retry/failover + streaming deadlines. Off by
    # default — the request path is then byte-identical to the
    # single-attempt router.
    from production_stack_tpu.router.fault_tolerance import (
        initialize_fault_tolerance,
    )

    state.fault_tolerance = initialize_fault_tolerance(
        args, service_discovery=state.service_discovery)
    if state.fault_tolerance is not None:
        cfg = state.fault_tolerance.config
        logger.info(
            "Fault tolerance enabled: max_retries=%d breaker_threshold=%d "
            "breaker_reset=%.0fs ttft_deadline=%.0fs "
            "inter_chunk_deadline=%.0fs", cfg.max_retries,
            cfg.breaker_failure_threshold, cfg.breaker_reset_s,
            cfg.ttft_deadline_s, cfg.inter_chunk_deadline_s)
        # Breaker-open mirror into the KV controller: a tripped endpoint
        # must stop being a pull source / kvaware routing target right
        # away — re-registration on recovery repopulates it.
        kv_controller = state.kv_controller
        events = state.events

        def _on_breaker_open(url: str) -> None:
            if events is not None:
                events.record("breaker_open", endpoint=url)
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:  # tripped off-loop (tests, threads)
                return
            loop.create_task(kv_controller.deregister_url(url))

        state.fault_tolerance.breaker.on_open = _on_breaker_open

    # Fleet cache + autoscale recommender (production_stack_tpu/kv/fleet):
    # both None unless their flags are set — the request path is then
    # byte-identical to the per-replica router.
    from production_stack_tpu.kv.fleet import initialize_fleet

    state.fleet, state.autoscaler = initialize_fleet(
        args, state.kv_controller, fault_tolerance=state.fault_tolerance)
    if state.fleet is not None:
        if state.fleet.config.l3_url:
            state.kv_controller.attach_l3(state.fleet.config.l3_url)
        logger.info(
            "Fleet cache enabled: min_match_chars=%d pull_timeout=%.1fs "
            "l3=%s", state.fleet.config.min_match_chars,
            state.fleet.config.pull_timeout_s,
            state.fleet.config.l3_url or "none")
    if state.autoscaler is not None:
        logger.info(
            "Autoscale recommender enabled: replicas=[%d, %d] "
            "queue_depth_target=%.1f",
            state.autoscaler.config.min_replicas,
            state.autoscaler.config.max_replicas,
            state.autoscaler.config.queue_depth_target)

    # LoRA adapter plane (production_stack_tpu/lora/registry.py): None
    # unless --lora-plane — adapter-free deployments keep the request
    # path byte-identical.
    from production_stack_tpu.lora.registry import initialize_lora_plane

    state.lora = initialize_lora_plane(
        args, service_discovery=state.service_discovery,
        fault_tolerance=state.fault_tolerance)

    # Dynamic config watcher.
    if getattr(args, "dynamic_config_json", None):
        from production_stack_tpu.router.dynamic_config import (
            initialize_dynamic_config_watcher,
        )

        state.dynamic_config_watcher = initialize_dynamic_config_watcher(
            args.dynamic_config_json, state,
            poll_interval=getattr(args, "dynamic_config_interval", 10.0)
        )

    # Periodic stats logger (reference stats/log_stats.py:37-115, app.py:287-295).
    if getattr(args, "log_stats", False):
        state.log_stats_thread = _start_log_stats_thread(
            state, getattr(args, "log_stats_interval", 10.0)
        )
    return state


def _start_log_stats_thread(state: RouterState, interval: float) -> threading.Thread:
    def loop():
        while True:
            time.sleep(interval)
            try:
                endpoints = state.service_discovery.get_endpoint_info()
                engine_stats = state.engine_stats_scraper.get_engine_stats()
                request_stats = state.request_stats_monitor.get_request_stats()
                metrics_mod.update_gauges(
                    endpoints, engine_stats, request_stats,
                    fault_tolerance=state.fault_tolerance)
                lines = ["", "==== Router stats ===="]
                for ep in endpoints:
                    rs = request_stats.get(ep.url)
                    es = engine_stats.get(ep.url)
                    lines.append(
                        f"{ep.url}: qps={getattr(rs, 'qps', 0):.2f} "
                        f"ttft={getattr(rs, 'ttft', -1):.3f} "
                        f"running={getattr(es, 'num_running_requests', 0)} "
                        f"waiting={getattr(es, 'num_queuing_requests', 0)} "
                        f"kv_usage={getattr(es, 'gpu_cache_usage_perc', 0):.2%}"
                    )
                lines.append("=" * 22)
                logger.info("\n".join(lines))
            except Exception as e:  # noqa: BLE001
                logger.debug("log_stats iteration failed: %s", e)

    t = threading.Thread(target=loop, daemon=True, name="log-stats")
    t.start()
    return t


def main(argv=None) -> None:
    args = parse_args(argv)
    import logging

    logging.getLogger().setLevel(args.log_level.upper())
    set_ulimit()
    workers = int(getattr(args, "router_workers", 1) or 1)
    if workers > 1:
        # Pre-fork BEFORE build_app: initialize_all starts scraper
        # threads and asyncio machinery that must not cross a fork.
        from production_stack_tpu.router.workers import run_multi_worker

        logger.info("Router pre-forking %d workers on %s:%d "
                    "(SO_REUSEPORT)", workers, args.host, args.port)
        run_multi_worker(args)
        return
    app = build_app(args)
    logger.info("Router listening on %s:%d", args.host, args.port)
    web.run_app(app, host=args.host, port=args.port, access_log=None)


if __name__ == "__main__":
    main()
