"""OpenAI Batch API: SQLite-backed queue + background processor.

Rebuild of reference ``src/vllm_router/services/batch_service/``
(``batch.py:19-104``, ``local_processor.py``). The reference's processor is a
stub that writes a result file without real inference; ours actually executes
each batch line against the routed engines (chat/completions/embeddings) and
writes an OpenAI-format output file, which is strictly more capable.

SQLite access runs in a worker thread (``aiosqlite`` is not in this image).
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from production_stack_tpu.router.files_service import Storage
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class BatchStatus:
    VALIDATING = "validating"
    IN_PROGRESS = "in_progress"
    COMPLETED = "completed"
    FAILED = "failed"
    CANCELLED = "cancelled"


@dataclass
class BatchInfo:
    id: str
    input_file_id: str
    endpoint: str
    completion_window: str = "24h"
    status: str = BatchStatus.VALIDATING
    created_at: int = field(default_factory=lambda: int(time.time()))
    completed_at: Optional[int] = None
    output_file_id: Optional[str] = None
    error_file_id: Optional[str] = None
    metadata: Optional[dict] = None
    request_counts: dict = field(default_factory=lambda: {"total": 0, "completed": 0, "failed": 0})

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "object": "batch",
            "endpoint": self.endpoint,
            "input_file_id": self.input_file_id,
            "completion_window": self.completion_window,
            "status": self.status,
            "created_at": self.created_at,
            "completed_at": self.completed_at,
            "output_file_id": self.output_file_id,
            "error_file_id": self.error_file_id,
            "metadata": self.metadata,
            "request_counts": self.request_counts,
        }


class BatchQueue:
    """Durable batch queue on SQLite (reference local_processor.py:35-66)."""

    def __init__(self, db_path: str = "/tmp/tpu_stack_batches.db"):
        self.db_path = db_path
        self._conn = sqlite3.connect(db_path, check_same_thread=False)
        self._lock = asyncio.Lock()
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS batches ("
            "id TEXT PRIMARY KEY, data TEXT NOT NULL)"
        )
        self._conn.commit()

    async def put(self, batch: BatchInfo) -> None:
        async with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO batches (id, data) VALUES (?, ?)",
                (batch.id, json.dumps(batch.to_dict())),
            )
            self._conn.commit()

    async def get(self, batch_id: str) -> Optional[BatchInfo]:
        async with self._lock:
            row = self._conn.execute(
                "SELECT data FROM batches WHERE id = ?", (batch_id,)
            ).fetchone()
        if row is None:
            return None
        return _batch_from_dict(json.loads(row[0]))

    async def list(self) -> "list[BatchInfo]":
        async with self._lock:
            rows = self._conn.execute("SELECT data FROM batches").fetchall()
        return [_batch_from_dict(json.loads(r[0])) for r in rows]

    async def pending(self) -> "list[BatchInfo]":
        return [
            b for b in await self.list()
            if b.status in (BatchStatus.VALIDATING, BatchStatus.IN_PROGRESS)
        ]


def _batch_from_dict(d: dict) -> BatchInfo:
    return BatchInfo(
        id=d["id"],
        input_file_id=d["input_file_id"],
        endpoint=d["endpoint"],
        completion_window=d.get("completion_window", "24h"),
        status=d.get("status", BatchStatus.VALIDATING),
        created_at=d.get("created_at", 0),
        completed_at=d.get("completed_at"),
        output_file_id=d.get("output_file_id"),
        error_file_id=d.get("error_file_id"),
        metadata=d.get("metadata"),
        request_counts=d.get("request_counts") or {"total": 0, "completed": 0, "failed": 0},
    )


class LocalBatchProcessor:
    """Background task that executes queued batches against the engines
    (reference LocalBatchProcessor.process_batches:170-221, but with real
    inference via the router's own routing + HTTP client)."""

    def __init__(self, storage: Storage, queue: BatchQueue, state, poll_interval: float = 2.0):
        self.storage = storage
        self.queue = queue
        self.state = state
        self.poll_interval = poll_interval
        self._task: Optional[asyncio.Task] = None
        self._running = True

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while self._running:
            try:
                for batch in await self.queue.pending():
                    await self._process_one(batch)
            except Exception as e:  # noqa: BLE001
                logger.error("Batch processor error: %s", e)
            await asyncio.sleep(self.poll_interval)

    async def _process_one(self, batch: BatchInfo) -> None:
        from production_stack_tpu.router.httpclient import get_client_session

        batch.status = BatchStatus.IN_PROGRESS
        await self.queue.put(batch)
        try:
            content = await self.storage.get_file_content(batch.input_file_id)
        except FileNotFoundError:
            batch.status = BatchStatus.FAILED
            await self.queue.put(batch)
            return
        lines = [ln for ln in content.decode().splitlines() if ln.strip()]
        batch.request_counts["total"] = len(lines)
        results, errors = [], []
        session = get_client_session()
        for line in lines:
            try:
                item = json.loads(line)
                body = item.get("body", {})
                endpoints = [
                    ep for ep in self.state.service_discovery.get_endpoint_info()
                    if ep.serves(body.get("model", "")) and not ep.sleep
                ]
                if not endpoints:
                    raise RuntimeError(f"no engine for model {body.get('model')}")
                url = self.state.router.route_request(
                    endpoints, None, None, {}, body
                )
                if asyncio.iscoroutine(url):
                    url = await url
                # Batch replays run long after the submitting client is
                # gone: authenticate with the deployment key (the
                # engines gate /v1/* when a key is configured).
                from production_stack_tpu.utils.auth import (
                    deployment_auth_headers,
                )

                async with session.post(
                    f"{url}{item.get('url', batch.endpoint)}", json=body,
                    headers=deployment_auth_headers(),
                ) as resp:
                    resp_body = await resp.json()
                    results.append({
                        "id": f"batch_req_{uuid.uuid4().hex[:12]}",
                        "custom_id": item.get("custom_id"),
                        "response": {"status_code": resp.status, "body": resp_body},
                        "error": None,
                    })
                    batch.request_counts["completed"] += 1
            except Exception as e:  # noqa: BLE001
                errors.append({"custom_id": item.get("custom_id") if "item" in dir() else None,
                               "error": str(e)})
                batch.request_counts["failed"] += 1
        out = "\n".join(json.dumps(r) for r in results)
        info = await self.storage.save_file(
            f"{batch.id}_output.jsonl", out.encode(), purpose="batch_output"
        )
        batch.output_file_id = info.id
        if errors:
            err_info = await self.storage.save_file(
                f"{batch.id}_errors.jsonl",
                "\n".join(json.dumps(e) for e in errors).encode(),
                purpose="batch_output",
            )
            batch.error_file_id = err_info.id
        batch.status = BatchStatus.COMPLETED
        batch.completed_at = int(time.time())
        await self.queue.put(batch)
        logger.info("Batch %s completed: %s", batch.id, batch.request_counts)

    def close(self) -> None:
        self._running = False
        if self._task:
            self._task.cancel()


async def create_batch(
    queue: BatchQueue, input_file_id: str, endpoint: str,
    completion_window: str = "24h", metadata: Optional[dict] = None,
) -> BatchInfo:
    batch = BatchInfo(
        id=f"batch_{uuid.uuid4().hex}",
        input_file_id=input_file_id,
        endpoint=endpoint,
        completion_window=completion_window,
        metadata=metadata,
    )
    await queue.put(batch)
    return batch
