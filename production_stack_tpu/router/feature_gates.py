"""Feature gate registry (reference src/vllm_router/experimental/feature_gates.py:48-109)."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_global_feature_gates: Optional["FeatureGates"] = None


class FeatureStage(enum.Enum):
    ALPHA = "Alpha"
    BETA = "Beta"
    GA = "GA"


@dataclass
class Feature:
    name: str
    default: bool
    stage: FeatureStage
    description: str = ""


KNOWN_FEATURES = {
    "SemanticCache": Feature("SemanticCache", False, FeatureStage.ALPHA,
                             "Serve chat completions from a semantic cache"),
    "PIIDetection": Feature("PIIDetection", False, FeatureStage.ALPHA,
                            "Block requests containing detected PII"),
    "KVOffload": Feature("KVOffload", False, FeatureStage.BETA,
                         "Engine-side HBM->host KV offload"),
}


class FeatureGates:
    def __init__(self, gates: Dict[str, bool]):
        self.gates = dict(gates)

    def is_enabled(self, name: str) -> bool:
        if name in self.gates:
            return self.gates[name]
        feature = KNOWN_FEATURES.get(name)
        return feature.default if feature else False


def parse_feature_gates(spec: str) -> Dict[str, bool]:
    gates: Dict[str, bool] = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"Invalid feature gate {item!r}, expected Name=bool")
        name, value = item.split("=", 1)
        name = name.strip()
        if name not in KNOWN_FEATURES:
            raise ValueError(
                f"Unknown feature gate {name!r}; known: {sorted(KNOWN_FEATURES)}"
            )
        gates[name] = value.strip().lower() in ("true", "1", "yes")
    return gates


def initialize_feature_gates(spec: str) -> "FeatureGates":
    global _global_feature_gates
    _global_feature_gates = FeatureGates(parse_feature_gates(spec))
    for name, enabled in _global_feature_gates.gates.items():
        stage = KNOWN_FEATURES[name].stage.value
        logger.info("Feature gate %s=%s (%s)", name, enabled, stage)
    return _global_feature_gates


def get_feature_gates() -> "FeatureGates":
    global _global_feature_gates
    if _global_feature_gates is None:
        _global_feature_gates = FeatureGates({})
    return _global_feature_gates
