"""QoSGate: the router-side admission controller.

One gate per router process, constructed only when `--qos-tenants-file`
is set (no tenants file -> no gate -> the request path is untouched).
The gate owns the tenant registry snapshot, per-tenant token buckets,
and the weighted-fair dispatch queue, and knows how to hot-reload the
tenants file (driven by the dynamic-config watcher's poll loop, or
lazily from the admission path as a fallback).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional, Tuple

from .fair_queue import (PRIORITY_CLASS_NUM, FairDispatchQueue, QueueLease,
                         priority_class)
from .tenants import TenantRegistry, TenantSpec
from .token_bucket import TokenBucket
from .usage import actual_tokens

logger = logging.getLogger("uvicorn")

# Fallback completion-token estimate when the request carries no
# max_tokens: matches the OpenAI-API default of "short".
_DEFAULT_COMPLETION_TOKENS = 64
_CHARS_PER_TOKEN = 4


def estimate_token_parts(request_json: dict) -> Tuple[int, int]:
    """(prompt_estimate, completion_estimate) for tokens/s accounting.

    ~4 chars/token on the prompt side (no tokenizer on the router), plus
    the requested max_tokens.  Deliberately rough: buckets only need the
    estimate to scale with request size, not to match the engine's count.
    Split in two so post-completion reconciliation can compare a
    completion-only measurement (SSE chunk count) against the same
    prompt-side estimate admission charged.
    """
    chars = 0
    msgs = request_json.get("messages")
    if isinstance(msgs, list):
        for m in msgs:
            content = m.get("content") if isinstance(m, dict) else m
            if isinstance(content, list):  # multimodal parts
                for part in content:
                    chars += len(str(part.get("text", "")) if isinstance(part, dict) else str(part))
            elif content is not None:
                chars += len(str(content))
    prompt = request_json.get("prompt")
    if isinstance(prompt, str):
        chars += len(prompt)
    elif isinstance(prompt, list):
        for p in prompt:
            chars += len(p) if isinstance(p, (str, list)) else 1
    prompt_tokens = chars // _CHARS_PER_TOKEN + 1
    max_tokens = request_json.get("max_tokens",
                                  request_json.get("max_completion_tokens"))
    if not isinstance(max_tokens, (int, float)) or max_tokens <= 0:
        max_tokens = _DEFAULT_COMPLETION_TOKENS
    return int(prompt_tokens), int(max_tokens)


def estimate_tokens(request_json: dict) -> int:
    """Cheap prompt+completion token estimate (see estimate_token_parts)."""
    prompt_tokens, completion_tokens = estimate_token_parts(request_json)
    return prompt_tokens + completion_tokens


class AdmitResult:
    """Token-bucket verdict plus the x-ratelimit-* header set."""

    __slots__ = ("admitted", "reason", "retry_after", "headers")

    def __init__(self, admitted: bool, reason: str = "",
                 retry_after: float = 0.0, headers: Optional[dict] = None):
        self.admitted = admitted
        self.reason = reason  # "" | "requests" | "tokens"
        self.retry_after = retry_after
        self.headers = headers or {}


class _TenantState:
    __slots__ = ("spec", "req_bucket", "tok_bucket")

    def __init__(self, spec: TenantSpec):
        self.spec = spec
        self.req_bucket = TokenBucket(
            spec.requests_per_second,
            spec.requests_per_second * spec.burst_seconds)
        self.tok_bucket = TokenBucket(
            spec.tokens_per_second,
            spec.tokens_per_second * spec.burst_seconds)


def _fmt_remaining(value: float) -> str:
    return "unlimited" if value == float("inf") else str(int(value))


class QoSGate:
    def __init__(self, tenants_file: str,
                 max_concurrency: Optional[int] = None,
                 shed_queue_depth: Optional[int] = None,
                 reload_interval_s: float = 2.0):
        self.tenants_file = tenants_file
        self._max_concurrency_override = max_concurrency
        self._shed_depth_override = shed_queue_depth
        self.reload_interval_s = reload_interval_s
        self._mtime: float = -1.0
        self._last_check = 0.0
        self.registry: TenantRegistry = TenantRegistry([])
        self._states: Dict[str, _TenantState] = {}
        self.queue = FairDispatchQueue()
        self._load(initial=True)

    # -- config reload ----------------------------------------------------
    def _load(self, initial: bool = False) -> None:
        registry = TenantRegistry.from_file(self.tenants_file)
        self.registry = registry
        # Rebuild bucket state only for tenants whose spec changed, so a
        # reload does not hand every tenant a fresh (full) bucket.
        states: Dict[str, _TenantState] = {}
        for spec in registry.tenants + [registry.default_tenant]:
            prev = self._states.get(spec.name)
            states[spec.name] = prev if prev and prev.spec == spec \
                else _TenantState(spec)
        self._states = states
        max_conc = self._max_concurrency_override or registry.max_concurrency
        shed = self._shed_depth_override if self._shed_depth_override is not None \
            else registry.shed_queue_depth
        self.queue.max_concurrency = max(int(max_conc), 1)
        self.queue.shed_queue_depth = max(int(shed), 0)
        try:
            self._mtime = os.stat(self.tenants_file).st_mtime
        except OSError:
            self._mtime = -1.0
        if not initial:
            logger.info("QoS tenants reloaded from %s: %s",
                        self.tenants_file, self.registry.names())

    def maybe_reload(self, force: bool = False) -> bool:
        """mtime-based hot reload; returns True when a reload happened."""
        now = time.monotonic()
        if not force and now - self._last_check < self.reload_interval_s:
            return False
        self._last_check = now
        try:
            mtime = os.stat(self.tenants_file).st_mtime
        except OSError:
            return False
        if mtime == self._mtime:
            return False
        try:
            self._load()
            return True
        except Exception as e:  # noqa: BLE001 -- any parse/validation error
            # Broad on purpose: a torn or hostile tenants file can raise
            # far more than json.JSONDecodeError (yaml.YAMLError,
            # TypeError on odd shapes, RecursionError on nesting bombs).
            # Whatever the failure, the admission path must keep serving
            # with the last-good registry — never fail open to a
            # zero-tenant default.
            logger.error("QoS tenants reload failed (%s); keeping previous "
                         "config: %s", self.tenants_file, e)
            self._mtime = mtime  # don't re-log every poll
            return False

    # -- admission --------------------------------------------------------
    def resolve(self, authorization: Optional[str]) -> TenantSpec:
        return self.registry.resolve(authorization)

    def request_priority(self, spec: TenantSpec,
                         header_value: Optional[str]) -> str:
        """Per-request class: X-Priority may downgrade the tenant default.

        An upgrade (batch tenant requesting interactive — a lower class
        number) is ignored unless the tenant is configured with
        `allow_priority_upgrade`; honoring it unconditionally would let a
        noisy batch tenant stamp every request interactive and bypass the
        shedding / slot-yielding / preemption ordering this gate exists
        to enforce.
        """
        requested = priority_class(header_value, default=spec.priority)
        if (PRIORITY_CLASS_NUM[requested] < PRIORITY_CLASS_NUM[spec.priority]
                and not spec.allow_priority_upgrade):
            return spec.priority
        return requested

    def _state(self, spec: TenantSpec) -> _TenantState:
        st = self._states.get(spec.name)
        if st is None or st.spec != spec:
            st = self._states[spec.name] = _TenantState(spec)
        return st

    def admit(self, spec: TenantSpec, request_json: dict) -> AdmitResult:
        st = self._state(spec)
        est = estimate_tokens(request_json)
        headers = {
            "x-ratelimit-limit-requests": _fmt_remaining(
                spec.requests_per_second if spec.requests_per_second > 0
                else float("inf")),
            "x-ratelimit-limit-tokens": _fmt_remaining(
                spec.tokens_per_second if spec.tokens_per_second > 0
                else float("inf")),
        }
        ok_req, retry_req = st.req_bucket.try_acquire(1.0)
        if not ok_req:
            headers["x-ratelimit-remaining-requests"] = "0"
            headers["x-ratelimit-remaining-tokens"] = _fmt_remaining(
                st.tok_bucket.remaining())
            headers["x-ratelimit-reset-requests"] = f"{retry_req:.3f}s"
            return AdmitResult(False, "requests", retry_req, headers)
        ok_tok, retry_tok = st.tok_bucket.try_acquire(float(est))
        if not ok_tok:
            # Refund the request-bucket token the failed attempt consumed.
            st.req_bucket._tokens = min(st.req_bucket.burst,
                                        st.req_bucket._tokens + 1.0)
            headers["x-ratelimit-remaining-requests"] = _fmt_remaining(
                st.req_bucket.remaining())
            headers["x-ratelimit-remaining-tokens"] = "0"
            headers["x-ratelimit-reset-tokens"] = f"{retry_tok:.3f}s"
            return AdmitResult(False, "tokens", retry_tok, headers)
        headers["x-ratelimit-remaining-requests"] = _fmt_remaining(
            st.req_bucket.remaining())
        headers["x-ratelimit-remaining-tokens"] = _fmt_remaining(
            st.tok_bucket.remaining())
        return AdmitResult(True, "", 0.0, headers)

    def reconcile(self, spec: TenantSpec, request_json: dict,
                  response_body: bytes) -> float:
        """Debit the tenant bucket with actual streamed usage.

        Admission charged an estimate the *client* controls (prompt
        chars + claimed max_tokens); a tenant understating max_tokens
        while streaming long completions would otherwise get the
        overage for free, every request.  After the response finishes
        (or the client disconnects mid-stream — partial output was
        still generated), measure what actually streamed and debit the
        positive overage.  Returns the extra tokens debited (0.0 when
        usage was at or under the estimate, or unmeasurable).

        Only overage is charged — honest over-estimates are not
        refunded, so padding max_tokens cannot be used to bank tokens.
        """
        measured = actual_tokens(response_body)
        if measured is None:
            return 0.0
        tokens, scope = measured
        prompt_est, completion_est = estimate_token_parts(request_json)
        if scope == "completion":
            # Chunk-count fallback covers the completion side only; add
            # the same prompt estimate admission charged.
            tokens += prompt_est
        extra = float(tokens - (prompt_est + completion_est))
        if extra <= 0:
            return 0.0
        st = self._state(spec)
        if st.tok_bucket.unlimited:
            return 0.0
        st.tok_bucket.debit(extra)
        return extra

    async def lease(self, spec: TenantSpec, priority: str,
                    request_json: dict) -> QueueLease:
        """Wait for a weighted-fair dispatch slot (may raise ShedError)."""
        return await self.queue.acquire(
            tenant=spec.name, weight=spec.weight, priority=priority,
            cost=float(estimate_tokens(request_json)))

    def health(self) -> dict:
        return {
            "tenants": self.registry.names(),
            "max_concurrency": self.queue.max_concurrency,
            "shed_queue_depth": self.queue.shed_queue_depth,
            "inflight": self.queue.inflight,
            "queued": self.queue.queued(),
        }
