"""Weighted-fair dispatch queue: deficit round-robin over tenants.

Admitted requests wait here for a dispatch slot before the router
proxies them upstream.  Two priority classes exist:

- `interactive` dispatches whenever fewer than `max_concurrency`
  interactive requests are in flight — it never queues behind `batch`
  (batch may have filled the shared slots; interactive is allowed to
  overshoot so an interactive burst rides on top of a batch flood
  instead of behind it).
- `batch` dispatches only while *total* in-flight stays under
  `max_concurrency`, and new batch arrivals are shed with `ShedError`
  once `shed_queue_depth` batch requests are already waiting.

Within a class, tenants are served by deficit round-robin: each visit
tops a tenant's deficit up by `quantum * weight` and the tenant sends
requests while its deficit covers their cost (cost = estimated tokens),
so a tenant with weight 4 drains ~4x the token volume per round of a
weight-1 tenant regardless of how many requests each has queued.
"""

from __future__ import annotations

import asyncio
import collections
import time
from typing import Deque, Dict, Optional

PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"
# Engine-side integer encoding (lower = more important, 0 = default so
# priority-less traffic behaves exactly like today's FCFS scheduler).
PRIORITY_CLASS_NUM = {PRIORITY_INTERACTIVE: 0, PRIORITY_BATCH: 1}


def priority_class(value: Optional[str], default: str = PRIORITY_INTERACTIVE) -> str:
    """Normalize a priority string (e.g. an X-Priority header value)."""
    if value:
        v = value.strip().lower()
        if v in PRIORITY_CLASS_NUM:
            return v
    return default


class ShedError(Exception):
    """Batch backlog exceeded shed_queue_depth; caller should 503."""

    def __init__(self, retry_after: float = 1.0):
        super().__init__("batch queue saturated")
        self.retry_after = retry_after


class QueueLease:
    """Held while a dispatched request is in flight; release() frees it."""

    __slots__ = ("priority", "wait_s", "_queue", "_released")

    def __init__(self, queue: "FairDispatchQueue", priority: str, wait_s: float):
        self.priority = priority
        self.wait_s = wait_s
        self._queue = queue
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._queue._release(self.priority)


class _Waiter:
    # A waiter is dead as soon as `fut` is cancelled: Task.cancel() on the
    # task awaiting acquire() cancels the future *synchronously*, while the
    # task's except-branch cleanup only runs at its next scheduling.  Any
    # _pump() in that window must therefore judge liveness by the future
    # itself, never by a flag set from the cleanup path.
    __slots__ = ("fut", "cost")

    def __init__(self, cost: float):
        self.fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.cost = cost


class _TenantQ:
    __slots__ = ("waiters", "deficit", "weight")

    def __init__(self, weight: float):
        self.waiters: Deque[_Waiter] = collections.deque()
        self.deficit = 0.0
        self.weight = weight


class FairDispatchQueue:
    def __init__(self, max_concurrency: int = 8, shed_queue_depth: int = 64,
                 quantum: float = 256.0):
        self.max_concurrency = max(int(max_concurrency), 1)
        self.shed_queue_depth = max(int(shed_queue_depth), 0)
        self.quantum = max(float(quantum), 1.0)
        self._inflight_total = 0
        self._inflight_interactive = 0
        # Per class: tenant name -> _TenantQ, plus DRR rotation order.
        self._queues: Dict[str, Dict[str, _TenantQ]] = {
            PRIORITY_INTERACTIVE: {}, PRIORITY_BATCH: {}}
        self._rr: Dict[str, Deque[str]] = {
            PRIORITY_INTERACTIVE: collections.deque(),
            PRIORITY_BATCH: collections.deque()}
        self._queued: Dict[str, int] = {PRIORITY_INTERACTIVE: 0,
                                        PRIORITY_BATCH: 0}

    # -- introspection ----------------------------------------------------
    @property
    def inflight(self) -> int:
        return self._inflight_total

    def queued(self, priority: Optional[str] = None) -> int:
        if priority is None:
            return sum(self._queued.values())
        return self._queued.get(priority, 0)

    # -- dispatch ---------------------------------------------------------
    def _can_dispatch(self, priority: str) -> bool:
        if priority == PRIORITY_INTERACTIVE:
            return self._inflight_interactive < self.max_concurrency
        return self._inflight_total < self.max_concurrency

    def _purge_head(self, priority: str, tq: _TenantQ) -> None:
        while tq.waiters and tq.waiters[0].fut.cancelled():
            tq.waiters.popleft()
            self._queued[priority] -= 1

    def _pick(self, priority: str) -> Optional[_Waiter]:
        """DRR-select the next waiter of a class, or None if class idle."""
        rr, queues = self._rr[priority], self._queues[priority]
        # Each full rotation adds quantum*weight to some tenant's deficit,
        # so this terminates in O(max_cost / quantum) rotations.
        while rr:
            name = rr[0]
            tq = queues[name]
            self._purge_head(priority, tq)
            if not tq.waiters:
                rr.popleft()
                del queues[name]
                continue
            head = tq.waiters[0]
            if tq.deficit < head.cost:
                tq.deficit += self.quantum * tq.weight
                rr.rotate(-1)
                continue
            tq.deficit -= head.cost
            tq.waiters.popleft()
            if not tq.waiters:
                rr.popleft()
                del queues[name]
            return head
        return None

    def _pump(self) -> None:
        while True:
            dispatched = False
            for priority in (PRIORITY_INTERACTIVE, PRIORITY_BATCH):
                if not self._queued[priority] or not self._can_dispatch(priority):
                    continue
                waiter = self._pick(priority)
                if waiter is None:  # only cancelled entries were queued
                    continue
                self._queued[priority] -= 1
                self._inflight_total += 1
                if priority == PRIORITY_INTERACTIVE:
                    self._inflight_interactive += 1
                # _purge_head() guarantees a picked waiter is live, and no
                # await separates the pick from here — set unconditionally
                # so an accounting bug surfaces as InvalidStateError instead
                # of a silently leaked slot.
                waiter.fut.set_result(None)
                dispatched = True
                break  # re-evaluate interactive first
            if not dispatched:
                return

    async def acquire(self, tenant: str, weight: float = 1.0,
                      priority: str = PRIORITY_INTERACTIVE,
                      cost: float = 1.0) -> QueueLease:
        priority = priority_class(priority)
        if (priority == PRIORITY_BATCH and self.shed_queue_depth
                and self._queued[PRIORITY_BATCH] >= self.shed_queue_depth):
            raise ShedError(retry_after=1.0)
        queues = self._queues[priority]
        tq = queues.get(tenant)
        if tq is None:
            tq = queues[tenant] = _TenantQ(max(weight, 1e-6))
            self._rr[priority].append(tenant)
        else:
            tq.weight = max(weight, 1e-6)
        waiter = _Waiter(max(cost, 1.0))
        tq.waiters.append(waiter)
        self._queued[priority] += 1
        t0 = time.monotonic()
        self._pump()
        try:
            await waiter.fut
        except asyncio.CancelledError:
            if waiter.fut.done() and not waiter.fut.cancelled():
                # Dispatched, but the awaiting task was cancelled before it
                # observed the slot — hand the slot straight back.
                self._release(priority)
            else:
                # Not dispatched.  A _pump() run between Task.cancel() and
                # this cleanup may already have purged the waiter (and its
                # _queued count), so only correct the books if it is still
                # enqueued.  Re-look up the tenant queue: the one we
                # appended to may have drained and been rebuilt since.
                tq_now = self._queues[priority].get(tenant)
                if tq_now is not None and waiter in tq_now.waiters:
                    tq_now.waiters.remove(waiter)
                    self._queued[priority] -= 1
            raise
        return QueueLease(self, priority, time.monotonic() - t0)

    def _release(self, priority: str) -> None:
        self._inflight_total = max(0, self._inflight_total - 1)
        if priority == PRIORITY_INTERACTIVE:
            self._inflight_interactive = max(0, self._inflight_interactive - 1)
        self._pump()
