"""Tenant declarations: API-key -> named tenant with limits and class.

Tenants live in a YAML/JSON file (hot-reloaded by the router's dynamic
config watcher):

```yaml
tenants:
  - name: acme
    api_keys: ["sk-acme-prod", "sk-acme-staging"]
    weight: 4                  # weighted-fair-queue share (DRR quantum)
    priority: interactive      # default class: interactive | batch
    allow_priority_upgrade: false  # X-Priority may only downgrade unless true
    requests_per_second: 10    # 0 / absent = unlimited
    tokens_per_second: 4000    # estimated prompt+completion tokens
    burst_seconds: 2.0         # bucket capacity = rate * burst_seconds
default_tenant:                # requests whose key matches no tenant
  name: default
  weight: 1
  priority: interactive
max_concurrency: 8             # fair-queue dispatch slots
shed_queue_depth: 64           # queued batch requests before shedding
```

Key lookup is by SHA-256 digest of the presented bearer token, so a
miss costs one hash regardless of how many tenants are declared and
no code path branches on secret bytes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional

_VALID_PRIORITIES = ("interactive", "batch")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    name: str
    api_keys: tuple = ()
    weight: float = 1.0
    priority: str = "interactive"  # default class; X-Priority may downgrade
    requests_per_second: float = 0.0  # 0 = unlimited
    tokens_per_second: float = 0.0  # 0 = unlimited
    burst_seconds: float = 2.0
    # Honor an X-Priority header that is MORE privileged than `priority`
    # (batch tenant asking for interactive).  Off by default: otherwise a
    # batch-classed tenant could set the header on every request and walk
    # around shedding, slot yielding, and preemption ordering.
    allow_priority_upgrade: bool = False

    @classmethod
    def from_dict(cls, raw: dict) -> "TenantSpec":
        name = str(raw.get("name") or "").strip()
        if not name:
            raise ValueError("tenant entry missing 'name'")
        priority = str(raw.get("priority", "interactive")).lower()
        if priority not in _VALID_PRIORITIES:
            raise ValueError(
                f"tenant {name!r}: priority must be one of "
                f"{_VALID_PRIORITIES}, got {priority!r}")
        keys = raw.get("api_keys", raw.get("api_key", ()))
        if isinstance(keys, str):
            keys = [k.strip() for k in keys.split(",") if k.strip()]
        weight = float(raw.get("weight", 1.0))
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        return cls(
            name=name,
            api_keys=tuple(str(k) for k in keys),
            weight=weight,
            priority=priority,
            requests_per_second=float(raw.get("requests_per_second", 0.0)),
            tokens_per_second=float(raw.get("tokens_per_second", 0.0)),
            burst_seconds=max(float(raw.get("burst_seconds", 2.0)), 0.1),
            allow_priority_upgrade=bool(raw.get("allow_priority_upgrade",
                                                False)),
        )


def _digest(key: str) -> str:
    return hashlib.sha256(key.encode("utf-8", "surrogatepass")).hexdigest()


class TenantRegistry:
    """Immutable snapshot of the tenants file (swap wholesale on reload)."""

    def __init__(self, tenants: List[TenantSpec],
                 default_tenant: Optional[TenantSpec] = None,
                 max_concurrency: int = 8,
                 shed_queue_depth: int = 64):
        self.tenants = list(tenants)
        self.default_tenant = default_tenant or TenantSpec(name="default")
        self.max_concurrency = max(int(max_concurrency), 1)
        self.shed_queue_depth = max(int(shed_queue_depth), 0)
        self._by_digest: Dict[str, TenantSpec] = {}
        for spec in self.tenants:
            for key in spec.api_keys:
                self._by_digest[_digest(key)] = spec

    @classmethod
    def from_dict(cls, raw: dict) -> "TenantRegistry":
        tenants = [TenantSpec.from_dict(t) for t in raw.get("tenants", [])]
        names = [t.name for t in tenants]
        if len(names) != len(set(names)):
            raise ValueError("duplicate tenant names in tenants file")
        default = None
        if raw.get("default_tenant"):
            default = TenantSpec.from_dict(raw["default_tenant"])
        return cls(
            tenants,
            default_tenant=default,
            max_concurrency=raw.get("max_concurrency", 8),
            shed_queue_depth=raw.get("shed_queue_depth", 64),
        )

    @classmethod
    def from_file(cls, path: str) -> "TenantRegistry":
        with open(path, encoding="utf-8") as f:
            text = f.read()
        # A zero-byte or whitespace-only file is almost always a torn
        # read: a writer truncating before rewriting, caught mid-swap by
        # the hot-reload poll.  yaml.safe_load would turn it into `None`
        # and the registry would silently fail OPEN — every key mapping
        # to default_tenant with no limits.  Refuse instead; the caller
        # (QoSGate.maybe_reload) keeps the last-good registry.
        if not text.strip():
            raise ValueError(
                f"tenants file {path}: empty (torn read?); refusing to "
                "load a zero-tenant registry")
        if path.endswith((".yaml", ".yml")):
            import yaml

            raw = yaml.safe_load(text)
        else:
            raw = json.loads(text)
        if not isinstance(raw, dict):
            raise ValueError(f"tenants file {path}: expected a mapping")
        return cls.from_dict(raw)

    def resolve(self, authorization: Optional[str]) -> TenantSpec:
        """Map an `Authorization: Bearer <key>` header to a tenant."""
        if authorization and authorization.startswith("Bearer "):
            token = authorization[len("Bearer "):]
            spec = self._by_digest.get(_digest(token))
            if spec is not None:
                return spec
        return self.default_tenant

    def names(self) -> List[str]:
        return [t.name for t in self.tenants] + [self.default_tenant.name]
