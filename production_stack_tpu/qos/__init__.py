"""Multi-tenant QoS: admission control, weighted-fair queuing, priority.

The router maps API keys to named tenants (`tenants.py`), enforces
per-tenant token-bucket limits (`token_bucket.py`), and dispatches
admitted requests through a deficit-round-robin weighted-fair queue with
two priority classes (`fair_queue.py`).  `gate.py` ties the three
together behind a single `QoSGate` that the router's request service
consults; with no tenants file configured the gate is never constructed
and the hot path is byte-identical to a QoS-less router.

Priority propagates to the engine tier as an `X-Priority` header
(`interactive` | `batch`); the engine scheduler admits by
(priority, arrival) and preempts lowest-priority-then-youngest.
"""

from .fair_queue import (  # noqa: F401
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    FairDispatchQueue,
    QueueLease,
    ShedError,
    priority_class,
)
from .gate import (AdmitResult, QoSGate, estimate_token_parts,  # noqa: F401
                   estimate_tokens)
from .tenants import TenantRegistry, TenantSpec  # noqa: F401
from .token_bucket import TokenBucket  # noqa: F401
