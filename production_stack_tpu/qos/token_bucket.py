"""Token bucket used for per-tenant requests/s and tokens/s limits.

Lazy-refill: tokens accrue at `rate` per second up to `burst`; an
acquire that cannot be covered leaves the bucket untouched and reports
how long the caller should wait (`Retry-After`).  `rate <= 0` means
unlimited.  Single-threaded by construction — the router's event loop
is the only caller — so no locking.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple


class TokenBucket:
    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._tokens = self.burst
        self._last = time.monotonic()

    @property
    def unlimited(self) -> bool:
        return self.rate <= 0

    def _refill(self, now: float) -> None:
        elapsed = now - self._last
        if elapsed > 0:
            self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
            self._last = now

    def try_acquire(self, amount: float = 1.0,
                    now: Optional[float] = None) -> Tuple[bool, float]:
        """Returns (granted, retry_after_seconds)."""
        if self.unlimited:
            return True, 0.0
        now = time.monotonic() if now is None else now
        self._refill(now)
        if self._tokens >= amount:
            self._tokens -= amount
            return True, 0.0
        # Oversized request (amount > burst) would never clear; quote the
        # time to a full bucket so the client backs off instead of spinning.
        deficit = min(amount, self.burst) - self._tokens
        return False, max(deficit / self.rate, 0.0)

    def debit(self, amount: float, now: Optional[float] = None) -> None:
        """Post-hoc charge for usage discovered after admission.

        Admission quotes against an *estimate*; when the completed
        request turns out to have consumed more (a tenant understating
        max_tokens while streaming long completions), the overage is
        debited here.  The balance may go negative — floored at -burst
        so one huge response costs at most one extra full window — which
        makes the next try_acquire fail until refill covers the debt.
        """
        if self.unlimited or amount <= 0:
            return
        self._refill(time.monotonic() if now is None else now)
        self._tokens = max(self._tokens - amount, -self.burst)

    def remaining(self, now: Optional[float] = None) -> float:
        if self.unlimited:
            return float("inf")
        self._refill(time.monotonic() if now is None else now)
        return self._tokens
