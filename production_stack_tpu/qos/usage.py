"""Measure *actual* token usage from a completed upstream response.

QoS admission charges the tenant bucket with an estimate derived from
the request (prompt chars / 4 + max_tokens).  That estimate is
client-controlled: a tenant that understates `max_tokens` and then
streams a long completion pays for 1 token and consumes 500.  After the
response has fully streamed, the router calls `actual_tokens()` on the
buffered body and debits the difference (QoSGate.reconcile) so gaming
the estimator only works once per bucket window.

Measurement sources, best first:

1. A `usage` object in the response — non-streaming JSON bodies, or the
   final SSE chunk when the engine emits stream usage.  Authoritative
   (prompt + completion as counted by the engine).
2. SSE chunk count — one `data:` event per streamed token in this
   stack.  Covers completion tokens only; the caller adds back its own
   prompt-side estimate so the comparison stays apples-to-apples.

Returns None when the body is unusable (error JSON, empty, non-UTF8);
the caller then skips reconciliation — never guesses.
"""

from __future__ import annotations

import json
from typing import Optional, Tuple

# How the measured number relates to the admission estimate:
#   "total"      — prompt + completion, engine-counted.
#   "completion" — completion side only (SSE chunk count fallback).
Measured = Tuple[int, str]


def _usage_total(obj: object) -> Optional[int]:
    if not isinstance(obj, dict):
        return None
    usage = obj.get("usage")
    if not isinstance(usage, dict):
        return None
    total = usage.get("total_tokens")
    if isinstance(total, (int, float)) and not isinstance(total, bool):
        return max(int(total), 0)
    prompt = usage.get("prompt_tokens", 0)
    completion = usage.get("completion_tokens", 0)
    if (isinstance(prompt, (int, float)) and not isinstance(prompt, bool)
            and isinstance(completion, (int, float))
            and not isinstance(completion, bool)):
        return max(int(prompt) + int(completion), 0)
    return None


def actual_tokens(body: bytes) -> Optional[Measured]:
    """Extract measured usage from a buffered response body."""
    if not body:
        return None
    stripped = body.lstrip()
    if not stripped.startswith(b"data:"):
        try:
            obj = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None
        total = _usage_total(obj)
        return (total, "total") if total is not None else None
    # SSE stream: one `data: {...}` event per line (blank-line separated).
    events = []
    for line in stripped.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        payload = line[len(b"data:"):].strip()
        if payload and payload != b"[DONE]":
            events.append(payload)
    if not events:
        return None
    # Engines that emit stream usage put it on one of the last chunks.
    for payload in reversed(events[-4:]):
        try:
            obj = json.loads(payload)
        except (ValueError, UnicodeDecodeError):
            continue
        total = _usage_total(obj)
        if total is not None:
            return (total, "total")
    return (len(events), "completion")
