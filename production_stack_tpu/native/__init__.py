"""ctypes bindings for the native (C++) components.

``libtpu_stack_pickers.so`` implements the endpoint pickers (prefix-aware
xxhash trie, round robin, kv-aware) — the compiled-router work the reference
does in Go gateway plugins (``src/gateway_inference_extension/``). The
Python router uses :class:`NativePicker` when the library is built
(``cmake -S native -B native/build && cmake --build native/build``) and
falls back to the pure-Python implementations otherwise.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Tuple

_LIB_ENV = "TPU_STACK_NATIVE_LIB"
_lib = None
_load_attempted = False


def _candidate_paths() -> List[str]:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    names = ["libtpu_stack_pickers.so"]
    dirs = [
        os.environ.get(_LIB_ENV, ""),
        os.path.join(here, "native", "build"),
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "lib"),
    ]
    out = []
    for d in dirs:
        if not d:
            continue
        if d.endswith(".so"):
            out.append(d)
            continue
        for n in names:
            out.append(os.path.join(d, n))
    return out


def _load():
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    for path in _candidate_paths():
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        lib.tpu_picker_create.restype = ctypes.c_void_p
        lib.tpu_picker_destroy.argtypes = [ctypes.c_void_p]
        lib.tpu_picker_set_endpoints.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.tpu_picker_pick_roundrobin.argtypes = [ctypes.c_void_p]
        lib.tpu_picker_pick_roundrobin.restype = ctypes.c_char_p
        lib.tpu_picker_pick_prefix.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.tpu_picker_pick_prefix.restype = ctypes.c_char_p
        lib.tpu_picker_pick_kv.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t)]
        lib.tpu_picker_pick_kv.restype = ctypes.c_char_p
        lib.tpu_picker_kv_admit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t]
        lib.tpu_picker_remove_endpoint.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p]
        lib.tpu_xxhash64.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.tpu_xxhash64.restype = ctypes.c_uint64
        _lib = lib
        break
    return _lib


def available() -> bool:
    return _load() is not None


def xxhash64(data: bytes) -> int:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    return int(lib.tpu_xxhash64(data, len(data)))


class NativePicker:
    """Endpoint picker backed by the C++ shared library."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native library not built; run "
                "`cmake -S native -B native/build && "
                "cmake --build native/build`"
            )
        self._lib = lib
        self._handle = lib.tpu_picker_create()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.tpu_picker_destroy(handle)
            self._handle = None

    def set_endpoints(self, endpoints: List[str]) -> None:
        blob = "\n".join(endpoints).encode()
        self._lib.tpu_picker_set_endpoints(self._handle, blob)

    def pick_roundrobin(self) -> Optional[str]:
        out = self._lib.tpu_picker_pick_roundrobin(self._handle)
        return out.decode() or None

    def pick_prefix(self, prompt: str) -> Optional[str]:
        data = prompt.encode()
        out = self._lib.tpu_picker_pick_prefix(
            self._handle, data, len(data))
        return out.decode() or None

    def pick_kv(self, prompt: str) -> Tuple[Optional[str], int]:
        data = prompt.encode()
        matched = ctypes.c_size_t(0)
        out = self._lib.tpu_picker_pick_kv(
            self._handle, data, len(data), ctypes.byref(matched))
        return (out.decode() or None), int(matched.value)

    def kv_admit(self, endpoint: str, hashes: List[int]) -> None:
        arr = (ctypes.c_uint64 * len(hashes))(*hashes)
        self._lib.tpu_picker_kv_admit(
            self._handle, endpoint.encode(), arr, len(hashes))

    def remove_endpoint(self, endpoint: str) -> None:
        self._lib.tpu_picker_remove_endpoint(
            self._handle, endpoint.encode())
