"""Shared small utilities for the router and engine.

Capability parity with reference src/vllm_router/utils.py:36-223 (SingletonMeta,
ModelType health-probe payloads, URL validation, ulimit bump, static-config
parsing helpers). Implementations are original.
"""

import enum
import resource
import threading
from urllib.parse import urlparse

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class SingletonMeta(type):
    """Thread-safe singleton metaclass (cf. reference utils.py:36-49)."""

    _instances: dict = {}
    _lock = threading.Lock()

    def __call__(cls, *args, **kwargs):
        if cls not in cls._instances:
            with cls._lock:
                if cls not in cls._instances:
                    cls._instances[cls] = super().__call__(*args, **kwargs)
        return cls._instances[cls]

    @classmethod
    def _reset_instance(mcs, cls):
        """Testing hook: drop the cached instance for ``cls``."""
        with mcs._lock:
            mcs._instances.pop(cls, None)


class SingletonABCMeta(SingletonMeta):
    """Singleton + ABC combination (used by abstract singletons)."""


class ModelType(enum.Enum):
    """Model capability classes and the dummy request used to health-probe each.

    Mirrors the semantics of reference utils.py:68-107 (chat / completion /
    embeddings / rerank / score / transcription probes).
    """

    chat = "/v1/chat/completions"
    completion = "/v1/completions"
    embeddings = "/v1/embeddings"
    rerank = "/v1/rerank"
    score = "/v1/score"
    transcription = "/v1/audio/transcriptions"

    @staticmethod
    def get_test_payload(model_type: str):
        mt = ModelType[model_type]
        if mt == ModelType.chat:
            return {
                "messages": [{"role": "user", "content": "Hi"}],
                "temperature": 0.0,
                "max_tokens": 3,
            }
        if mt == ModelType.completion:
            return {"prompt": "Hi", "temperature": 0.0, "max_tokens": 3}
        if mt == ModelType.embeddings:
            return {"input": "Hi"}
        if mt == ModelType.rerank:
            return {"query": "q", "documents": ["d"]}
        if mt == ModelType.score:
            return {"text_1": "a", "text_2": "b"}
        if mt == ModelType.transcription:
            return {"file": _silent_wav()}
        raise ValueError(f"unknown model type {model_type}")

    @staticmethod
    def get_all_fields():
        return [m.name for m in ModelType]


def _silent_wav(duration_s: float = 0.1, rate: int = 16000) -> bytes:
    """Generate a minimal silent RIFF/WAV payload for transcription probes.

    The reference generates one at runtime too (utils.py:188-223); we build
    the 44-byte PCM header by hand to avoid any audio dependency.
    """
    n_samples = int(duration_s * rate)
    data_size = n_samples * 2  # 16-bit mono
    header = b"RIFF"
    header += (36 + data_size).to_bytes(4, "little")
    header += b"WAVEfmt "
    header += (16).to_bytes(4, "little")
    header += (1).to_bytes(2, "little")      # PCM
    header += (1).to_bytes(2, "little")      # mono
    header += rate.to_bytes(4, "little")
    header += (rate * 2).to_bytes(4, "little")
    header += (2).to_bytes(2, "little")
    header += (16).to_bytes(2, "little")
    header += b"data"
    header += data_size.to_bytes(4, "little")
    return header + b"\x00" * data_size


def validate_url(url: str) -> bool:
    """True iff ``url`` is an absolute http(s) URL with a hostname."""
    try:
        parsed = urlparse(url)
        return parsed.scheme in ("http", "https") and bool(parsed.netloc)
    except (ValueError, AttributeError):
        return False


def parse_static_urls(static_backends: str) -> "list[str]":
    urls = parse_comma_separated_args(static_backends)
    out = []
    for url in urls:
        if validate_url(url):
            out.append(url)
        else:
            logger.warning("Skipping invalid URL: %s", url)
    return out


def parse_static_model_types(static_model_types: str) -> "list[str]":
    types = parse_comma_separated_args(static_model_types)
    valid = set(ModelType.get_all_fields())
    for t in types or []:
        if t not in valid:
            raise ValueError(f"Invalid model type {t!r}; expected one of {sorted(valid)}")
    return types


def parse_comma_separated_args(arg: "str | None") -> "list[str] | None":
    if arg is None:
        return None
    return [item.strip() for item in arg.split(",") if item.strip()]


def parse_static_aliases(static_aliases: str) -> "dict[str, str]":
    """Parse ``alias:model,alias2:model2`` into a dict."""
    aliases: dict = {}
    for pair in parse_comma_separated_args(static_aliases) or []:
        if ":" not in pair:
            raise ValueError(f"Invalid alias spec {pair!r}, expected alias:model")
        alias, model = pair.split(":", 1)
        aliases[alias.strip()] = model.strip()
    return aliases


def is_model_healthy(url: str, model: str, model_type: str, timeout: float = 10.0) -> bool:
    """Probe an engine with a real dummy inference (cf. reference utils.py:188-223).

    Sends the per-model-type test payload to the matching endpoint and treats
    any 200 response as healthy.
    """
    import requests

    mt = ModelType[model_type]
    payload = ModelType.get_test_payload(model_type)
    try:
        if mt == ModelType.transcription:
            resp = requests.post(
                f"{url}{mt.value}",
                files={"file": ("probe.wav", payload["file"], "audio/wav")},
                data={"model": model},
                timeout=timeout,
            )
        else:
            resp = requests.post(
                f"{url}{mt.value}",
                json={"model": model, **payload},
                timeout=timeout,
            )
        return resp.status_code == 200
    except Exception:  # noqa: BLE001
        return False


def set_ulimit(target_soft_limit: int = 65535) -> None:
    """Raise RLIMIT_NOFILE soft limit so many concurrent streams can be open."""
    res = resource.RLIMIT_NOFILE
    soft, hard = resource.getrlimit(res)
    if soft < target_soft_limit:
        try:
            resource.setrlimit(res, (min(target_soft_limit, hard), hard))
        except ValueError as e:
            logger.warning("Could not raise ulimit -n to %d: %s", target_soft_limit, e)
