"""Colored per-module logging.

Capability parity with reference src/vllm_router/log.py (init_logger with
colored level names); implementation is our own formatter on stdlib logging.
"""

import logging
import os
import sys

_FORMAT = "[%(asctime)s] %(levelname)s %(name)s: %(message)s"
_DATEFMT = "%Y-%m-%d %H:%M:%S"

_COLORS = {
    "DEBUG": "\033[36m",     # cyan
    "INFO": "\033[32m",      # green
    "WARNING": "\033[33m",   # yellow
    "ERROR": "\033[31m",     # red
    "CRITICAL": "\033[41m",  # red background
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def __init__(self, use_color: bool):
        super().__init__(_FORMAT, _DATEFMT)
        self._use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        if self._use_color:
            color = _COLORS.get(record.levelname)
            if color:
                record = logging.makeLogRecord(record.__dict__)
                record.levelname = f"{color}{record.levelname}{_RESET}"
        return super().format(record)


def _default_level() -> int:
    name = os.environ.get("TPU_STACK_LOG_LEVEL", "INFO").upper()
    return getattr(logging, name, logging.INFO)


def init_logger(name: str, level: "int | str | None" = None) -> logging.Logger:
    """Create (or fetch) a logger with a colored stream handler attached once."""
    logger = logging.getLogger(name)
    if isinstance(level, str):
        level = getattr(logging, level.upper(), logging.INFO)
    logger.setLevel(level if level is not None else _default_level())
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_ColorFormatter(sys.stderr.isatty()))
        logger.addHandler(handler)
        logger.propagate = False
    return logger
