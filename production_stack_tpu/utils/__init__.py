from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.misc import (
    SingletonMeta,
    ModelType,
    parse_comma_separated_args,
    parse_static_aliases,
    parse_static_model_types,
    parse_static_urls,
    set_ulimit,
    validate_url,
)

__all__ = [
    "init_logger",
    "SingletonMeta",
    "ModelType",
    "parse_comma_separated_args",
    "parse_static_aliases",
    "parse_static_model_types",
    "parse_static_urls",
    "set_ulimit",
    "validate_url",
]
