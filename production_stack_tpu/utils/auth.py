"""Deployment API-key auth shared by the router and engine tiers
(reference tutorial 11 "secure vLLM serve", VLLM_API_KEY).

Semantics follow vLLM: the key gates the INFERENCE surface (`/v1/*`
plus the non-versioned aliases of the same endpoints), not the
intra-stack control plane — probes (`/health`), scrapes (`/metrics`),
the KV controller channel (`/kv/*`), and sleep administration carry no
client credentials and stay open. Router-originated calls to engines
(model probes, batch replays) attach the deployment key registered at
app build time.

Comparisons are constant-time (`hmac.compare_digest`)."""

from __future__ import annotations

import hmac
import os
from typing import Optional

# Non-/v1 aliases of gated inference endpoints.
_GATED_EXACT = frozenset({"/score", "/rerank", "/tokenize", "/detokenize"})


def is_gated(path: str) -> bool:
    """True when the path belongs to the API-key-protected surface."""
    return path.startswith("/v1/") or path in _GATED_EXACT


def resolve_api_key(explicit: Optional[str] = None) -> Optional[str]:
    """Explicit flag value, else the vLLM-compatible env vars."""
    return (explicit or os.environ.get("VLLM_API_KEY")
            or os.environ.get("TPU_STACK_API_KEY") or None)


def check_bearer(authorization: Optional[str], key: str) -> bool:
    """Constant-time check of an `Authorization: Bearer <key>` header."""
    if not authorization or not authorization.startswith("Bearer "):
        return False
    return hmac.compare_digest(authorization[len("Bearer "):], key)


def auth_headers(key: Optional[str]) -> dict:
    return {"Authorization": f"Bearer {key}"} if key else {}


def unauthorized_response():
    from aiohttp import web

    return web.json_response(
        {"error": {"message": "invalid or missing API key",
                   "type": "AuthenticationError"}}, status=401)


# The key this process uses for calls IT originates toward other tiers
# (the router's model probes and batch replays). Registered once at app
# build; one shared key per deployment is the supported topology.
_deployment_key: Optional[str] = None


def set_deployment_key(key: Optional[str]) -> None:
    global _deployment_key
    _deployment_key = key


def deployment_auth_headers() -> dict:
    return auth_headers(_deployment_key)
