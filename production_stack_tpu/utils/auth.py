"""Deployment API-key auth shared by the router and engine tiers
(reference tutorial 11 "secure vLLM serve", VLLM_API_KEY).

Semantics follow vLLM: the key gates the INFERENCE surface (`/v1/*`
plus the non-versioned aliases of the same endpoints), not the
intra-stack control plane — probes (`/health`), scrapes (`/metrics`),
the KV controller reporting channel (`/kv/register|admit|evict|lookup`),
and sleep administration carry no client credentials and stay open.
Control-plane endpoints that can take replicas out of service
(`/autoscale/*`, `/kv/deregister`) are the exception: they are
PRIVILEGED (see :func:`is_privileged`) and require the deployment key
whenever one is configured. Router-originated calls to engines (model
probes, batch replays) attach the deployment key registered at app
build time.

Comparisons are constant-time (`hmac.compare_digest`)."""

from __future__ import annotations

import hmac
import os
from typing import Iterable, Optional, Tuple, Union

# Non-/v1 aliases of gated inference endpoints.
_GATED_EXACT = frozenset({"/score", "/rerank", "/tokenize", "/detokenize"})


def is_gated(path: str) -> bool:
    """True when the path belongs to the API-key-protected surface."""
    return path.startswith("/v1/") or path in _GATED_EXACT


# Destructive/privileged control-plane endpoints registered on the
# client-facing router port: scale-in auto-picks a victim and drives its
# /drain with the router's own deployment key, and /kv/deregister sweeps
# a replica's routing claims. Unauthenticated access to either is a
# one-request denial of service, so — unlike the rest of the /kv
# reporting channel — they require the deployment key when one is set.
# The engine's /debug/profile (programmatic jax.profiler capture, plus
# the served artifact dir beneath it) is privileged for the same reason:
# a profiler trace steals device time and writes to disk. The router's
# /debug/events journal exposes control-plane topology (endpoint URLs,
# breaker/lease churn) and is gated the same way. The remaining /debug
# surfaces are read-only but leak operational detail all the same —
# traces carry request ids, backend URLs, and slow-request timelines,
# steps carry workload shape, and the loop monitor names source
# locations of blocking code — so the whole /debug tree requires the
# deployment key when one is set.
_PRIVILEGED_EXACT = frozenset({"/kv/deregister", "/debug/profile",
                               "/debug/events", "/debug/traces",
                               "/debug/steps", "/debug/loop",
                               "/debug/lora"})
# /debug/kv/* (pull economics, trie introspection) leaks cache topology,
# holder URLs, and workload prefix structure — privileged as a prefix so
# future additions under it are born gated. /debug/snapshot is the
# per-worker federation feed (the union of every other /debug surface in
# one body) and /debug/workers carries pids and shared-state divergence
# views — both prefixes so ?query variants and future sub-paths stay
# gated.
# /lora/* is the adapter distribution fan-out (load/unload across the
# fleet) — control-plane writes, privileged as a prefix.
_PRIVILEGED_PREFIXES = ("/autoscale/", "/debug/profile/",
                        "/debug/traces/", "/debug/kv/",
                        "/debug/snapshot", "/debug/workers",
                        "/lora/")


def is_privileged(path: str) -> bool:
    """True for control-plane paths that can take replicas out of
    service; gated like the inference surface (never open)."""
    return (path in _PRIVILEGED_EXACT
            or path.startswith(_PRIVILEGED_PREFIXES))


def _split_keys(value: str) -> Tuple[str, ...]:
    return tuple(k.strip() for k in value.split(",") if k.strip())


def resolve_api_keys(explicit: Optional[str] = None) -> Tuple[str, ...]:
    """All accepted deployment keys, in declaration order.

    Sources, first match wins: the explicit flag value, the
    vLLM-compatible env vars, or a keyfile (`VLLM_API_KEY_FILE` /
    `TPU_STACK_API_KEY_FILE`, one key per line, `#` comments).  Flag and
    env values may hold several comma-separated keys; every key opens
    the same gated surface (rotation windows, per-team keys).

    A configured-but-unreadable keyfile raises instead of returning no
    keys: returning () would silently disable the bearer gate on every
    gated endpoint (fail open) over a typo or missing mount."""
    raw = (explicit or os.environ.get("VLLM_API_KEY")
           or os.environ.get("TPU_STACK_API_KEY") or None)
    if raw:
        return _split_keys(raw)
    keyfile = (os.environ.get("VLLM_API_KEY_FILE")
               or os.environ.get("TPU_STACK_API_KEY_FILE") or None)
    if keyfile:
        try:
            with open(keyfile, encoding="utf-8") as f:
                lines = [ln.strip() for ln in f]
        except OSError as e:
            raise RuntimeError(
                f"API keyfile {keyfile!r} is configured but unreadable "
                f"({e}); refusing to start with auth disabled") from e
        return tuple(ln for ln in lines if ln and not ln.startswith("#"))
    return ()


def resolve_api_key(explicit: Optional[str] = None) -> Optional[str]:
    """First accepted key (the one this deployment presents outbound)."""
    keys = resolve_api_keys(explicit)
    return keys[0] if keys else None


def check_bearer(authorization: Optional[str],
                 key: Union[str, Iterable[str]]) -> bool:
    """Constant-time check of an `Authorization: Bearer <key>` header.

    `key` may be a single key or an iterable of accepted keys; every
    candidate is compared (no early exit on match) so timing does not
    reveal which configured key a probe collided with."""
    if not authorization or not authorization.startswith("Bearer "):
        return False
    presented = authorization[len("Bearer "):]
    keys = (key,) if isinstance(key, str) else tuple(key)
    ok = False
    for k in keys:
        ok |= hmac.compare_digest(presented, k)
    return ok


def auth_headers(key: Optional[str]) -> dict:
    return {"Authorization": f"Bearer {key}"} if key else {}


def unauthorized_response():
    from aiohttp import web

    return web.json_response(
        {"error": {"message": "invalid or missing API key",
                   "type": "AuthenticationError"}}, status=401)


# The key this process uses for calls IT originates toward other tiers
# (the router's model probes and batch replays). Registered once at app
# build; one shared key per deployment is the supported topology.
_deployment_key: Optional[str] = None


def set_deployment_key(key: Optional[str]) -> None:
    global _deployment_key
    _deployment_key = key


def deployment_auth_headers() -> dict:
    return auth_headers(_deployment_key)
