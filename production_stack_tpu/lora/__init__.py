"""The LoRA adapter control plane (router side).

One base model, many adapters: the engine already hot-swaps adapter
weights in jit-stable slots (engine/core.py); this package makes
adapters a routed, cached, metered serving dimension above it — the
S-LoRA / Punica serving pattern applied to the router tier.
"""

from production_stack_tpu.lora.registry import (  # noqa: F401
    AdapterRegistry,
    LoraPlaneConfig,
    initialize_lora_plane,
)
