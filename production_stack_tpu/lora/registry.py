"""Router-side adapter registry: residency tracking + placement.

The engine tier holds adapter weights in a fixed number of jit-stable
LoRA slots (``engine/core.py``: slot 0 is the base model, slots
``1..max_loras-1`` hot-swap). This registry is the router's view of
that state, scraped from each replica's ``/v1/lora_adapters``:

- **Residency**: which adapter is resident on which replica, with an
  LRU clock per (replica, adapter) so evictions pick the coldest slot.
- **Distribution**: ``POST /lora/load`` fans an adapter out to N
  replicas (fewest-resident-first), LRU-evicting on replicas whose
  slots are full; ``POST /lora/unload`` retracts it.
- **Affinity support**: ``ensure_resident`` is the request path's
  single-flight on-demand load — an adapter-addressed request that
  lands on a replica without the adapter triggers exactly one load per
  (replica, adapter) no matter how many requests pile up behind it,
  with the breaker/timeout semantics of ``router/fault_tolerance.py``
  (a breaker-open replica is never loaded against).
- **Discovery refresh**: every scrape pushes the fresh adapter list
  back into service discovery (``set_lora_adapters``), fixing the
  set-once staleness of ``EndpointInfo.lora_adapters`` so an unloaded
  adapter stops attracting requests within one scrape interval.

Created only when ``--lora-plane`` is set; with the flag off the
request path never reaches this module (flag-off parity convention).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


@dataclass
class LoraPlaneConfig:
    scrape_interval_s: float = 10.0
    # On-demand load deadline on the request path: past this the
    # affinity miss degrades (reroute to a resident replica or 503).
    load_timeout_s: float = 60.0
    # /lora/load fan-out width when the operator does not pass one.
    default_replicas: int = 1
    # Adapter-affinity pinning: when True (default) adapter-addressed
    # requests restrict routing to replicas where the adapter is already
    # resident. Off, every replica is a candidate and misses load
    # on-demand — the A/B baseline leg, not a production setting.
    affinity: bool = True
    api_key: Optional[str] = None


class _Residency:
    """One replica's scraped adapter state."""

    __slots__ = ("adapters", "max_loras", "capacity", "base_model",
                 "scraped_at")

    def __init__(self):
        # adapter name -> last-used monotonic stamp (the LRU clock;
        # scrape inserts at 0 so never-routed adapters evict first).
        self.adapters: Dict[str, float] = {}
        self.max_loras: int = 0
        self.capacity: int = 0
        self.base_model: str = ""
        self.scraped_at: float = 0.0


class AdapterRegistry:
    """The router's adapter control plane (see module docstring)."""

    def __init__(self, config: LoraPlaneConfig,
                 service_discovery: Any = None,
                 fault_tolerance: Any = None):
        self.config = config
        self.service_discovery = service_discovery
        self.fault_tolerance = fault_tolerance
        self._residency: Dict[str, _Residency] = {}
        # Single-flight on-demand loads: (url, adapter) -> Task.
        self._load_flights: Dict[tuple, "asyncio.Task"] = {}
        # One lock per replica serializes evict+load sequences: two
        # adapters loading onto the same full replica concurrently would
        # otherwise race on the LRU victim (double-unload, then one load
        # still finds the slot table full and fails spuriously).
        self._replica_locks: Dict[str, "asyncio.Lock"] = {}
        # Every adapter the plane has seen (scraped or loaded). LRU
        # eviction is capacity management and must NOT shrink the served
        # model set — an adapter evicted from its last replica stays
        # known and reloads on demand at its next request. Only an
        # explicit operator unload (POST /lora/unload) forgets it.
        self._known: "set[str]" = set()
        # Operation counters (mirrored by /debug/lora; the Prometheus
        # side lives in router/metrics.py).
        self.loads_total = 0
        self.load_failures_total = 0
        self.evictions_total = 0
        self.affinity_hits_total = 0
        self.affinity_misses_total = 0
        self.scrapes_total = 0

    # -- HTTP plumbing ---------------------------------------------------

    def _headers(self) -> Dict[str, str]:
        if self.config.api_key:
            return {"Authorization": f"Bearer {self.config.api_key}"}
        return {}

    def _blocked_urls(self) -> "set[str]":
        ft = self.fault_tolerance
        if ft is not None:
            try:
                return ft.breaker.blocked_urls()
            except Exception:  # noqa: BLE001 - breaker view is advisory
                return set()
        return set()

    # -- residency queries ------------------------------------------------

    def is_resident(self, url: str, adapter: str) -> bool:
        res = self._residency.get(url.rstrip("/"))
        return res is not None and adapter in res.adapters

    def resident_urls(self, adapter: str) -> List[str]:
        return [url for url, res in self._residency.items()
                if adapter in res.adapters]

    def base_model_of(self, adapter: str) -> Optional[str]:
        """Base model of the replicas holding ``adapter`` (None until a
        scrape has filled in replica base models)."""
        for res in self._residency.values():
            if adapter in res.adapters and res.base_model:
                return res.base_model
        return None

    def known_adapters(self) -> "set[str]":
        names: "set[str]" = set(self._known)
        for res in self._residency.values():
            names.update(res.adapters)
        return names

    def touch(self, url: str, adapter: str) -> None:
        """Bump the LRU clock: this adapter just served on this replica."""
        res = self._residency.get(url.rstrip("/"))
        if res is not None and adapter in res.adapters:
            res.adapters[adapter] = time.monotonic()

    def record_affinity(self, adapter: str, hit: bool) -> None:
        from production_stack_tpu.router import metrics as router_metrics

        if hit:
            self.affinity_hits_total += 1
            router_metrics.lora_affinity_hits.labels(adapter=adapter).inc()
        else:
            self.affinity_misses_total += 1
            router_metrics.lora_affinity_misses.labels(adapter=adapter).inc()

    def snapshot(self) -> dict:
        """The /debug/lora body."""
        replicas = {}
        for url, res in sorted(self._residency.items()):
            replicas[url] = {
                "adapters": sorted(res.adapters),
                "max_loras": res.max_loras,
                "capacity": res.capacity,
                "free_slots": max(res.capacity - len(res.adapters), 0),
                "base_model": res.base_model,
                "scraped_age_s": (
                    round(time.monotonic() - res.scraped_at, 3)
                    if res.scraped_at else None),
            }
        return {
            "replicas": replicas,
            "adapters": {
                name: sorted(self.resident_urls(name))
                for name in sorted(self.known_adapters())
            },
            "counters": {
                "loads": self.loads_total,
                "load_failures": self.load_failures_total,
                "evictions": self.evictions_total,
                "affinity_hits": self.affinity_hits_total,
                "affinity_misses": self.affinity_misses_total,
                "scrapes": self.scrapes_total,
            },
            "config": {
                "scrape_interval_s": self.config.scrape_interval_s,
                "load_timeout_s": self.config.load_timeout_s,
                "default_replicas": self.config.default_replicas,
                "affinity": self.config.affinity,
            },
        }

    # -- scraping ----------------------------------------------------------

    async def scrape_once(self, urls: List[str]) -> None:
        """Refresh residency from each replica's /v1/lora_adapters.

        Unreachable replicas keep their last-known residency (routing
        still filters them through health/breaker state); replicas that
        left the endpoint list are dropped entirely.
        """
        import aiohttp

        keep = {u.rstrip("/") for u in urls}
        for gone in [u for u in self._residency if u not in keep]:
            del self._residency[gone]
        async with aiohttp.ClientSession(headers=self._headers()) as session:
            results = await asyncio.gather(
                *(self._scrape_one(session, u) for u in sorted(keep)),
                return_exceptions=True)
        for r in results:
            if isinstance(r, Exception):  # pragma: no cover - gather guard
                logger.debug("lora scrape error: %s", r)
        self.scrapes_total += 1

    async def _scrape_one(self, session, url: str) -> None:
        import aiohttp

        try:
            async with session.get(
                f"{url}/v1/lora_adapters",
                timeout=aiohttp.ClientTimeout(total=5),
            ) as resp:
                if resp.status != 200:
                    return
                body = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
            return
        res = self._residency.get(url)
        if res is None:
            res = self._residency[url] = _Residency()
        scraped = {str(a.get("lora_name")) for a in body.get("adapters", [])
                   if a.get("lora_name")}
        # Keep LRU stamps for adapters that stayed; new ones start cold.
        res.adapters = {name: res.adapters.get(name, 0.0)
                        for name in scraped}
        self._known.update(scraped)
        res.max_loras = int(body.get("max_loras", 0) or 0)
        res.capacity = int(
            body.get("capacity", max(res.max_loras - 1, 0)) or 0)
        res.base_model = str(body.get("base_model", "") or "")
        res.scraped_at = time.monotonic()
        self._refresh_discovery(url, sorted(scraped))

    def _refresh_discovery(self, url: str, adapters: List[str]) -> None:
        """Push fresh residency into service discovery so
        ``EndpointInfo.lora_adapters`` (and therefore ``serves()`` and
        adapter salting) tracks loads/unloads instead of staying at its
        registration-time value."""
        sd = self.service_discovery
        fn = getattr(sd, "set_lora_adapters", None)
        if fn is not None:
            try:
                fn(url, adapters)
            except Exception:  # noqa: BLE001 - discovery mirror is advisory
                logger.debug("lora discovery refresh failed", exc_info=True)

    async def scrape_loop(self) -> None:
        """Background task: periodic residency scrape of every
        discovered endpoint (started from the router's on_startup)."""
        while True:
            await asyncio.sleep(self.config.scrape_interval_s)
            try:
                sd = self.service_discovery
                urls = [ep.url for ep in sd.get_endpoint_info()] if sd else []
                await self.scrape_once(urls)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - scrape is best-effort
                logger.debug("lora scrape round failed: %s", e)

    # -- load / unload -----------------------------------------------------

    async def ensure_resident(self, url: str, adapter: str) -> bool:
        """Request-path on-demand load: make ``adapter`` resident on
        ``url``, single-flight per (replica, adapter). Returns True when
        the adapter is (now) resident. Never raises."""
        url = url.rstrip("/")
        if self.is_resident(url, adapter):
            return True
        if url in self._blocked_urls():
            # Breaker-open replica: don't spend the load timeout against
            # a replica that is already failing.
            return False
        key = (url, adapter)
        task = self._load_flights.get(key)
        if task is None:
            task = asyncio.ensure_future(self._load_one(url, adapter))
            self._load_flights[key] = task
            task.add_done_callback(
                lambda _t: self._load_flights.pop(key, None))
        try:
            # Awaiting the shared Task is cancellation-safe: a cancelled
            # follower abandons its await without killing the load.
            return bool(await task)
        except Exception as e:  # noqa: BLE001 - load is best-effort
            logger.warning("lora on-demand load %s on %s failed: %s",
                           adapter, url, e)
            return False

    async def _load_one(self, url: str, adapter: str) -> bool:
        """One load RPC against one replica, LRU-evicting on a full
        reply. Updates residency + metrics on success."""
        from production_stack_tpu.router import metrics as router_metrics

        import aiohttp

        timeout = aiohttp.ClientTimeout(total=self.config.load_timeout_s)
        lock = self._replica_locks.setdefault(url, asyncio.Lock())
        try:
            async with lock, aiohttp.ClientSession(
                    headers=self._headers()) as session:
                status = await self._post_load(session, url, adapter, timeout)
                if status == 400 and await self._evict_lru(
                        session, url, timeout):
                    status = await self._post_load(
                        session, url, adapter, timeout)
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            logger.warning("lora load %s on %s unreachable: %s",
                           adapter, url, e)
            self.load_failures_total += 1
            return False
        if status != 200:
            self.load_failures_total += 1
            return False
        self.loads_total += 1
        router_metrics.lora_loads.labels(adapter=adapter).inc()
        self._known.add(adapter)
        res = self._residency.get(url)
        if res is None:
            res = self._residency[url] = _Residency()
        res.adapters[adapter] = time.monotonic()
        self._refresh_discovery(url, sorted(res.adapters))
        return True

    async def _post_load(self, session, url: str, adapter: str,
                         timeout) -> int:
        async with session.post(
            f"{url}/v1/load_lora_adapter",
            json={"lora_name": adapter},
            timeout=timeout,
        ) as resp:
            return resp.status

    async def _evict_lru(self, session, url: str, timeout) -> bool:
        """Unload the least-recently-used adapter on ``url`` to free a
        slot (the engine replied 400 "no free slots"). Returns True when
        an eviction was carried out."""
        from production_stack_tpu.router import metrics as router_metrics

        res = self._residency.get(url)
        if res is None or not res.adapters:
            return False
        victim = min(res.adapters, key=res.adapters.get)
        try:
            async with session.post(
                f"{url}/v1/unload_lora_adapter",
                json={"lora_name": victim},
                timeout=timeout,
            ) as resp:
                if resp.status == 404:
                    # Stale residency: the engine no longer holds the
                    # victim — dropping our entry IS the reconciliation
                    # (a slot is free that we thought was taken).
                    res.adapters.pop(victim, None)
                    self._refresh_discovery(url, sorted(res.adapters))
                    return True
                if resp.status != 200:
                    return False
        except Exception:  # noqa: BLE001 - eviction RPC is best-effort
            return False
        res.adapters.pop(victim, None)
        self.evictions_total += 1
        router_metrics.lora_evictions.labels(adapter=victim).inc()
        self._refresh_discovery(url, sorted(res.adapters))
        logger.info("lora: LRU-evicted %s from %s", victim, url)
        return True

    async def load_adapter(self, adapter: str, urls: List[str],
                           replicas: Optional[int] = None) -> dict:
        """Fan-out distribution (POST /lora/load): make ``adapter``
        resident on ``replicas`` of the given replicas, preferring ones
        where it already is, then those with the most free slots."""
        want = max(1, int(replicas or self.config.default_replicas))
        blocked = self._blocked_urls()
        candidates = [u.rstrip("/") for u in urls
                      if u.rstrip("/") not in blocked]

        def free_slots(u: str) -> int:
            res = self._residency.get(u)
            if res is None:
                return 0
            return res.capacity - len(res.adapters)

        candidates.sort(key=lambda u: (not self.is_resident(u, adapter),
                                       -free_slots(u), u))
        loaded: List[str] = []
        failed: List[str] = []
        for u in candidates[:want]:
            if await self.ensure_resident(u, adapter):
                loaded.append(u)
            else:
                failed.append(u)
        return {"adapter": adapter, "requested_replicas": want,
                "loaded": loaded, "failed": failed,
                "skipped_breaker_open": sorted(
                    blocked & {u.rstrip("/") for u in urls})}

    async def unload_adapter(self, adapter: str, urls: List[str]) -> dict:
        """Fan-out retraction (POST /lora/unload) from every replica
        where the adapter is resident."""
        from production_stack_tpu.router import metrics as router_metrics

        import aiohttp

        timeout = aiohttp.ClientTimeout(total=self.config.load_timeout_s)
        unloaded: List[str] = []
        failed: List[str] = []
        targets = [u.rstrip("/") for u in urls
                   if self.is_resident(u, adapter)]
        async with aiohttp.ClientSession(headers=self._headers()) as session:
            for u in targets:
                try:
                    async with session.post(
                        f"{u}/v1/unload_lora_adapter",
                        json={"lora_name": adapter},
                        timeout=timeout,
                    ) as resp:
                        ok = resp.status == 200
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    ok = False
                if ok:
                    unloaded.append(u)
                    res = self._residency.get(u)
                    if res is not None:
                        res.adapters.pop(adapter, None)
                        self._refresh_discovery(u, sorted(res.adapters))
                    router_metrics.lora_evictions.labels(
                        adapter=adapter).inc()
                    self.evictions_total += 1
                else:
                    failed.append(u)
        if not failed:
            # Operator retraction: the adapter is gone from the served
            # model set (requests now 404, no on-demand reload).
            self._known.discard(adapter)
        return {"adapter": adapter, "unloaded": unloaded, "failed": failed}


def initialize_lora_plane(args, service_discovery: Any = None,
                          fault_tolerance: Any = None,
                          ) -> Optional[AdapterRegistry]:
    """Build the AdapterRegistry from parsed router args — None unless
    ``--lora-plane`` is set, preserving the flag-off request path byte
    for byte."""
    if not getattr(args, "lora_plane", False):
        return None
    from production_stack_tpu.utils import auth

    keys = auth.resolve_api_keys(getattr(args, "api_key", None))
    return AdapterRegistry(
        LoraPlaneConfig(
            scrape_interval_s=getattr(args, "lora_scrape_interval", 10.0),
            load_timeout_s=getattr(args, "lora_load_timeout", 60.0),
            default_replicas=getattr(args, "lora_default_replicas", 1),
            affinity=not getattr(args, "lora_no_affinity", False),
            api_key=keys[0] if keys else None,
        ),
        service_discovery=service_discovery,
        fault_tolerance=fault_tolerance,
    )
