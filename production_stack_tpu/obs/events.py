"""Fleet event journal: a bounded ring of control-plane events.

Stdlib-only like ``trace.py``/``steps.py``. The control plane already
*logs* its interesting transitions — breaker trips, failovers, lease
sweeps, anti-entropy resyncs, drains, scale-in, OOM pool-shrink rungs,
QoS sheds, canary failures — but log lines are not queryable and cannot
be overlaid on a dashboard. :class:`EventJournal` records each of those
transitions as a small structured record stamped with both monotonic and
wall-clock time, the endpoint it concerns, and the active trace id when
one exists; ``GET /debug/events`` serves the ring newest-first and can
render it directly in the Grafana annotations JSON shape so fleet events
overlay every dashboard row.

Recording is a dict append under a lock — cheap enough that the journal
is always constructed (like the router's TraceRecorder) and callers never
need a ``if journal is not None`` guard on the hot control-plane paths.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: Event kinds the control plane emits today. The journal accepts any
#: string (new subsystems should not need a code change here to record),
#: but the known set is exported for tests and for the /debug/events
#: ``?kind=`` filter error message.
EVENT_KINDS = (
    "breaker_open",
    "breaker_reset",
    "failover",
    "retry_exhausted",
    "lease_sweep",
    "kv_resync",
    "drain",
    "scale_in",
    "pool_shrink",
    "qos_shed",
    "canary_failure",
)


class EventJournal:
    """Bounded, thread-safe ring buffer of control-plane events."""

    def __init__(self, service: str = "", capacity: int = 1024):
        self.service = service
        self.capacity = max(1, int(capacity))
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.recorded_total = 0
        #: per-kind counts survive ring eviction (totals, not a window).
        self._kind_counts: Dict[str, int] = {}

    def record(
        self,
        kind: str,
        endpoint: Optional[str] = None,
        trace_id: Optional[str] = None,
        **attributes: Any,
    ) -> dict:
        """Append one event. Returns the stored record (for tests)."""
        event = {
            "kind": kind,
            "time_unix": time.time(),
            "time_monotonic": time.monotonic(),
            "endpoint": endpoint,
            "trace_id": trace_id,
            "attributes": {k: v for k, v in attributes.items()
                           if v is not None},
        }
        with self._lock:
            self._events.append(event)
            self.recorded_total += 1
            self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1
        return event

    def snapshot(
        self,
        limit: int = 100,
        kind: Optional[str] = None,
    ) -> List[dict]:
        """Newest-first copies of up to ``limit`` events."""
        with self._lock:
            events = list(self._events)
        out: List[dict] = []
        for ev in reversed(events):
            if kind is not None and ev["kind"] != kind:
                continue
            out.append(dict(ev))
            if len(out) >= limit:
                break
        return out

    def kind_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._kind_counts)

    def summary(self) -> dict:
        with self._lock:
            return {
                "service": self.service,
                "capacity": self.capacity,
                "recorded_total": self.recorded_total,
                "buffered": len(self._events),
                "kind_counts": dict(self._kind_counts),
            }

    def fed_snapshot(self, limit: int = 100) -> dict:
        """Worker-local state for the federation plane: the summary
        (whose ``kind_counts`` the merged view sums) plus newest-first
        ring records ready for ``federation.merge_rings``."""
        out = self.summary()
        out["events"] = self.snapshot(limit=limit)
        return out

    def to_grafana(self, limit: int = 100, kind: Optional[str] = None) -> List[dict]:
        """Events in the Grafana annotations JSON shape (one annotation
        per event: epoch-millis ``time``, ``tags``, markdown ``text``), so
        a dashboard annotation query can overlay fleet events directly."""
        out = []
        for ev in self.snapshot(limit=limit, kind=kind):
            tags = [ev["kind"]]
            if ev.get("endpoint"):
                tags.append(ev["endpoint"])
            detail = " ".join(
                f"{k}={v}" for k, v in sorted(ev["attributes"].items()))
            text = ev["kind"] if not detail else f"{ev['kind']}: {detail}"
            out.append({
                "time": int(ev["time_unix"] * 1000),
                "tags": tags,
                "text": text,
            })
        return out
