"""Request tracing: W3C trace-context + an in-process flight recorder.

Stdlib-only by design (the serving image pins its dependency set): no
opentelemetry-sdk, no exporter packages. What this module provides:

- :func:`parse_traceparent` / :func:`format_traceparent` -- the W3C
  ``traceparent`` header (``00-<32hex trace>-<16hex span>-<2hex flags>``),
  the propagation contract between router and engine.
- :func:`trace_id_from_request_id` -- correlation fallback: when no
  ``traceparent`` arrives, both sides derive the *same* trace id from the
  ``X-Request-Id`` they already share, so traces still stitch.
- :class:`Span` / :class:`RequestTrace` -- one request's stage timeline.
- :class:`TraceRecorder` -- bounded ring buffer of completed traces
  ("flight recorder"), per-stage sum/count aggregates feeding the engine's
  ``tpu:*_time_seconds`` exposition, slow-request detection (one structured
  JSON log line per offender), and optional OTLP-JSON export to a file or
  an HTTP collector endpoint.
- :class:`StageClock` -- the tiny mutable mark-sheet the engine server
  hands into ``EngineCore`` so the engine thread can stamp queue/prefill/
  decode boundaries without knowing anything about spans.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str, int]]:
    """Parse a W3C ``traceparent`` header into (trace_id, span_id, flags).

    Returns ``None`` for anything malformed — a bad header from a client
    must never break the request path, it just starts a fresh trace.
    """
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, int(flags, 16)


def format_traceparent(trace_id: str, span_id: str, flags: int = 1) -> str:
    return f"00-{trace_id}-{span_id}-{flags:02x}"


def trace_id_from_request_id(request_id: str) -> str:
    """Stable 32-hex trace id derived from an ``X-Request-Id``.

    Router and engine share the request id even when the ``traceparent``
    header is absent or stripped by a middlebox; hashing it means both
    sides land on the same trace id independently.
    """
    digest = hashlib.sha256(request_id.encode()).hexdigest()[:32]
    if digest == "0" * 32:  # all-zero trace ids are invalid per W3C
        digest = "1" * 32
    return digest


class Span:
    """One timed stage. ``end`` is None while open; ``finish()`` closes it."""

    __slots__ = ("name", "span_id", "parent_span_id", "start", "end",
                 "attributes", "events")

    def __init__(
        self,
        name: str,
        start: Optional[float] = None,
        parent_span_id: Optional[str] = None,
        attributes: Optional[Dict[str, Any]] = None,
        span_id: Optional[str] = None,
    ):
        self.name = name
        self.span_id = span_id or new_span_id()
        self.parent_span_id = parent_span_id
        self.start = time.time() if start is None else start
        self.end: Optional[float] = None
        self.attributes: Dict[str, Any] = dict(attributes or {})
        # Point-in-time span events (OTel semantics): retry, failover...
        # Serialized only when non-empty, so eventless traces keep their
        # historical JSON shape byte-for-byte.
        self.events: List[dict] = []

    @property
    def duration_s(self) -> float:
        end = self.end if self.end is not None else time.time()
        return max(0.0, end - self.start)

    def finish(self, end: Optional[float] = None, **attributes) -> "Span":
        if self.end is None:
            self.end = time.time() if end is None else end
        if attributes:
            self.attributes.update(attributes)
        return self

    def add_event(self, name: str, timestamp: Optional[float] = None,
                  **attributes) -> dict:
        event = {
            "name": name,
            "time_unix": time.time() if timestamp is None else timestamp,
            "attributes": dict(attributes),
        }
        self.events.append(event)
        return event

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "start_unix": self.start,
            "end_unix": self.end,
            "duration_s": round(self.duration_s, 6),
            "attributes": self.attributes,
        }
        if self.events:
            out["events"] = [dict(e) for e in self.events]
        return out


class RequestTrace:
    """All spans recorded for one request on one service.

    The first span started is the root by convention; child spans default
    their parent to it unless an explicit ``parent`` is given.
    """

    def __init__(
        self,
        request_id: str,
        trace_id: Optional[str] = None,
        parent_span_id: Optional[str] = None,
        service: str = "",
    ):
        self.request_id = request_id
        self.trace_id = trace_id or trace_id_from_request_id(request_id)
        # Span id of the remote parent (e.g. the router's upstream span,
        # arriving at the engine via traceparent). The local root span
        # links under it.
        self.remote_parent_span_id = parent_span_id
        self.service = service
        self.spans: List[Span] = []

    @property
    def root(self) -> Optional[Span]:
        return self.spans[0] if self.spans else None

    def start_span(
        self,
        name: str,
        start: Optional[float] = None,
        parent: Optional[Span] = None,
        **attributes,
    ) -> Span:
        if parent is not None:
            parent_id = parent.span_id
        elif self.spans:
            parent_id = self.spans[0].span_id
        else:
            parent_id = self.remote_parent_span_id
        span = Span(name, start=start, parent_span_id=parent_id,
                    attributes=attributes)
        self.spans.append(span)
        return span

    def add_span(
        self,
        name: str,
        start: float,
        end: float,
        parent: Optional[Span] = None,
        **attributes,
    ) -> Span:
        span = self.start_span(name, start=start, parent=parent, **attributes)
        span.finish(end=end)
        return span

    @property
    def start(self) -> float:
        return min((s.start for s in self.spans), default=0.0)

    @property
    def duration_s(self) -> float:
        if self.root is not None and self.root.end is not None:
            return self.root.duration_s
        ends = [s.end for s in self.spans if s.end is not None]
        if not ends:
            return 0.0
        return max(0.0, max(ends) - self.start)

    def close(self, end: Optional[float] = None) -> None:
        for span in self.spans:
            if span.end is None:
                span.finish(end=end)

    def summary(self) -> dict:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "service": self.service,
            "root": self.root.name if self.root else None,
            "start_unix": self.start,
            "duration_s": round(self.duration_s, 6),
            "num_spans": len(self.spans),
        }

    def to_dict(self) -> dict:
        out = self.summary()
        out["remote_parent_span_id"] = self.remote_parent_span_id
        out["spans"] = [s.to_dict() for s in self.spans]
        return out

    def to_otlp(self) -> dict:
        """One ``resourceSpans`` entry in OTLP-JSON shape — the format an
        OTel collector's ``otlp`` HTTP receiver (or ``filelog`` + a
        translator) ingests, so the observability/otel-example stack can
        consume our export without an SDK on this side."""
        spans = []
        for s in self.spans:
            end = s.end if s.end is not None else s.start
            entry = {
                "traceId": self.trace_id,
                "spanId": s.span_id,
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(int(s.start * 1e9)),
                "endTimeUnixNano": str(int(end * 1e9)),
                "attributes": [_otlp_attr(k, v)
                               for k, v in s.attributes.items()],
            }
            if s.events:
                entry["events"] = [{
                    "timeUnixNano": str(int(e["time_unix"] * 1e9)),
                    "name": e["name"],
                    "attributes": [_otlp_attr(k, v)
                                   for k, v in e["attributes"].items()],
                } for e in s.events]
            if s.parent_span_id:
                entry["parentSpanId"] = s.parent_span_id
            spans.append(entry)
        return {
            "resource": {"attributes": [
                _otlp_attr("service.name", self.service or "tpu-stack"),
                _otlp_attr("request.id", self.request_id),
            ]},
            "scopeSpans": [{
                "scope": {"name": "production_stack_tpu.obs"},
                "spans": spans,
            }],
        }


def _otlp_attr(key: str, value: Any) -> dict:
    if isinstance(value, bool):
        v: dict = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


class StageClock:
    """Per-request stage marks stamped by the engine thread.

    The server creates one per request and threads it through
    ``EngineCore.add_request``; the core only ever sets attributes on it
    (no imports, no locking — single writer per field, reader runs after
    the request finishes).
    """

    __slots__ = ("arrival", "prefill_start", "prefill_end", "first_token",
                 "last_token", "tokens", "prompt_tokens", "cached_tokens",
                 "preemptions", "prefill_chunks")

    def __init__(self, arrival: Optional[float] = None):
        self.arrival = time.time() if arrival is None else arrival
        self.prefill_start = 0.0
        self.prefill_end = 0.0
        self.first_token = 0.0
        self.last_token = 0.0
        self.tokens = 0
        self.prompt_tokens = 0
        self.cached_tokens = 0
        self.preemptions = 0
        # Chunked prefill: scheduler chunks dispatched for this prompt.
        self.prefill_chunks = 0


# ---------------------------------------------------------------------------
# Exporters (--trace-export toggle)
# ---------------------------------------------------------------------------


class _FileExporter:
    """Append one OTLP-JSON line per trace to a file."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def export(self, payload: dict) -> None:
        line = json.dumps(payload, separators=(",", ":"))
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")

    def close(self) -> None:
        pass


class _HttpExporter:
    """POST OTLP-JSON to a collector endpoint from a background thread.

    Export must never slow the request path: traces are queued (bounded)
    and shipped by a daemon worker; failures are logged and dropped.
    """

    def __init__(self, url: str, max_queue: int = 1024):
        self.url = url
        self._queue: deque = deque(maxlen=max_queue)
        self._event = threading.Event()
        self._closed = False
        self._errors = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="trace-export")
        self._thread.start()

    def export(self, payload: dict) -> None:
        self._queue.append(payload)
        self._event.set()

    def _run(self) -> None:
        while not self._closed:
            self._event.wait(timeout=1.0)
            self._event.clear()
            while self._queue:
                payload = self._queue.popleft()
                try:
                    req = urllib.request.Request(
                        self.url,
                        data=json.dumps(payload).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    urllib.request.urlopen(req, timeout=5.0).close()
                except (urllib.error.URLError, OSError, ValueError) as e:
                    self._errors += 1
                    if self._errors <= 3 or self._errors % 100 == 0:
                        logger.warning(
                            "trace export to %s failed (%d so far): %s",
                            self.url, self._errors, e)

    def close(self) -> None:
        self._closed = True
        self._event.set()


def make_exporter(spec: Optional[str]):
    """``--trace-export`` spec: ``file:/path`` or ``http(s)://host/v1/traces``.

    Anything else non-empty is treated as a file path.
    """
    if not spec:
        return None
    if spec.startswith(("http://", "https://")):
        return _HttpExporter(spec)
    if spec.startswith("file:"):
        spec = spec[len("file:"):]
    return _FileExporter(spec)


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TraceRecorder:
    """Bounded ring buffer of completed request traces plus stage rollups.

    Thread-safe: the router records from the event loop, the engine from
    the event loop after the engine thread filled the StageClock, and
    ``/metrics`` reads the rollups concurrently.
    """

    def __init__(
        self,
        service: str,
        capacity: int = 512,
        slow_threshold_s: float = 0.0,
        export: Optional[str] = None,
        log: Optional[logging.Logger] = None,
        sample_rate: float = 1.0,
        slow_log_interval_s: float = 0.0,
    ):
        self.service = service
        self.capacity = max(1, int(capacity))
        self.slow_threshold_s = float(slow_threshold_s or 0.0)
        # Head sampling for always-on production tracing: traces whose id
        # hashes above the rate skip the ring buffer, slow-trace logging,
        # and export — but their stage rollups still feed /metrics, so
        # the tpu:*_time_seconds series stay exact. Deterministic by
        # trace id: router and engine keep/drop the SAME requests, so
        # sampled traces still stitch across services.
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        # Minimum seconds between slow_trace log lines (0 = unlimited,
        # the historical behavior). Slow requests are always COUNTED.
        self.slow_log_interval_s = float(slow_log_interval_s or 0.0)
        self._last_slow_log = 0.0
        self._traces: "OrderedDict[str, RequestTrace]" = OrderedDict()
        self._lock = threading.Lock()
        self._stage: Dict[str, List[float]] = {}  # name -> [sum_s, count]
        self.slow_requests = 0
        self.recorded_total = 0
        self.sampled_out_total = 0
        self.slow_logs_suppressed_total = 0
        self._exporter = make_exporter(export)
        self._log = log or logger

    # -- recording --------------------------------------------------------

    def begin(
        self,
        request_id: str,
        traceparent: Optional[str] = None,
    ) -> RequestTrace:
        """Create (but do not yet store) a trace for one request,
        continuing the incoming W3C context when one is present."""
        ctx = parse_traceparent(traceparent)
        if ctx is not None:
            trace_id, parent_span_id, _flags = ctx
        else:
            trace_id = trace_id_from_request_id(request_id)
            parent_span_id = None
        return RequestTrace(
            request_id,
            trace_id=trace_id,
            parent_span_id=parent_span_id,
            service=self.service,
        )

    def sampled(self, trace_id: str) -> bool:
        """Deterministic keep/drop decision for a trace id. At the default
        rate of 1.0 everything is kept (the flag-off path stays
        byte-identical: ``record`` never even consults this)."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        try:
            bucket = int(trace_id[:8], 16) / float(0xFFFFFFFF)
        except (ValueError, TypeError):
            return True  # malformed ids must never break the request path
        return bucket < self.sample_rate

    def record(self, trace: RequestTrace) -> None:
        """Store a completed trace: ring-buffer it, roll up stage sums,
        flag slow requests, export if configured."""
        trace.close()
        keep = self.sample_rate >= 1.0 or self.sampled(trace.trace_id)
        with self._lock:
            if keep:
                self._traces.pop(trace.request_id, None)
                self._traces[trace.request_id] = trace
                while len(self._traces) > self.capacity:
                    self._traces.popitem(last=False)
            else:
                self.sampled_out_total += 1
            for span in trace.spans:
                agg = self._stage.setdefault(span.name, [0.0, 0])
                agg[0] += span.duration_s
                agg[1] += 1
            self.recorded_total += 1
            is_slow = (self.slow_threshold_s > 0
                       and trace.duration_s >= self.slow_threshold_s)
            log_slow = is_slow and keep
            if is_slow:
                self.slow_requests += 1
                if log_slow and self.slow_log_interval_s > 0:
                    now = time.time()
                    if now - self._last_slow_log < self.slow_log_interval_s:
                        self.slow_logs_suppressed_total += 1
                        log_slow = False  # still counted above
                    else:
                        self._last_slow_log = now
        if log_slow:
            self._log.warning(
                "slow_trace %s",
                json.dumps({
                    "event": "slow_trace",
                    "service": self.service,
                    "threshold_s": self.slow_threshold_s,
                    **trace.to_dict(),
                }, separators=(",", ":")),
            )
        if keep and self._exporter is not None:
            try:
                self._exporter.export({"resourceSpans": [trace.to_otlp()]})
            except OSError as e:
                logger.warning("trace export failed: %s", e)

    # -- retrieval --------------------------------------------------------

    def get(self, request_id: str) -> Optional[RequestTrace]:
        with self._lock:
            return self._traces.get(request_id)

    def root_attribute_values(self, name: str) -> List[float]:
        """Numeric values of a root-span attribute across the ring, oldest
        first. The storm/chaos harnesses read ``overhead_s`` this way to
        report ``router_overhead_p99`` without scraping /metrics."""
        with self._lock:
            traces = list(self._traces.values())
        out: List[float] = []
        for tr in traces:
            if tr.root is None:
                continue
            v = tr.root.attributes.get(name)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out.append(float(v))
        return out

    def list(self, min_duration_s: float = 0.0, limit: int = 100) -> List[dict]:
        with self._lock:
            traces = list(self._traces.values())
        out = []
        for tr in reversed(traces):  # newest first
            if tr.duration_s >= min_duration_s:
                out.append(tr.summary())
            if len(out) >= limit:
                break
        return out

    def stage_stats(self) -> Dict[str, Tuple[float, int]]:
        """{span name: (total_seconds, count)} across recorded traces —
        the source for the tpu:*_time_seconds sum/count exposition."""
        with self._lock:
            return {k: (v[0], v[1]) for k, v in self._stage.items()}

    def fed_snapshot(self, limit: int = 100,
                     request_id: Optional[str] = None) -> dict:
        """Worker-local state for the federation plane
        (``obs/federation.py``): ring summaries newest-first plus the
        cumulative counters the merged view sums. ``request_id`` pulls
        one full trace timeline so the multi-worker
        ``/debug/traces/{id}`` fan-in can find which worker holds it."""
        out = {
            "service": self.service,
            "capacity": self.capacity,
            "recorded_total": self.recorded_total,
            "slow_requests": self.slow_requests,
            "sampled_out_total": self.sampled_out_total,
            "slow_logs_suppressed_total": self.slow_logs_suppressed_total,
            "traces": self.list(limit=limit),
        }
        if request_id is not None:
            tr = self.get(request_id)
            out["trace"] = tr.to_dict() if tr is not None else None
        return out

    def close(self) -> None:
        if self._exporter is not None:
            self._exporter.close()
