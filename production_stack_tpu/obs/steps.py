"""Step flight recorder: per-engine-step records + roofline accounting.

The request-level flight recorder (:mod:`production_stack_tpu.obs.trace`)
answers "where did THIS request's time go"; this module answers "what was
the device doing, step by step". ``EngineCore._loop`` appends one record
per model step — prefill, budgeted prefill chunk step, fused decode
burst, or speculative verify burst — carrying the batch composition, the
scheduled token count, the measured wall time, and an *estimated* HBM
byte count from a small roofline model:

    bytes ≈ forwards × param_bytes            (weight reads)
          + kv_read_tokens  × kv_token_bytes  (paged-attention KV reads)
          + kv_write_tokens × kv_token_bytes  (KV page writes)

That is the same weights+KV traffic model behind
``BENCH_DECODE_PROFILE_r05.json``'s floors, so the derived
``tpu:model_bandwidth_utilization`` gauge (achieved bytes/s over the
recent step window vs the device HBM floor) is directly comparable to
the profiled ``gap_vs_combined_floor``.

Everything here is stdlib-only and cheap: one dict append under a lock
per engine step (steps are milliseconds to seconds of device time; the
record is microseconds of host time — the recorder-overhead A/B test
holds it to <1% tokens/s).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Step kinds, in scheduling order. "fused" is reserved for the planned
# single fused prefill+decode step program (ROADMAP open item 1) so the
# /debug/steps schema and the Prometheus label set are stable when it
# lands.
STEP_KINDS = ("prefill", "prefill_chunk", "decode_burst", "spec_verify",
              "fused")

# Device HBM bandwidth floor (bytes/s) for the utilization gauge. The
# default is the v5e figure used to derive the decode floors in
# BENCH_DECODE_PROFILE_r05.json; override per deployment with
# TPU_STACK_HBM_GBS (decimal bytes/s).
DEFAULT_HBM_BYTES_PER_S = 819e9


def device_hbm_bytes_per_s() -> float:
    try:
        return float(os.environ.get("TPU_STACK_HBM_GBS", "") or
                     DEFAULT_HBM_BYTES_PER_S)
    except ValueError:
        return DEFAULT_HBM_BYTES_PER_S


class StepRecorder:
    """Bounded ring buffer of per-step records plus per-kind rollups.

    Thread-safe: the engine thread records, ``/metrics`` and
    ``/debug/steps`` read concurrently from the event loop.
    """

    def __init__(
        self,
        capacity: int = 1024,
        param_bytes: int = 0,
        kv_token_bytes: int = 0,
        hbm_bytes_per_s: Optional[float] = None,
        window_s: float = 60.0,
    ):
        self.capacity = max(1, int(capacity))
        # Roofline constants. param_bytes is often unknown at construction
        # (weights load after the recorder exists); the core fills it in
        # lazily before the first record.
        self.param_bytes = int(param_bytes)
        self.kv_token_bytes = int(kv_token_bytes)
        self.hbm_bytes_per_s = float(
            hbm_bytes_per_s if hbm_bytes_per_s is not None
            else device_hbm_bytes_per_s())
        self.window_s = float(window_s)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        # kind -> [wall_s_sum, count, tokens, hbm_bytes]
        self._kinds: Dict[str, List[float]] = {
            k: [0.0, 0, 0, 0] for k in STEP_KINDS}
        self.recorded_total = 0

    # -- recording --------------------------------------------------------

    def record(
        self,
        kind: str,
        wall_s: float,
        *,
        rows: int = 0,
        tokens: int = 0,
        forwards: int = 1,
        kv_read_tokens: int = 0,
        kv_write_tokens: int = 0,
        batched: bool = False,
    ) -> dict:
        """Append one step record; returns it (tests inspect the shape)."""
        hbm_bytes = (
            forwards * self.param_bytes
            + (kv_read_tokens + kv_write_tokens) * self.kv_token_bytes
        )
        with self._lock:
            self.recorded_total += 1
            rec = {
                "step": self.recorded_total,
                "ts_unix": time.time(),
                "kind": kind,
                "wall_s": round(wall_s, 6),
                "rows": rows,
                "tokens": tokens,
                "forwards": forwards,
                "kv_read_tokens": kv_read_tokens,
                "kv_write_tokens": kv_write_tokens,
                "hbm_bytes": hbm_bytes,
                "batched": batched,
            }
            self._ring.append(rec)
            agg = self._kinds.setdefault(kind, [0.0, 0, 0, 0])
            agg[0] += wall_s
            agg[1] += 1
            agg[2] += tokens
            agg[3] += hbm_bytes
        return rec

    # -- retrieval --------------------------------------------------------

    def snapshot(self, limit: Optional[int] = None,
                 kind: Optional[str] = None) -> List[dict]:
        """Newest-first list of records, optionally filtered by kind."""
        with self._lock:
            recs = list(self._ring)
        out = []
        for rec in reversed(recs):
            if kind is not None and rec["kind"] != kind:
                continue
            out.append(rec)
            if limit is not None and len(out) >= limit:
                break
        return out

    def kind_stats(self) -> Dict[str, dict]:
        """Lifetime per-kind rollups (every known kind always present, so
        the Prometheus series never vanish between scrapes)."""
        with self._lock:
            return {
                k: {"wall_s": v[0], "count": v[1], "tokens": v[2],
                    "hbm_bytes": v[3]}
                for k, v in self._kinds.items()
            }

    def bandwidth_utilization(self, now: Optional[float] = None) -> float:
        """Achieved HBM bytes/s over the recent step window divided by the
        device floor: estimated bytes moved by steps that STARTED inside
        the window, over their summed wall time (model-active seconds, not
        wall-clock — idle gaps between steps are not a bandwidth claim)."""
        if now is None:
            now = time.time()
        cutoff = now - self.window_s
        with self._lock:
            wall = 0.0
            moved = 0
            for rec in self._ring:
                if rec["ts_unix"] - rec["wall_s"] >= cutoff:
                    wall += rec["wall_s"]
                    moved += rec["hbm_bytes"]
        if wall <= 0.0 or self.hbm_bytes_per_s <= 0.0:
            return 0.0
        return (moved / wall) / self.hbm_bytes_per_s

    def summary(self) -> dict:
        """Header block for /debug/steps (everything but the records)."""
        return {
            "capacity": self.capacity,
            "recorded_total": self.recorded_total,
            "param_bytes": self.param_bytes,
            "kv_token_bytes": self.kv_token_bytes,
            "hbm_bytes_per_s": self.hbm_bytes_per_s,
            "window_s": self.window_s,
            "bandwidth_utilization": round(self.bandwidth_utilization(), 6),
            "kinds": self.kind_stats(),
        }
