"""Worker telemetry federation: snapshot/merge for every router store.

The multi-worker router (``--router-workers N``, SO_REUSEPORT pre-fork)
runs N identical processes behind one port. Every telemetry surface the
stack built — the prometheus registry, the TraceRecorder ring, the
EventJournal, the SLO outcome counts, the loop-monitor rings, the KV
pull ledger — is process-local in-memory state, so without this module
going multi-worker silently fragments ``/metrics`` into whichever
worker the scrape landed on and turns every ``/debug/*`` view into a
1/N sample. This module is the merge half of the federation protocol:

- Each store exposes a ``fed_snapshot()`` (JSON-serializable local
  state; see ``obs/trace.py``, ``obs/events.py``, ``obs/looplag.py``,
  ``router/slo.py``, ``kv/economics.py``) and the registry is dumped by
  ``router/metrics.py:registry_snapshot()``. Snapshots travel over the
  privileged per-worker ``GET /debug/snapshot`` (UDS loopback).
- The functions here merge those snapshots: counters and histogram
  samples SUM across workers; gauges follow an explicit semantics map
  (cumulative mirrors sum, identical-view gauges take max, everything
  else becomes a per-``worker``-labeled series); ring records are
  stamped ``worker=<id>`` and re-sorted newest-first.
- Shared mutable state (breaker views, the KV controller trie) is NOT
  merged — each worker's view is digested and compared, and divergence
  is reported (``/debug/workers``) instead of papered over.

Stdlib-only, like the rest of ``obs/``: the HTTP fan-in lives in
``router/workers.py``; everything here is pure data transformation so
it unit-tests without sockets.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple

#: Label added to per-worker series and merged ring records.
WORKER_LABEL = "worker"

#: Gauges whose value is a cumulative total mirrored from a monotonic
#: source at scrape time (the ``_total``-suffixed gauge convention):
#: summing across workers reproduces the fleet total, exactly like a
#: counter.
GAUGE_SUM = frozenset({
    "vllm_router:trace_sampled_out_total",
    "vllm_router:slow_trace_logs_suppressed_total",
    "vllm_router:loop_stalls_total",
    "vllm_router:loop_component_seconds_total",
    "vllm_router:kv_pull_net_seconds_saved_total",
})

#: Gauges every worker computes from the same underlying source (service
#: discovery, engine-side scrapes): the views are identical up to scrape
#: phase, so summing would multiply by N — take the max instead.
GAUGE_MAX = frozenset({
    "vllm_router:healthy_pods_total",
    "vllm_router:autoscale_recommended_replicas",
    "vllm_router:autoscale_current_replicas",
    "vllm_router:num_requests_running",
    "vllm_router:num_requests_waiting",
    "vllm_router:gpu_cache_usage_perc",
    "vllm_router:gpu_prefix_cache_hit_rate",
})
# Every other gauge (per-worker traffic slices like current_qps /
# avg_ttft, process gauges like mem_usage_bytes, window rollups like
# event_loop_lag_seconds{stat=p99} and goodput_ratio, per-process views
# like circuit_state and kv_controller_instances) gets a worker label:
# those values are only meaningful per process.


def _sample_key(name: str, labels: Dict[str, str]) -> Tuple:
    return (name, tuple(sorted(labels.items())))


def merge_metric_families(worker_families: Dict[int, List[dict]]
                          ) -> List[dict]:
    """Merge per-worker registry snapshots into one family list.

    ``worker_families``: worker id -> ``registry_snapshot()`` output
    (list of ``{"name", "type", "documentation", "samples":
    [[sample_name, labels, value], ...]}``). Counter, histogram, and
    summary samples sum per (name, labels); ``_created`` timestamps take
    the earliest. Gauges follow :data:`GAUGE_SUM` / :data:`GAUGE_MAX`,
    defaulting to a per-worker ``worker=<id>`` label.
    """
    order: List[str] = []
    meta: Dict[str, dict] = {}
    # family name -> sample key -> [sample_name, labels, value]
    merged: Dict[str, Dict[Tuple, list]] = {}
    for wid in sorted(worker_families):
        for family in worker_families[wid]:
            name = family["name"]
            if name not in meta:
                order.append(name)
                meta[name] = {"name": name,
                              "type": family.get("type", "untyped"),
                              "documentation":
                                  family.get("documentation", "")}
                merged[name] = {}
            ftype = meta[name]["type"]
            bucket = merged[name]
            for sample_name, labels, value in family.get("samples", ()):
                labels = dict(labels)
                if ftype == "gauge" and name not in GAUGE_SUM \
                        and name not in GAUGE_MAX:
                    labels[WORKER_LABEL] = str(wid)
                key = _sample_key(sample_name, labels)
                prior = bucket.get(key)
                if prior is None:
                    bucket[key] = [sample_name, labels, value]
                elif sample_name.endswith("_created"):
                    prior[2] = min(prior[2], value)
                elif ftype == "gauge" and name in GAUGE_MAX:
                    prior[2] = max(prior[2], value)
                else:  # counters, histograms, summaries, GAUGE_SUM
                    prior[2] = prior[2] + value
    out = []
    for name in order:
        family = dict(meta[name])
        samples = sorted(merged[name].values(),
                         key=lambda s: (s[0], sorted(s[1].items())))
        family["samples"] = samples
        out.append(family)
    return out


def _escape_label_value(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _format_value(value: float) -> str:
    # prometheus_client text format: integers render as "1.0".
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    return repr(float(value))


def render_exposition(families: List[dict]) -> bytes:
    """Merged families rendered in the Prometheus text exposition
    format (the merged ``/metrics`` body worker 0 serves)."""
    lines: List[str] = []
    for family in families:
        doc = (family.get("documentation") or "").replace("\\", "\\\\") \
            .replace("\n", "\\n")
        lines.append(f"# HELP {family['name']} {doc}")
        lines.append(f"# TYPE {family['name']} {family.get('type', 'untyped')}")
        for sample_name, labels, value in family["samples"]:
            if labels:
                label_str = ",".join(
                    f'{k}="{_escape_label_value(v)}"'
                    for k, v in sorted(labels.items()))
                lines.append(
                    f"{sample_name}{{{label_str}}} {_format_value(value)}")
            else:
                lines.append(f"{sample_name} {_format_value(value)}")
    return ("\n".join(lines) + "\n").encode("utf-8")


def merge_rings(worker_records: Dict[int, Iterable[dict]],
                time_key: str = "time_unix",
                limit: Optional[int] = None) -> List[dict]:
    """Merge per-worker ring snapshots (each already newest-first) into
    one newest-first list with every record stamped ``worker=<id>``."""
    out: List[dict] = []
    for wid, records in worker_records.items():
        for rec in records or ():
            stamped = dict(rec)
            stamped[WORKER_LABEL] = wid
            out.append(stamped)
    out.sort(key=lambda r: float(r.get(time_key) or 0.0), reverse=True)
    if limit is not None:
        out = out[:max(int(limit), 0)]
    return out


def sum_counts(dicts: Iterable[Optional[Dict[str, float]]]
               ) -> Dict[str, float]:
    """Per-key sum across worker count dicts (SLO outcomes, event kind
    counts); ``None`` entries (store absent on that worker) skipped."""
    out: Dict[str, float] = {}
    for d in dicts:
        for key, value in (d or {}).items():
            out[key] = out.get(key, 0) + value
    return out


def parse_worker_param(raw: Optional[str],
                       worker_ids: Iterable[int]) -> Optional[int]:
    """Validate a ``?worker=`` filter. Returns None when absent, the
    worker id when valid, raises ValueError (the 400 message) otherwise."""
    if raw is None or raw == "":
        return None
    try:
        wid = int(raw)
    except (TypeError, ValueError):
        raise ValueError("worker must be an integer")
    known = sorted(set(worker_ids))
    if wid not in known:
        raise ValueError(f"unknown worker {wid} (workers: {known})")
    return wid


def _canonical(view) -> str:
    return json.dumps(view, sort_keys=True, separators=(",", ":"),
                      default=str)


#: Shared-mutable-state digests compared across workers. Keys must match
#: what ``router/workers.py:local_snapshot`` puts under ``divergence``.
DIVERGENCE_KINDS = ("breaker_view", "trie_digest")


def divergence_report(snaps: List[dict]) -> Dict[str, dict]:
    """Compare each worker's shared-state digests pairwise.

    Divergence here is EXPECTED under ``--router-workers``: breakers
    trip per process, and KV register/admit reports land on whichever
    worker accepted the connection. The report (and the
    ``vllm_router:worker_state_divergence_total`` counter fed from it)
    exists to measure that fragmentation so the future state-service
    split is justified by evidence, not assumption.
    """
    out: Dict[str, dict] = {}
    for kind in DIVERGENCE_KINDS:
        views = {int(s["worker"]): (s.get("divergence") or {}).get(kind)
                 for s in snaps}
        canon = {_canonical(v) for v in views.values()}
        out[kind] = {
            "diverged": len(canon) > 1,
            "views": {str(w): views[w] for w in sorted(views)},
        }
    return out


def merge_worker_snapshots(snaps: List[dict]) -> dict:
    """The ``/debug/workers`` body: topology, per-worker rollups, summed
    outcomes, and the shared-state divergence report."""
    snaps = sorted(snaps, key=lambda s: int(s["worker"]))
    per_worker = []
    for snap in snaps:
        loop = snap.get("loop") or {}
        summary = loop.get("summary") or {}
        lag = summary.get("lag") or {}
        slo = snap.get("slo") or {}
        per_worker.append({
            "worker": int(snap["worker"]),
            "pid": snap.get("pid"),
            "time_unix": snap.get("time_unix"),
            "outcomes": slo.get("counts"),
            "loop_lag_p99_s": lag.get("p99"),
            "loop_lag_window": loop.get("window"),
            "loop_samples_total": summary.get("samples_total"),
            "loop_stall_s": summary.get("stall_s_measured"),
            # Per-component on-loop seconds (streaming_relay vs
            # relay_feed is how the relay A/B proves the byte copy left
            # the loop on each worker, not just in aggregate).
            "loop_components": summary.get("components"),
            "traces_recorded_total":
                (snap.get("traces") or {}).get("recorded_total"),
            "events_recorded_total":
                (snap.get("events") or {}).get("recorded_total"),
        })
    return {
        "workers": [int(s["worker"]) for s in snaps],
        "per_worker": per_worker,
        "outcomes": sum_counts(
            (s.get("slo") or {}).get("counts") for s in snaps),
        "events_kind_counts": sum_counts(
            (s.get("events") or {}).get("kind_counts") for s in snaps),
        "divergence": divergence_report(snaps),
    }
