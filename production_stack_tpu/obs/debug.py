"""``/debug/*`` HTTP surfaces, shared by router, engine, and fake engine.

The whole ``/debug`` tree is privileged (``utils/auth.py``): with a
deployment key configured every surface below requires it — traces leak
request ids, backend URLs, and slow-request timelines; steps leak
workload shape; the loop monitor names source locations of blocking
code.

- ``GET /debug/traces``                 -- newest-first summaries; filters:
  ``?min_duration_s=0.25`` and ``?limit=50``.
- ``GET /debug/traces/{request_id}``    -- full span timeline as JSON;
  ``?format=otlp`` returns the OTLP-JSON resourceSpans shape instead.
- ``GET /debug/steps``                  -- engine-only: newest-first step
  flight-recorder records; filters: ``?limit=50`` and
  ``?kind=decode_burst``.
- ``GET /debug/events``                 -- router-only: the fleet event
  journal, newest-first; filters ``?limit=50`` and
  ``?kind=breaker_open``; ``?format=grafana`` returns the Grafana
  annotations JSON shape for dashboard overlay.
- ``GET /debug/loop``                   -- event-loop health
  (``--loop-monitor``): lag rollups, stall buckets, per-component
  on-loop seconds, and the blocking-call watchdog's top-blockers table;
  ``?blockers=10`` bounds the table.
"""

from __future__ import annotations

from aiohttp import web

from production_stack_tpu.obs.events import EventJournal
from production_stack_tpu.obs.steps import STEP_KINDS, StepRecorder
from production_stack_tpu.obs.trace import TraceRecorder


def add_debug_routes(router, recorder: TraceRecorder) -> None:
    """Attach the trace endpoints to an aiohttp ``UrlDispatcher``."""

    async def list_traces(request: web.Request) -> web.Response:
        try:
            min_duration = float(request.query.get("min_duration_s", 0) or 0)
        except ValueError:
            return web.json_response(
                {"error": "min_duration_s must be a number"}, status=400)
        try:
            limit = int(request.query.get("limit", 100) or 100)
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        return web.json_response({
            "service": recorder.service,
            "capacity": recorder.capacity,
            "recorded_total": recorder.recorded_total,
            "slow_requests": recorder.slow_requests,
            "traces": recorder.list(min_duration_s=min_duration, limit=limit),
        })

    async def get_trace(request: web.Request) -> web.Response:
        trace = recorder.get(request.match_info["request_id"])
        if trace is None:
            return web.json_response({"error": "trace not found"}, status=404)
        if request.query.get("format") == "otlp":
            return web.json_response({"resourceSpans": [trace.to_otlp()]})
        return web.json_response(trace.to_dict())

    router.add_get("/debug/traces", list_traces)
    router.add_get("/debug/traces/{request_id}", get_trace)


def add_step_debug_routes(router, recorder: StepRecorder) -> None:
    """Attach ``GET /debug/steps`` (engine step flight recorder)."""

    async def list_steps(request: web.Request) -> web.Response:
        try:
            limit = int(request.query.get("limit", 100) or 100)
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        if limit < 1:
            return web.json_response(
                {"error": "limit must be >= 1"}, status=400)
        kind = request.query.get("kind") or None
        if kind is not None and kind not in STEP_KINDS:
            return web.json_response(
                {"error": f"unknown kind {kind!r} "
                          f"(one of: {', '.join(STEP_KINDS)})"},
                status=400)
        out = recorder.summary()
        out["steps"] = recorder.snapshot(limit=limit, kind=kind)
        return web.json_response(out)

    router.add_get("/debug/steps", list_steps)


def add_event_debug_routes(router, journal: EventJournal) -> None:
    """Attach ``GET /debug/events`` (fleet event journal)."""

    async def list_events(request: web.Request) -> web.Response:
        try:
            limit = int(request.query.get("limit", 100) or 100)
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        if limit < 1:
            return web.json_response(
                {"error": "limit must be >= 1"}, status=400)
        kind = request.query.get("kind") or None
        if request.query.get("format") == "grafana":
            return web.json_response(
                journal.to_grafana(limit=limit, kind=kind))
        out = journal.summary()
        out["events"] = journal.snapshot(limit=limit, kind=kind)
        return web.json_response(out)

    router.add_get("/debug/events", list_events)


def add_loop_debug_routes(router, monitor) -> None:
    """Attach ``GET /debug/loop`` (event-loop health; ``LoopMonitor``)."""

    async def loop_health(request: web.Request) -> web.Response:
        try:
            blockers = int(request.query.get("blockers", 10) or 10)
        except ValueError:
            return web.json_response(
                {"error": "blockers must be an integer"}, status=400)
        if blockers < 1:
            return web.json_response(
                {"error": "blockers must be >= 1"}, status=400)
        out = monitor.summary()
        out["top_blockers"] = monitor.detector.top_blockers(
            limit=blockers)
        return web.json_response(out)

    router.add_get("/debug/loop", loop_health)
