"""``/debug/*`` HTTP surfaces, shared by router, engine, and fake engine.

The whole ``/debug`` tree is privileged (``utils/auth.py``): with a
deployment key configured every surface below requires it — traces leak
request ids, backend URLs, and slow-request timelines; steps leak
workload shape; the loop monitor names source locations of blocking
code.

- ``GET /debug/traces``                 -- newest-first summaries; filters:
  ``?min_duration_s=0.25`` and ``?limit=50``.
- ``GET /debug/traces/{request_id}``    -- full span timeline as JSON;
  ``?format=otlp`` returns the OTLP-JSON resourceSpans shape instead.
- ``GET /debug/steps``                  -- engine-only: newest-first step
  flight-recorder records; filters: ``?limit=50`` and
  ``?kind=decode_burst``.
- ``GET /debug/events``                 -- router-only: the fleet event
  journal, newest-first; filters ``?limit=50`` and
  ``?kind=breaker_open``; ``?format=grafana`` returns the Grafana
  annotations JSON shape for dashboard overlay.
- ``GET /debug/loop``                   -- event-loop health
  (``--loop-monitor``): lag rollups, stall buckets, per-component
  on-loop seconds, and the blocking-call watchdog's top-blockers table;
  ``?blockers=10`` bounds the table.
- ``GET /debug/kv/economics``           -- router-only (``--fleet-cache``):
  the pull ledger's win/loss summary, the crossover advisor's
  recommended ``--fleet-min-match-chars``, and newest-first pull
  records; ``?limit=50`` bounds the record list.
- ``GET /debug/kv/trie``                -- router-only: KV controller trie
  introspection — per-instance claim counts, depth distribution,
  approximate memory footprint, hottest prefixes by reuse count;
  ``?top=10`` bounds the hottest-prefix table.
"""

from __future__ import annotations

from aiohttp import web

from production_stack_tpu.obs.events import EventJournal
from production_stack_tpu.obs.steps import STEP_KINDS, StepRecorder
from production_stack_tpu.obs.trace import TraceRecorder


def add_debug_routes(router, recorder: TraceRecorder) -> None:
    """Attach the trace endpoints to an aiohttp ``UrlDispatcher``."""

    async def list_traces(request: web.Request) -> web.Response:
        try:
            min_duration = float(request.query.get("min_duration_s", 0) or 0)
        except ValueError:
            return web.json_response(
                {"error": "min_duration_s must be a number"}, status=400)
        try:
            limit = int(request.query.get("limit", 100) or 100)
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        return web.json_response({
            "service": recorder.service,
            "capacity": recorder.capacity,
            "recorded_total": recorder.recorded_total,
            "slow_requests": recorder.slow_requests,
            "traces": recorder.list(min_duration_s=min_duration, limit=limit),
        })

    async def get_trace(request: web.Request) -> web.Response:
        trace = recorder.get(request.match_info["request_id"])
        if trace is None:
            return web.json_response({"error": "trace not found"}, status=404)
        if request.query.get("format") == "otlp":
            return web.json_response({"resourceSpans": [trace.to_otlp()]})
        return web.json_response(trace.to_dict())

    router.add_get("/debug/traces", list_traces)
    router.add_get("/debug/traces/{request_id}", get_trace)


def add_step_debug_routes(router, recorder: StepRecorder,
                          extra_stats=None) -> None:
    """Attach ``GET /debug/steps`` (engine step flight recorder).

    ``extra_stats``: optional zero-arg callable returning a dict merged
    into the summary — the engine folds its resident/offload KV
    page-occupancy breakdown in here."""

    async def list_steps(request: web.Request) -> web.Response:
        try:
            limit = int(request.query.get("limit", 100) or 100)
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        if limit < 1:
            return web.json_response(
                {"error": "limit must be >= 1"}, status=400)
        kind = request.query.get("kind") or None
        if kind is not None and kind not in STEP_KINDS:
            return web.json_response(
                {"error": f"unknown kind {kind!r} "
                          f"(one of: {', '.join(STEP_KINDS)})"},
                status=400)
        out = recorder.summary()
        if extra_stats is not None:
            out.update(extra_stats())
        out["steps"] = recorder.snapshot(limit=limit, kind=kind)
        return web.json_response(out)

    router.add_get("/debug/steps", list_steps)


def add_event_debug_routes(router, journal: EventJournal) -> None:
    """Attach ``GET /debug/events`` (fleet event journal)."""

    async def list_events(request: web.Request) -> web.Response:
        try:
            limit = int(request.query.get("limit", 100) or 100)
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        if limit < 1:
            return web.json_response(
                {"error": "limit must be >= 1"}, status=400)
        kind = request.query.get("kind") or None
        if request.query.get("format") == "grafana":
            return web.json_response(
                journal.to_grafana(limit=limit, kind=kind))
        out = journal.summary()
        out["events"] = journal.snapshot(limit=limit, kind=kind)
        return web.json_response(out)

    router.add_get("/debug/events", list_events)


def add_kv_economics_debug_routes(router, fleet) -> None:
    """Attach ``GET /debug/kv/economics`` (fleet pull ledger + crossover
    advisor; router-only, registered only with ``--fleet-cache`` on —
    same convention as the engine-only ``/debug/steps``)."""

    async def economics(request: web.Request) -> web.Response:
        try:
            limit = int(request.query.get("limit", 100) or 100)
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        if limit < 1:
            return web.json_response(
                {"error": "limit must be >= 1"}, status=400)
        ledger = fleet.ledger
        out = ledger.summary()
        out["advisor"] = ledger.advise(
            current_min_match_chars=fleet.config.min_match_chars)
        out["auto_min_match"] = {
            "enabled": fleet.config.auto_min_match,
            "interval_s": fleet.config.auto_min_match_interval_s,
            "damping": fleet.config.auto_min_match_damping,
            "applied": fleet.auto_min_match_applied,
            "last": fleet.auto_min_match_last,
        }
        out["records"] = ledger.snapshot(limit=limit)
        return web.json_response(out)

    router.add_get("/debug/kv/economics", economics)


def add_kv_trie_debug_routes(router, controller) -> None:
    """Attach ``GET /debug/kv/trie`` (KV controller trie introspection)."""

    async def trie(request: web.Request) -> web.Response:
        try:
            top = int(request.query.get("top", 10) or 10)
        except ValueError:
            return web.json_response(
                {"error": "top must be an integer"}, status=400)
        if top < 1:
            return web.json_response(
                {"error": "top must be >= 1"}, status=400)
        return web.json_response(await controller.trie_snapshot(top=top))

    router.add_get("/debug/kv/trie", trie)


def add_loop_debug_routes(router, monitor) -> None:
    """Attach ``GET /debug/loop`` (event-loop health; ``LoopMonitor``)."""

    async def loop_health(request: web.Request) -> web.Response:
        try:
            blockers = int(request.query.get("blockers", 10) or 10)
        except ValueError:
            return web.json_response(
                {"error": "blockers must be an integer"}, status=400)
        if blockers < 1:
            return web.json_response(
                {"error": "blockers must be >= 1"}, status=400)
        out = monitor.summary()
        out["top_blockers"] = monitor.detector.top_blockers(
            limit=blockers)
        return web.json_response(out)

    router.add_get("/debug/loop", loop_health)
