"""Dependency-free request tracing (router + engine).

``trace``      -- W3C trace-context propagation, spans, the flight-recorder
                  ring buffer, OTLP-JSON export, slow-trace logging.
``debug``      -- aiohttp ``/debug/traces`` handlers shared by the router,
                  the engine server, and the fake engine.
"""

from production_stack_tpu.obs.trace import (  # noqa: F401
    RequestTrace,
    Span,
    StageClock,
    TraceRecorder,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    trace_id_from_request_id,
)
