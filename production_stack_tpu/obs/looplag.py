"""Event-loop introspection: lag monitor, blocking-call detector, and
on-loop component attribution.

The router (and each engine server) is a single asyncio event loop;
when that loop stalls, every in-flight request pays the delay at once.
This module measures the three things needed to turn "the router is the
ceiling" into attributed evidence:

``LoopMonitor``
    A self-rescheduling ``loop.call_later`` tick that measures
    scheduling delay (how late the tick fired versus when it asked to
    run) into a bounded ring with p50/p99/max rollups, plus severity-
    bucketed stall counters (multiples of the stall threshold).

``BlockingCallDetector``
    A daemon watchdog thread that notices when the loop hasn't ticked
    for the stall threshold, samples the loop thread's stack via
    ``sys._current_frames()``, and aggregates offending frames into a
    top-blockers table (stall counts + cumulative stall seconds keyed
    by ``file:line:func``) — executor-worthy work hiding on the loop is
    named, not guessed.

``LoopComponentTimers``
    On-loop CPU-seconds per named component. ``wrap()`` drives a
    coroutine resume-by-resume, timing only the synchronous slices that
    actually hold the loop (awaited off-loop time is excluded);
    ``measure()`` covers plain synchronous sections.

Everything here is stdlib-only and hermetic: ``observe()`` and
``sample()`` accept explicit ``now`` values so tests can replay
synthetic stalls without a live loop. Metric export lives with each
server's scrape path (``router/metrics.py`` mirrors into the prometheus
registry; ``engine/server.py`` emits hand-rolled ``tpu:`` lines), and
``GET /debug/loop`` (privileged) serves the same rollups plus the
top-blockers table.
"""

from __future__ import annotations

import sys
import threading
import time
import types
from collections import deque
from typing import Any, Callable, Dict, List, Optional

#: Stall severity buckets: (label, multiple of the stall threshold).
#: Each stall increments exactly one bucket — the highest it reaches —
#: so the buckets are disjoint and their sum is the total stall count.
STALL_BUCKETS = (("1x", 1.0), ("5x", 5.0), ("20x", 20.0))

#: Default stall threshold: a callback holding the loop for 100 ms is
#: already ~100 concurrent requests' worth of added latency.
DEFAULT_STALL_THRESHOLD_S = 0.1

#: Default tick interval. Lag resolution is one interval; 50 ms keeps
#: the tick itself invisible in profiles (20 wakeups/s).
DEFAULT_TICK_INTERVAL_S = 0.05

#: Router components the attribution shim knows about. Shims are
#: installed by the router wiring; the tuple exists so the metrics
#: surface and docs agree on the label set.
ROUTER_COMPONENTS = (
    "qos_admission",
    "fleet_pull",
    "kv_controller",
    "streaming_relay",
    "relay_feed",
    "slo_classify",
    "metrics_scrape",
)

#: Attribution key used when the watchdog cannot resolve the loop
#: thread's frame (thread not yet registered, or already exited).
UNATTRIBUTED = "unattributed"


def _frame_location(frame) -> str:
    """``file:line:func`` with the filename shortened to its last two
    path components (enough to disambiguate, short enough to label)."""
    code = frame.f_code
    parts = code.co_filename.replace("\\", "/").split("/")
    short = "/".join(parts[-2:])
    return f"{short}:{frame.f_lineno}:{code.co_name}"


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class LoopComponentTimers:
    """Cumulative on-loop CPU-seconds per named component."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seconds: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}

    def add(self, component: str, seconds: float) -> None:
        with self._lock:
            self._seconds[component] = (
                self._seconds.get(component, 0.0) + seconds)
            self._calls[component] = self._calls.get(component, 0) + 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._seconds)

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {
                comp: {
                    "seconds": round(self._seconds[comp], 6),
                    "calls": self._calls.get(comp, 0),
                }
                for comp in sorted(self._seconds)
            }

    def measure(self, component: str):
        """Context manager timing a synchronous on-loop section."""
        return _MeasureCtx(self, component)

    def wrap(self, component: str, coro):
        """Awaitable wrapper measuring ``coro``'s on-loop time.

        Drives the coroutine resume-by-resume: each ``send``/``throw``
        runs synchronously on the event loop, so the sum of those
        slices is exactly the CPU time the component held the loop.
        Time parked on an await (the ``yield`` back to the loop) is not
        counted. The total is recorded once, when the coroutine
        finishes, errors, or is cancelled.
        """
        return _drive(coro, lambda s: self.add(component, s))


class _MeasureCtx:
    __slots__ = ("_timers", "_component", "_t0")

    def __init__(self, timers: LoopComponentTimers, component: str):
        self._timers = timers
        self._component = component

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc_info):
        self._timers.add(self._component,
                         time.perf_counter() - self._t0)
        return False


@types.coroutine
def _drive(coro, record: Callable[[float], None]):
    """Generator-coroutine that forwards every resume into ``coro``
    while timing only the synchronous slices (see ``wrap``)."""
    total = 0.0
    value: Any = None
    exc: Optional[BaseException] = None
    try:
        while True:
            t0 = time.perf_counter()
            try:
                if exc is not None:
                    pending, exc = exc, None
                    yielded = coro.throw(pending)
                else:
                    yielded = coro.send(value)
            except StopIteration as stop:
                total += time.perf_counter() - t0
                return stop.value
            except BaseException:
                total += time.perf_counter() - t0
                raise
            total += time.perf_counter() - t0
            value = None
            try:
                value = yield yielded
            except BaseException as caught:  # incl. CancelledError
                exc = caught
    finally:
        record(total)


class BlockingCallDetector(threading.Thread):
    """Watchdog thread attributing loop stalls to the blocking frame.

    Polls at a fraction of the stall threshold; whenever the monitored
    loop hasn't ticked for at least the threshold it samples the loop
    thread's current stack and charges the elapsed stall time to the
    innermost frame's ``file:line:func``. Attribution uses a watermark
    (``now - max(last_tick, previous_poll)``) so cumulative attributed
    seconds track the full stall duration even when the watchdog
    itself is scheduled late under load.
    """

    def __init__(self, monitor: "LoopMonitor",
                 poll_s: Optional[float] = None):
        super().__init__(daemon=True,
                         name=f"loop-watchdog-{monitor.service}")
        self.monitor = monitor
        self.poll_s = (poll_s if poll_s is not None
                       else max(0.01, monitor.stall_threshold_s / 4.0))
        self._stop_event = threading.Event()
        self._lock = threading.Lock()
        # key -> {"stalls": int, "samples": int, "stall_s": float,
        #         "stack": [..]}; "stalls" counts distinct stall
        # episodes in which this frame was sampled.
        self._blockers: Dict[str, dict] = {}
        self._stalled = False
        self._stall_keys: set = set()
        self._watermark: Optional[float] = None
        self._charge_floor = 0.0
        self.samples_total = 0
        self.stall_s_attributed = 0.0
        self.stall_s_unattributed = 0.0

    def mark_boundary(self, now: Optional[float] = None) -> None:
        """Clamp attribution at a measurement-window boundary. A stall
        that straddles the boundary otherwise charges its pre-boundary
        seconds into the new window's delta, which is how the r13
        artifact recorded a per-rung ``loop_stall_attribution`` of 1.37
        (> 1.0): the harness snapshots blocker/stall counters at rung
        start, but the first in-rung poll charged time reaching back to
        a tick *before* the snapshot. Callers (e.g. the saturation
        harness at each rung boundary) invoke this right where they
        snapshot, and no in-window charge will predate it."""
        self._charge_floor = time.monotonic() if now is None else now

    def run(self) -> None:
        while not self._stop_event.wait(self.poll_s):
            try:
                self.sample()
            except Exception:  # pragma: no cover - never kill watchdog
                pass

    def stop(self) -> None:
        self._stop_event.set()

    def sample(self, now: Optional[float] = None,
               frame: Any = None) -> bool:
        """One watchdog pass. Public (with explicit ``now``/``frame``)
        so tests can replay stalls deterministically. Returns whether a
        stall was observed."""
        mon = self.monitor
        last = mon.last_tick()
        if last is None:
            return False
        if now is None:
            now = time.monotonic()
        if (now - last) < mon.stall_threshold_s:
            self._stalled = False
            self._stall_keys.clear()
            self._watermark = None
            return False
        new_stall = not self._stalled
        self._stalled = True
        if frame is None:
            frames = sys._current_frames()
            frame = (frames.get(mon.loop_thread_id)
                     if mon.loop_thread_id is not None else None)
        if frame is None:
            key, stack = UNATTRIBUTED, []
        else:
            key = _frame_location(frame)
            stack = []
            walker = frame
            while walker is not None and len(stack) < 8:
                stack.append(_frame_location(walker))
                walker = walker.f_back
            walker = None
        # Charge the elapsed stall time since the last attribution
        # point: the tick that started the stall on the first poll, the
        # previous poll afterwards.
        floor = last if self._watermark is None else self._watermark
        floor = max(floor, self._charge_floor)
        charged = max(0.0, now - max(last, floor))
        self._watermark = now
        with self._lock:
            self.samples_total += 1
            rec = self._blockers.setdefault(
                key, {"stalls": 0, "samples": 0, "stall_s": 0.0,
                      "stack": []})
            if new_stall or key not in self._stall_keys:
                rec["stalls"] += 1
                self._stall_keys.add(key)
            if new_stall:
                self._stall_keys = {key}
            rec["samples"] += 1
            rec["stall_s"] += charged
            rec["stack"] = stack
            if key == UNATTRIBUTED:
                self.stall_s_unattributed += charged
            else:
                self.stall_s_attributed += charged
        frame = None
        return True

    def top_blockers(self, limit: int = 10) -> List[dict]:
        """Blocker table sorted by cumulative stall seconds, worst
        first."""
        with self._lock:
            items = [
                {"frame": key,
                 "stalls": rec["stalls"],
                 "samples": rec["samples"],
                 "stall_s": round(rec["stall_s"], 6),
                 "stack": list(rec["stack"])}
                for key, rec in self._blockers.items()
            ]
        items.sort(key=lambda r: r["stall_s"], reverse=True)
        return items[:limit]

    def blocker_snapshot(self) -> Dict[str, dict]:
        """Cheap copy of per-key counters (no stacks) for delta
        computation across a measurement window."""
        with self._lock:
            return {key: {"stalls": rec["stalls"],
                          "stall_s": rec["stall_s"]}
                    for key, rec in self._blockers.items()}


class LoopMonitor:
    """Event-loop lag monitor (tick + ring + rollups) and facade over
    the watchdog and component timers.

    ``start()`` must be called on the loop being monitored (it captures
    the loop and its thread id); ``stop()`` is idempotent.
    """

    def __init__(self, service: str, *,
                 stall_threshold_s: float = DEFAULT_STALL_THRESHOLD_S,
                 interval_s: Optional[float] = None,
                 capacity: int = 4096,
                 watchdog_poll_s: Optional[float] = None):
        if stall_threshold_s <= 0:
            raise ValueError("stall_threshold_s must be positive")
        self.service = service
        self.stall_threshold_s = float(stall_threshold_s)
        self.interval_s = (float(interval_s) if interval_s is not None
                           else min(DEFAULT_TICK_INTERVAL_S,
                                    self.stall_threshold_s / 2.0))
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)  # (seq, t, lag)
        self.samples_total = 0
        self.lag_s_sum = 0.0
        self.stall_s_sum = 0.0
        self.stall_counts: Dict[str, int] = {
            label: 0 for label, _ in STALL_BUCKETS}
        self.components = LoopComponentTimers()
        self.detector = BlockingCallDetector(
            self, poll_s=watchdog_poll_s)
        self.loop_thread_id: Optional[int] = None
        self._loop = None
        self._handle = None
        self._last_tick: Optional[float] = None
        self._expected: Optional[float] = None
        self._started = False

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        """Begin ticking on the running loop and start the watchdog."""
        import asyncio

        if self._started:
            return
        self._loop = asyncio.get_running_loop()
        self.loop_thread_id = threading.get_ident()
        self._started = True
        now = time.monotonic()
        self._last_tick = now
        self._expected = now + self.interval_s
        self._handle = self._loop.call_later(self.interval_s, self._tick)
        self.detector.start()

    def stop(self) -> None:
        self._started = False
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        self.detector.stop()
        if self.detector.is_alive():
            self.detector.join(timeout=1.0)

    def _tick(self) -> None:
        now = time.monotonic()
        self.observe(max(0.0, now - self._expected), now=now)
        self._last_tick = now
        if self._started:
            self._expected = now + self.interval_s
            self._handle = self._loop.call_later(
                self.interval_s, self._tick)

    # -- recording / queries ------------------------------------------

    def observe(self, lag_s: float,
                now: Optional[float] = None) -> None:
        """Record one lag sample (public for synthetic-stall tests)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            self.samples_total += 1
            self.lag_s_sum += lag_s
            self._ring.append((self.samples_total, now, lag_s))
            if lag_s >= self.stall_threshold_s:
                self.stall_s_sum += lag_s
                label = STALL_BUCKETS[0][0]
                for name, mult in STALL_BUCKETS:
                    if lag_s >= self.stall_threshold_s * mult:
                        label = name
                self.stall_counts[label] += 1

    def last_tick(self) -> Optional[float]:
        return self._last_tick

    def seq(self) -> int:
        """Sequence number of the newest sample (monotonic; use as the
        ``since_seq`` marker for windowed percentiles)."""
        return self.samples_total

    def percentiles(self, since_seq: int = 0,
                    window_s: Optional[float] = None,
                    now: Optional[float] = None) -> dict:
        """p50/p99/max over ring samples newer than ``since_seq`` and,
        when ``window_s`` is given, no older than that many seconds."""
        with self._lock:
            entries = list(self._ring)
        if window_s is not None:
            if now is None:
                now = time.monotonic()
            cutoff = now - window_s
            entries = [e for e in entries if e[1] >= cutoff]
        if since_seq:
            entries = [e for e in entries if e[0] > since_seq]
        lags = sorted(e[2] for e in entries)
        return {
            "count": len(lags),
            "p50": round(_percentile(lags, 0.50), 6),
            "p99": round(_percentile(lags, 0.99), 6),
            "max": round(lags[-1], 6) if lags else 0.0,
        }

    def stalls(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.stall_counts)

    def summary(self, now: Optional[float] = None) -> dict:
        """One-call rollup of everything (served at /debug/loop)."""
        pct = self.percentiles(now=now)
        with self._lock:
            samples = self.samples_total
            lag_sum = self.lag_s_sum
            stall_s = self.stall_s_sum
            stalls = dict(self.stall_counts)
        det = self.detector
        return {
            "service": self.service,
            "interval_s": self.interval_s,
            "stall_threshold_s": self.stall_threshold_s,
            "capacity": self.capacity,
            "samples_total": samples,
            "lag_s_sum": round(lag_sum, 6),
            "lag": pct,
            "stalls": stalls,
            "stall_s_measured": round(stall_s, 6),
            "stall_s_attributed": round(det.stall_s_attributed, 6),
            "stall_s_unattributed": round(det.stall_s_unattributed, 6),
            "watchdog_poll_s": det.poll_s,
            "watchdog_samples": det.samples_total,
            "components": self.components.stats(),
        }

    def fed_snapshot(self, lag_window_s: Optional[float] = None,
                     blockers: int = 10) -> dict:
        """Worker-local state for the federation plane. ``lag_window_s``
        adds a windowed percentile rollup (the saturation harness reads
        per-worker lag p99 over exactly one rung's elapsed time)."""
        out = {
            "summary": self.summary(),
            "top_blockers": self.detector.top_blockers(limit=blockers),
        }
        if lag_window_s is not None:
            out["window"] = dict(
                self.percentiles(window_s=float(lag_window_s)),
                window_s=float(lag_window_s))
        return out
