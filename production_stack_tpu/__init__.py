"""production-stack-tpu: a TPU-native LLM serving stack.

A ground-up rebuild of the capabilities of vLLM Production Stack
(reference: /root/reference) designed TPU-first:

- ``engine/``   -- a JAX/XLA/Pallas OpenAI-compatible serving engine with a
  paged KV cache in TPU HBM, continuous batching, and pjit/shard_map
  parallelism over a ``jax.sharding.Mesh`` (the part the reference outsources
  to vLLM container images).
- ``models/``   -- functional JAX model definitions (Llama, OPT, Mixtral).
- ``ops/``      -- Pallas TPU kernels (paged attention, flash attention) with
  pure-XLA fallbacks for CPU test meshes.
- ``parallel/`` -- mesh construction, sharding rules (dp/tp/pp/sp/ep), ring
  attention, and the KV transfer fabric (ICI/DCN) replacing NIXL/UCX.
- ``router/``   -- the OpenAI-compatible request router: service discovery,
  session/prefix/KV-aware routing, disaggregated prefill two-phase flow,
  stats, /metrics (mirrors reference src/vllm_router/).
- ``kv/``       -- KV offload (HBM -> host), standalone cache server and the
  KV controller used for kv-aware routing (the LMCache-equivalent layer).
"""

__version__ = "0.1.0"
