"""Hermetic LoRA adapter-plane A/B: affinity pinning ON vs OFF.

The physics, with no TPU and no model: three :class:`FakeEngine`
replicas each hold ``max_loras - 1 = 2`` adapter slots while the
workload addresses **four** adapters plus the base model — the fleet
can hold every adapter somewhere, but no replica can hold them all.
Adapter loads cost ``lora_load_delay_s`` of wall time (the simulated
weight fetch), paid on the request path by whichever request triggers
the on-demand load.

- **affinity_on** leg: the router runs ``--lora-plane`` with affinity
  pinning (the default). After a one-time ``POST /lora/load`` prime,
  every adapter request routes to the replica already holding its
  adapter: the load delay is paid once per adapter, the hit rate is
  ~1.0, and adapter TTFT stays at the engine's base TTFT.
- **affinity_off** leg: same plane, ``--lora-no-affinity``. Round-robin
  scatters each adapter across all three replicas, demanding 4x3 = 12
  resident slots from a fleet with 6 — every round re-loads adapters
  through the LRU-evict path, so loads and evictions churn and the
  load delay lands on p99 TTFT.

Both legs must complete every request (misses degrade to an on-demand
load, never an error); the A/B quantifies hit rate and p99 TTFT.

Used by ``bench.py`` (BENCH_LORA=1) and ``tests/test_lora_plane.py``.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from production_stack_tpu.testing.fleet_ab import _start
from production_stack_tpu.testing.qos_ab import (
    _p99,
    _reset_router_singletons,
)

BASE_MODEL = "lora-base"


def _adapter_name(i: int) -> str:
    return f"sql-expert-{i}"


def _adapter_prompt(i: int, chars: int = 600) -> str:
    """Per-adapter repeat prompt (each adapter's tenant re-sends its own
    context, the usual multi-tenant shape)."""
    return (f"adapter-{i:02d} tenant corpus, schema table_{i} columns. "
            * 32)[:chars]


async def _ttft_request(session, router_url: str, model: str, prompt: str,
                        timeout_s: float = 30.0) -> Optional[float]:
    """One streamed chat completion; returns TTFT (first content chunk)
    on a complete stream, None on any failure."""
    import aiohttp

    t0 = time.perf_counter()
    try:
        async with session.post(
            router_url + "/v1/chat/completions",
            json={"model": model, "max_tokens": 2, "stream": True,
                  "messages": [{"role": "user", "content": prompt}]},
            timeout=aiohttp.ClientTimeout(total=timeout_s),
        ) as resp:
            if resp.status != 200:
                return None
            ttft = None
            done = False
            async for line in resp.content:
                stripped = line.strip()
                if stripped == b"data: [DONE]":
                    done = True
                elif ttft is None and stripped.startswith(b"data:"):
                    ttft = time.perf_counter() - t0
            return ttft if done else None
    except (aiohttp.ClientError, asyncio.TimeoutError):
        return None


async def _run_leg(*, affinity: bool, adapters: int, rounds: int,
                   per_adapter: int, concurrency: int, engine_ttft: float,
                   load_delay_s: float, replicas: int,
                   max_loras: int) -> dict:
    import aiohttp

    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import build_parser
    from production_stack_tpu.testing.fake_engine import (
        FakeEngine,
        run_fake_engine,
    )

    _reset_router_singletons()
    engines = [FakeEngine(model=BASE_MODEL, ttft=engine_ttft,
                          max_tokens_default=2, max_loras=max_loras)
               for _ in range(replicas)]
    for e in engines:
        e.lora_load_delay_s = load_delay_s
    runners = [await run_fake_engine(e, "127.0.0.1", 0) for e in engines]
    urls = [e.self_url for e in engines]

    args = build_parser().parse_args([])
    args.static_backends = ",".join(urls)
    args.static_models = ",".join([BASE_MODEL] * replicas)
    # Round-robin on purpose: it maximizes adapter requests landing off
    # the resident replica, which is exactly what affinity pinning fixes.
    args.routing_logic = "roundrobin"
    args.engine_stats_interval = 60
    args.lora_plane = True
    args.lora_no_affinity = not affinity
    router_app = build_app(args)
    router_runner, router_url = await _start(router_app)

    names = [_adapter_name(i) for i in range(adapters)]
    prompts = {name: _adapter_prompt(i) for i, name in enumerate(names)}
    adapter_ttfts: List[float] = []
    base_ttfts: List[float] = []
    failed = 0
    sem = asyncio.Semaphore(concurrency)

    async def one(session, model: str, prompt: str, bucket: List[float]):
        nonlocal failed
        async with sem:
            ttft = await _ttft_request(session, router_url, model, prompt)
            if ttft is None:
                failed += 1
            else:
                bucket.append(ttft)

    debug: dict = {}
    try:
        async with aiohttp.ClientSession() as session:
            # Prime: distribute every adapter to one replica through the
            # router's fan-out (the helm post-install hook does the same
            # against the engines directly). Barrier before traffic so
            # both legs start from identical residency.
            for name in names:
                async with session.post(
                    router_url + "/lora/load",
                    json={"lora_name": name, "replicas": 1},
                    timeout=aiohttp.ClientTimeout(total=30),
                ) as resp:
                    body = await resp.json()
                    if resp.status != 200 or not body.get("loaded"):
                        raise RuntimeError(
                            f"prime load of {name!r} failed: {body}")
            for _ in range(rounds):
                tasks = []
                for name in names:
                    tasks.extend(
                        one(session, name, prompts[name], adapter_ttfts)
                        for _ in range(per_adapter))
                tasks.extend(
                    one(session, BASE_MODEL,
                        "base workload prompt, shared by every tenant.",
                        base_ttfts)
                    for _ in range(per_adapter))
                await asyncio.gather(*tasks)
            async with session.get(
                router_url + "/debug/lora",
                timeout=aiohttp.ClientTimeout(total=10),
            ) as resp:
                debug = await resp.json() if resp.status == 200 else {}
    finally:
        await router_runner.cleanup()
        for runner in runners:
            await runner.cleanup()
        _reset_router_singletons()

    counters = debug.get("counters", {})
    hits = counters.get("affinity_hits", 0)
    misses = counters.get("affinity_misses", 0)
    adapter_sorted = sorted(adapter_ttfts)
    per_engine: Dict[str, int] = {}
    for e in engines:
        for name, n in e.lora_request_counts.items():
            per_engine[name] = per_engine.get(name, 0) + n
    return {
        "affinity": affinity,
        "adapters": adapters,
        "rounds": rounds,
        "per_adapter": per_adapter,
        "completed": len(adapter_ttfts) + len(base_ttfts),
        "failed": failed,
        "adapter_ttft_p50_s": round(
            adapter_sorted[len(adapter_sorted) // 2], 4)
        if adapter_sorted else None,
        "adapter_ttft_p99_s": round(_p99(adapter_ttfts), 4)
        if adapter_ttfts else None,
        "base_ttft_p99_s": round(_p99(base_ttfts), 4)
        if base_ttfts else None,
        "affinity_hits": hits,
        "affinity_misses": misses,
        "affinity_hit_rate": round(hits / (hits + misses), 4)
        if (hits + misses) else None,
        "router_loads": counters.get("loads", 0),
        "router_evictions": counters.get("evictions", 0),
        "engine_loads": sum(e.lora_loads for e in engines),
        "engine_unloads": sum(e.lora_unloads for e in engines),
        "adapter_requests_by_engine": per_engine,
    }


async def run_lora_ab(*, adapters: int = 4, rounds: int = 3,
                      per_adapter: int = 3, concurrency: int = 8,
                      engine_ttft: float = 0.02,
                      load_delay_s: float = 0.15,
                      replicas: int = 3, max_loras: int = 3,
                      skip_off: bool = False) -> dict:
    """Run the affinity-on leg then the affinity-off baseline; A/B dict.

    ``skip_off`` runs only the ON leg (tier-1 test uses it — the OFF
    leg exists to quantify the pinning win, not to gate correctness)."""
    on = await _run_leg(
        affinity=True, adapters=adapters, rounds=rounds,
        per_adapter=per_adapter, concurrency=concurrency,
        engine_ttft=engine_ttft, load_delay_s=load_delay_s,
        replicas=replicas, max_loras=max_loras)
    off = None
    if not skip_off:
        off = await _run_leg(
            affinity=False, adapters=adapters, rounds=rounds,
            per_adapter=per_adapter, concurrency=concurrency,
            engine_ttft=engine_ttft, load_delay_s=load_delay_s,
            replicas=replicas, max_loras=max_loras)
    speedup = None
    if (off and on["adapter_ttft_p99_s"] and off["adapter_ttft_p99_s"]
            and on["adapter_ttft_p99_s"] > 0):
        speedup = round(
            off["adapter_ttft_p99_s"] / on["adapter_ttft_p99_s"], 2)
    return {
        "metric": "lora_affinity_ab",
        "unit": "adapter_p99_ttft_speedup",
        "value": speedup,
        "adapters": adapters,
        "rounds": rounds,
        "per_adapter": per_adapter,
        "concurrency": concurrency,
        "engine_ttft_s": engine_ttft,
        "load_delay_s": load_delay_s,
        "replicas": replicas,
        "max_loras": max_loras,
        "affinity_on": on,
        "affinity_off": off,
    }
