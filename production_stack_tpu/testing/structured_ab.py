"""Structured-output conformance + overhead harness.

Two hermetic measurements, both CPU-only:

- **Corpus conformance** (``run_corpus_conformance``): every case of the
  30-case corpus (``structured/corpus.json``) is sent through the REAL
  router to :class:`FakeEngine` replicas — once over the vLLM guided
  surface (``guided_json`` / ``guided_regex``) and once over the OpenAI
  ``response_format`` surface — and the returned content must fullmatch
  the case's compiled automaton (plus :func:`validate_instance` for
  schema cases). An uncompilable schema must come back 400. The fake
  engine compiles constraints with the production compiler, so this
  exercises the same parse/compile/400 path the engine server runs.

- **Mask overhead A/B** (``run_engine_overhead``): the real
  :class:`EngineCore` on CPU decodes the same greedy traffic twice —
  unconstrained, then constrained by a NON-BINDING regex (``(.|\\s)*``,
  which allows every token) — so the legs emit identical tokens and the
  delta is pure structured-path cost: packed-mask H2D input, host FSM
  advance per emitted token, and mask-row fills. Both legs run
  ``decode_steps=1`` because structured rows are scheduled one step per
  burst (the host must observe each token before shipping the next
  mask); pinning the plain leg to the same burst width isolates mask
  cost from scheduling width.

Used by ``bench.py`` (``BENCH_STRUCTURED=1`` ->
``BENCH_STRUCTURED_r10.json``) and ``tests/test_structured_output.py``.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import List, Optional

from production_stack_tpu.structured.api import compile_char_dfa
from production_stack_tpu.structured.corpus import (
    case_request_fields, case_spec, load_corpus)
from production_stack_tpu.structured.schema import validate_instance
from production_stack_tpu.testing.qos_ab import _reset_router_singletons

MODEL = "structured-model"

# Allows every token (``.`` = any non-newline byte, ``\s`` the rest):
# masking stays ON — rows are computed, shipped, and advanced — but the
# constraint never changes what greedy decoding picks.
NON_BINDING_REGEX = r"(.|\s)*"


async def _start(app):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def _chat(session, router_url: str, fields: dict,
                timeout_s: float = 30.0):
    """POST one non-streamed chat completion; (status, content)."""
    import aiohttp

    body = {"model": MODEL, "max_tokens": 64, "stream": False,
            "messages": [{"role": "user", "content": "emit the value"}]}
    body.update(fields)
    async with session.post(
        router_url + "/v1/chat/completions", json=body,
        timeout=aiohttp.ClientTimeout(total=timeout_s),
    ) as resp:
        if resp.status != 200:
            return resp.status, None
        payload = await resp.json()
        return 200, payload["choices"][0]["message"]["content"]


async def run_corpus_conformance(surface: str = "guided",
                                 engines: int = 2) -> dict:
    """Replay the corpus through router -> fake engines; per-case
    automaton fullmatch (+ schema validation) on the returned content."""
    import aiohttp

    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import build_parser
    from production_stack_tpu.testing.fake_engine import (
        FakeEngine, run_fake_engine)

    _reset_router_singletons()
    fakes = [FakeEngine(model=MODEL) for _ in range(engines)]
    runners = [await run_fake_engine(e, "127.0.0.1", 0) for e in fakes]

    args = build_parser().parse_args([])
    args.static_backends = ",".join(e.self_url for e in fakes)
    args.static_models = ",".join([MODEL] * engines)
    args.routing_logic = "roundrobin"
    args.engine_stats_interval = 60
    router_app = build_app(args)
    router_runner, router_url = await _start(router_app)

    passed: List[str] = []
    failed: List[dict] = []
    try:
        async with aiohttp.ClientSession() as session:
            for case in load_corpus():
                status, content = await _chat(
                    session, router_url,
                    case_request_fields(case, surface=surface))
                ok = status == 200 and content is not None
                if ok:
                    dfa = compile_char_dfa(case_spec(case))
                    ok = dfa.fullmatch(content)
                    if ok and case["kind"] == "json_schema":
                        ok = validate_instance(
                            case["spec"], json.loads(content))
                (passed if ok else failed).append(
                    case["name"] if ok else
                    {"case": case["name"], "status": status,
                     "content": content})
            # The 400 path: an uncompilable schema must be rejected at
            # the router, never forwarded.
            bad_status, _ = await _chat(
                session, router_url,
                {"guided_json": {"allOf": [{"type": "string"}]}})
            rejects_uncompilable = bad_status == 400
    finally:
        await router_runner.cleanup()
        for runner in runners:
            await runner.cleanup()
        _reset_router_singletons()

    return {
        "surface": surface,
        "cases": len(passed) + len(failed),
        "passed": len(passed),
        "failed": failed,
        "conformance": round(
            len(passed) / max(len(passed) + len(failed), 1), 4),
        "rejects_uncompilable": rejects_uncompilable,
        "engine_structured_requests": sum(
            e.structured_requests_total for e in fakes),
    }


def _collect_all(eng, requests, timeout_s: float = 300.0):
    """Submit all requests and drain until every one finishes; returns
    (total_tokens, wall_seconds)."""
    import queue

    done = queue.Queue()
    counts = {}

    def make_cb(rid):
        def on_token(token, finish):
            if token is not None:
                counts[rid] = counts.get(rid, 0) + 1
            if finish is not None:
                done.put(rid)
        return on_token

    t0 = time.perf_counter()
    for rid, prompt_ids, sampling in requests:
        eng.add_request(rid, prompt_ids, sampling, make_cb(rid))
    remaining = len(requests)
    deadline = time.time() + timeout_s
    while remaining > 0 and time.time() < deadline:
        try:
            done.get(timeout=1.0)
            remaining -= 1
        except queue.Empty:
            continue
    wall = time.perf_counter() - t0
    if remaining:
        raise RuntimeError(f"{remaining} bench requests never finished")
    return sum(counts.values()), wall


def run_engine_overhead(*, n_requests: int = 8, max_tokens: int = 32,
                        repeats: int = 3) -> dict:
    """Masked vs unmasked greedy tokens/s on the real CPU engine."""
    import jax

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.core import EngineCore
    from production_stack_tpu.engine.sampling import SamplingParams

    eng = EngineCore(
        EngineConfig(model="tiny-llama", max_model_len=128,
                     max_num_seqs=8, block_size=4, num_blocks=256,
                     min_prefill_bucket=16, max_loras=0,
                     decode_steps=1),
        devices=jax.devices()[:1])
    eng.start()
    try:
        def leg(structured: bool) -> float:
            body = {"temperature": 0, "max_tokens": max_tokens}
            if structured:
                body["guided_regex"] = NON_BINDING_REGEX
            best = 0.0
            for r in range(repeats):
                reqs = []
                for i in range(n_requests):
                    sampling = SamplingParams.from_request(dict(body))
                    ids = eng.tokenizer.encode(f"bench prompt {i}")
                    reqs.append((f"{'m' if structured else 'u'}{r}-{i}",
                                 ids, sampling))
                tokens, wall = _collect_all(eng, reqs)
                best = max(best, tokens / wall if wall > 0 else 0.0)
            return best

        # Warm pass (first dispatches may still trace), then measure.
        leg(False)
        unmasked = leg(False)
        masked = leg(True)
    finally:
        eng.stop()

    overhead_pct = round(100.0 * (1.0 - masked / unmasked), 2) \
        if unmasked > 0 else None
    return {
        "n_requests": n_requests,
        "max_tokens": max_tokens,
        "decode_steps": 1,
        "unmasked_tokens_per_s": round(unmasked, 2),
        "masked_tokens_per_s": round(masked, 2),
        "overhead_pct": overhead_pct,
        "structured_stats": {
            k: v for k, v in eng.stats().items()
            if k.startswith("structured")},
    }


def run_structured_ab(*, n_requests: int = 8, max_tokens: int = 32,
                      repeats: int = 3, skip_overhead: bool = False) -> dict:
    """Full A/B: both conformance surfaces plus the mask-overhead legs.

    ``skip_overhead`` runs conformance only (no jax import) — the
    tier-1 router e2e test uses the conformance half directly."""
    guided = asyncio.run(run_corpus_conformance(surface="guided"))
    rf = asyncio.run(run_corpus_conformance(surface="response_format"))
    overhead = None if skip_overhead else run_engine_overhead(
        n_requests=n_requests, max_tokens=max_tokens, repeats=repeats)
    return {
        "metric": "structured_output_ab",
        "unit": "mask_overhead_pct",
        "value": overhead["overhead_pct"] if overhead else None,
        "conformance_guided": guided,
        "conformance_response_format": rf,
        "overhead": overhead,
    }
