"""Hermetic fleet A/B: global prefix cache ON (cross-replica pulls) vs OFF.

The physics, with no TPU and no model: three :class:`FakeEngine`
replicas serve repeat-prompt traffic through the real router with
**round-robin** routing — so a user's second request lands on a
*different* replica than the one that prefilled their prefix. Each user
has a unique ~1.2 kB prompt prefix (well past the fleet's
``min_match_chars``), and each fake engine skips the cached fraction of
its TTFT, like real prefix-cache reuse.

- **pulls_on** leg: the router runs with ``--fleet-cache``. After the
  prime round, the KV controller knows which replica holds each prefix;
  on a repeat request routed elsewhere, the router orchestrates a
  ``/kv/pull`` from the holder before forwarding, so the repeat prefill
  is (mostly) cached and TTFT collapses.
- **pulls_off** leg: same traffic, no fleet cache. A repeat request that
  round-robins onto a different replica recomputes the whole prefix —
  full TTFT. Only the ~1/N that happen to re-land on the holder reuse.

Used by ``bench.py`` (BENCH_FLEET=1) and ``tests/test_fleet.py``.
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional

from production_stack_tpu.testing.qos_ab import (
    _p99,
    _reset_router_singletons,
)

MODEL = "fleet-model"


async def _start(app):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


def _user_prompt(i: int, chars: int = 1200) -> str:
    """Unique-per-user prompt prefix, distinct from char 0 so no two
    users share leading controller chunks."""
    return (f"user-{i:03d} corpus line about topic {i}. " * 64)[:chars]


async def _ttft_request(session, router_url: str, prompt: str,
                        timeout_s: float = 30.0) -> Optional[float]:
    """One streamed chat completion; returns TTFT (first content chunk)
    on a complete stream, None on any failure."""
    import aiohttp

    t0 = time.perf_counter()
    try:
        async with session.post(
            router_url + "/v1/chat/completions",
            json={"model": MODEL, "max_tokens": 2, "stream": True,
                  "messages": [{"role": "user", "content": prompt}]},
            timeout=aiohttp.ClientTimeout(total=timeout_s),
        ) as resp:
            if resp.status != 200:
                return None
            ttft = None
            done = False
            async for line in resp.content:
                stripped = line.strip()
                if stripped == b"data: [DONE]":
                    done = True
                elif ttft is None and stripped.startswith(b"data:"):
                    ttft = time.perf_counter() - t0
            return ttft if done else None
    except (aiohttp.ClientError, asyncio.TimeoutError):
        return None


async def _run_leg(*, fleet_on: bool, users: int, rounds: int,
                   concurrency: int, engine_ttft: float,
                   min_match_chars: int) -> dict:
    import aiohttp

    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import build_parser
    from production_stack_tpu.testing.fake_engine import (
        FakeEngine,
        run_fake_engine,
    )

    _reset_router_singletons()
    engines = [FakeEngine(model=MODEL, ttft=engine_ttft,
                          max_tokens_default=2) for _ in range(3)]
    runners = [await run_fake_engine(e, "127.0.0.1", 0) for e in engines]
    urls = [e.self_url for e in engines]

    args = build_parser().parse_args([])
    args.static_backends = ",".join(urls)
    args.static_models = ",".join([MODEL] * 3)
    # Round-robin on purpose: it maximizes repeat requests landing off
    # the holder replica, which is exactly the case fleet pulls fix.
    args.routing_logic = "roundrobin"
    args.engine_stats_interval = 60
    if fleet_on:
        args.fleet_cache = True
        args.fleet_min_match_chars = min_match_chars
    router_app = build_app(args)
    router_runner, router_url = await _start(router_app)
    for e in engines:
        await e.configure_kv(router_url)

    prompts = [_user_prompt(i) for i in range(users)]
    cold: List[float] = []
    reuse: List[float] = []
    failed = 0
    sem = asyncio.Semaphore(concurrency)

    async def one(session, i: int, bucket: List[float]):
        nonlocal failed
        async with sem:
            ttft = await _ttft_request(session, router_url, prompts[i])
            if ttft is None:
                failed += 1
            else:
                bucket.append(ttft)

    try:
        async with aiohttp.ClientSession() as session:
            # Prime round: every user's prefix lands on some replica and
            # is admitted to the controller. Later rounds are the reuse
            # traffic the A/B measures; the barrier between rounds makes
            # sure admissions precede lookups.
            await asyncio.gather(
                *[one(session, i, cold) for i in range(users)])
            for _ in range(rounds - 1):
                await asyncio.gather(
                    *[one(session, i, reuse) for i in range(users)])
    finally:
        await router_runner.cleanup()
        for runner in runners:
            await runner.cleanup()
        _reset_router_singletons()

    reuse_total = users * (rounds - 1)
    pulls = sum(e.kv_pulls_received for e in engines)
    sorted_reuse = sorted(reuse)
    return {
        "fleet_on": fleet_on,
        "users": users,
        "rounds": rounds,
        "engine_ttft_s": engine_ttft,
        "completed": len(cold) + len(reuse),
        "failed": failed,
        "cold_ttft_p50_s": round(sorted(cold)[len(cold) // 2], 4)
        if cold else None,
        "reuse_ttft_p50_s": round(sorted_reuse[len(sorted_reuse) // 2], 4)
        if reuse else None,
        "reuse_ttft_mean_s": round(sum(reuse) / len(reuse), 4)
        if reuse else None,
        "reuse_ttft_p99_s": round(_p99(reuse), 4) if reuse else None,
        "cross_replica_pulls": pulls,
        "cross_replica_hit_rate": round(pulls / reuse_total, 4)
        if reuse_total else None,
        "pulls_served": sum(e.kv_pulls_served for e in engines),
        "engine_requests": [len(e.requests_seen) for e in engines],
        "engine_prefix_hit_chunks": sum(
            e.prefix_cache_hits for e in engines),
    }


async def run_fleet_ab(*, users: int = 10, rounds: int = 3,
                       concurrency: int = 4, engine_ttft: float = 0.2,
                       min_match_chars: int = 256,
                       skip_off: bool = False) -> dict:
    """Run the pulls-on leg then the pulls-off baseline; A/B dict.

    ``skip_off`` runs only the ON leg (tier-1 test uses it — the OFF leg
    exists to quantify the TTFT win, not to gate correctness)."""
    on = await _run_leg(
        fleet_on=True, users=users, rounds=rounds, concurrency=concurrency,
        engine_ttft=engine_ttft, min_match_chars=min_match_chars)
    off = None
    if not skip_off:
        off = await _run_leg(
            fleet_on=False, users=users, rounds=rounds,
            concurrency=concurrency, engine_ttft=engine_ttft,
            min_match_chars=min_match_chars)
    speedup = None
    if off and on["reuse_ttft_mean_s"] and off["reuse_ttft_mean_s"]:
        if on["reuse_ttft_mean_s"] > 0:
            speedup = round(
                off["reuse_ttft_mean_s"] / on["reuse_ttft_mean_s"], 2)
    return {
        "metric": "fleet_prefix_cache_ab",
        "unit": "reuse_ttft_speedup",
        "value": speedup,
        "cross_replica_hit_rate": on["cross_replica_hit_rate"],
        "users": users,
        "rounds": rounds,
        "concurrency": concurrency,
        "engine_ttft_s": engine_ttft,
        "pulls_on": on,
        "pulls_off": off,
    }
