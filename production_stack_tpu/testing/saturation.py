"""Hermetic router saturation harness: step offered load until goodput
collapses.

No TPU and no model: four :class:`FakeEngine` replicas answer short
streamed completions through the real router running with a real
``--slo-config``, while rungs of closed-loop users (each user issues its
requests back-to-back, so offered load is exactly the rung's user
count) climb from hundreds to 10k+ concurrent. The engines themselves
are nearly free, so what saturates is the thing this harness is about:
the router process — its event loop, proxy streaming, QoS/SLO
accounting, and socket handling.

Per rung the harness reports throughput (RPS), client-side latency
percentiles, the router's own SLO outcome deltas (the ``ok`` / ``slow``
/ ``shed`` / ``failed`` / ``client_abort`` classifier under test), the
goodput ratio, and ``router_overhead_p99`` from the in-process trace
ring. The **knee** is the first rung whose goodput falls below the
collapse threshold; the **RPS ceiling** is the best throughput seen at
or before it. The per-rung outcome deltas double as the classifier's
reconciliation proof: every request that obtained an HTTP response got
exactly one outcome. Past the process fd budget (everything — client,
router, and engine sockets — shares one rlimit, four fds per in-flight
request) the kernel sheds connections before the router can accept
them; those are reported per rung as ``unreached`` and are the only
requests allowed to go unclassified, so reconciliation tightens to
``responses <= classified <= offered`` on shedding rungs and stays
exact everywhere else.

The router runs with ``--loop-monitor`` on, so every rung also records
event-loop evidence: ``loop_lag_p99_s`` (scheduling-lag p99 over the
rung's own samples), ``loop_stall_s`` (lag-measured stall seconds),
``loop_stall_attributed_s`` / ``loop_stall_attribution`` (how much of
that stall time the blocking-call watchdog pinned to named
``file:line:func`` frames), and ``top_blockers`` (the rung's top-3
frames by stall seconds). This is the scale-out decision artifact
ROADMAP item 3 asks for: the knee rung names the code holding the loop,
not just the rung where goodput collapsed.

Used by ``bench.py`` (BENCH_SATURATION=1, artifact
``BENCH_SATURATION_r13.json``) and, at toy scale, by
``tests/test_slo.py``.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import time
from typing import List, Optional

import yaml

from production_stack_tpu.testing.qos_ab import (
    _p99,
    _reset_router_singletons,
)

MODEL = "sat-model"

#: Default rung ladder (concurrent closed-loop users). The top rung is
#: the 10k+ mark the harness exists for; earlier rungs locate the knee.
DEFAULT_STEPS = (100, 500, 1000, 2500, 5000, 10000)

#: Objectives served to the router for the run: under saturation the
#: queueing delay blows through the TTFT bound long before connections
#: fail, so goodput collapse is observable while requests still finish.
SLO_CONFIG = {
    "default": {
        "ttft_p99_s": 1.0,
        "inter_token_p99_s": 0.5,
        "availability": 0.999,
    },
}


async def _start(app, shutdown_timeout: float = 0.5):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0,
                       shutdown_timeout=shutdown_timeout, backlog=4096)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def _one_request(session, router_url: str,
                       client_timeout_s: float,
                       max_tokens: int = 4):
    """One streamed completion.

    Returns ``("done", latency)`` on a complete stream, ``("response",
    None)`` when the router answered with anything else (an error
    status, or a stream that broke after the status line — either way
    the router saw the request and must classify it), and ``("none",
    None)`` when the connection died before any HTTP status arrived —
    the request may never have reached the router at all (fd-exhaustion
    shedding at the socket layer)."""
    import aiohttp

    t0 = time.perf_counter()
    got_response = False
    try:
        async with session.post(
            router_url + "/v1/completions",
            json={"model": MODEL, "prompt": "ping",
                  "max_tokens": max_tokens, "stream": True},
            timeout=aiohttp.ClientTimeout(total=client_timeout_s),
        ) as resp:
            got_response = True
            if resp.status != 200:
                return ("response", None)
            # iter_any + a short carry tail instead of line iteration:
            # the closed-loop clients share the host with the router
            # under test, so client-side parsing cost directly lowers
            # the ceiling being measured. The tail handles a [DONE]
            # frame split across reads.
            done = False
            tail = b""
            async for chunk in resp.content.iter_any():
                blob = tail + chunk
                if b"data: [DONE]" in blob:
                    done = True
                tail = blob[-16:]
            if done:
                return ("done", time.perf_counter() - t0)
            return ("response", None)
    except (aiohttp.ClientError, asyncio.TimeoutError):
        return (("response" if got_response else "none"), None)


async def run_saturation(*, steps=DEFAULT_STEPS,
                         requests_per_user: int = 2,
                         replicas: int = 4,
                         engine_ttft: float = 0.001,
                         client_timeout_s: float = 300.0,
                         collapse_threshold: float = 0.9) -> dict:
    import aiohttp

    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import build_parser
    from production_stack_tpu.testing.fake_engine import FakeEngine
    from production_stack_tpu.utils.misc import set_ulimit

    # Client + router + engine sockets all live in this one process; the
    # top rung alone wants ~3x its user count in fds.
    set_ulimit(target_soft_limit=max(65535, 4 * max(steps) + 8192))

    _reset_router_singletons()
    engines = [FakeEngine(model=MODEL, ttft=engine_ttft,
                          max_tokens_default=4) for _ in range(replicas)]
    started = [await _start(e.make_app()) for e in engines]
    runners = [r for r, _ in started]
    urls = [u for _, u in started]

    total_requests = sum(s * requests_per_user for s in steps)

    slo_file = tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", prefix="slo-sat-", delete=False)
    yaml.safe_dump(SLO_CONFIG, slo_file)
    slo_file.close()

    args = build_parser().parse_args([])
    args.static_backends = ",".join(urls)
    args.static_models = ",".join([MODEL] * replicas)
    args.routing_logic = "roundrobin"
    args.engine_stats_interval = 60
    args.slo_config = slo_file.name
    # Ring must hold a whole rung so the per-rung overhead slice is the
    # full rung population, not whatever survived eviction.
    args.trace_buffer = max(1024, max(steps) * requests_per_user)
    # Event-loop introspection on: per-rung lag percentiles + the
    # blocking-call watchdog's frame attribution are the point of the
    # artifact.
    args.loop_monitor = True
    router_app = build_app(args)
    state = router_app["state"]
    # Swap in a monitor whose lag ring holds hours of ticks: per-rung
    # percentiles must cover the whole rung, not the last few minutes.
    # (Replaced before startup; on_startup starts whatever is attached.)
    from production_stack_tpu.obs.looplag import LoopMonitor

    state.loop_monitor = LoopMonitor(
        "tpu-stack-router",
        stall_threshold_s=state.loop_monitor.stall_threshold_s,
        capacity=1 << 18)
    router_runner, router_url = await _start(router_app)

    rungs: List[dict] = []
    knee = None
    rps_ceiling = 0.0
    try:
        async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
        ) as session:
            for users in steps:
                prev_counts = state.slo.counts()
                recorder = state.trace_recorder
                overhead_before = len(
                    recorder.root_attribute_values("overhead_s"))
                monitor = state.loop_monitor
                # Rung boundary: clamp the watchdog's charge floor so
                # wall time that accrued before this rung cannot be
                # charged into this rung's attribution delta. The poll
                # clock and the lag ring's tick clock straddle rung
                # boundaries independently — the committed r13 artifact
                # recorded a 1.37 attribution ratio from exactly that
                # straddle.
                monitor.detector.mark_boundary()
                lag_seq0 = monitor.seq()
                stall_s0 = monitor.stall_s_sum
                attributed0 = monitor.detector.stall_s_attributed
                blockers0 = monitor.detector.blocker_snapshot()
                latencies: List[float] = []
                failed = [0]
                unreached = [0]

                async def user(n):
                    for _ in range(n):
                        kind, latency = await _one_request(
                            session, router_url, client_timeout_s)
                        if kind == "done":
                            latencies.append(latency)
                        else:
                            failed[0] += 1
                            if kind == "none":
                                unreached[0] += 1

                t0 = time.perf_counter()
                await asyncio.gather(
                    *[user(requests_per_user) for _ in range(users)])
                elapsed = time.perf_counter() - t0

                # An errored-out client returns before the router
                # handler notices the disconnect; give classification a
                # bounded window to catch up before reconciling. Only
                # requests shed before the router accepted them
                # (unreached) may legitimately never be counted.
                total = users * requests_per_user
                expected = total - unreached[0]
                prev_total = sum(prev_counts.values())
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if sum(state.slo.counts().values()) - prev_total \
                            >= expected:
                        break
                    await asyncio.sleep(0.05)

                counts = state.slo.counts()
                outcomes = {k: counts[k] - prev_counts.get(k, 0)
                            for k in counts
                            if counts[k] - prev_counts.get(k, 0)}
                classified = sum(outcomes.values())
                good = outcomes.get("ok", 0)
                goodput = round(good / classified, 4) if classified else None
                overhead_vals = recorder.root_attribute_values(
                    "overhead_s")[overhead_before:]
                # Event-loop evidence for this rung: lag percentiles
                # over the rung's own tick samples, stall seconds from
                # the lag ring (the measured quantity), and the
                # watchdog's frame attribution delta (the explanation).
                loop_pct = monitor.percentiles(since_seq=lag_seq0)
                loop_stall_s = monitor.stall_s_sum - stall_s0
                loop_attr_s = (monitor.detector.stall_s_attributed
                               - attributed0)
                blockers1 = monitor.detector.blocker_snapshot()
                blocker_deltas = []
                for key, rec in blockers1.items():
                    before = blockers0.get(key, {"stalls": 0,
                                                 "stall_s": 0.0})
                    delta_s = rec["stall_s"] - before["stall_s"]
                    if delta_s > 0:
                        blocker_deltas.append({
                            "frame": key,
                            "stalls": rec["stalls"] - before["stalls"],
                            "stall_s": round(delta_s, 6),
                        })
                blocker_deltas.sort(key=lambda b: b["stall_s"],
                                    reverse=True)
                completed = len(latencies)
                responses = total - unreached[0]
                rps = round(completed / elapsed, 1) if elapsed else None
                rung = {
                    "users": users,
                    "requests": total,
                    "completed": completed,
                    "failed": failed[0],
                    "responses": responses,
                    "unreached": unreached[0],
                    "elapsed_s": round(elapsed, 2),
                    "rps": rps,
                    "p50_latency_s": round(
                        sorted(latencies)[completed // 2], 4)
                    if latencies else None,
                    "p99_latency_s": round(_p99(latencies), 4)
                    if latencies else None,
                    "outcomes": outcomes,
                    "outcomes_classified": classified,
                    # Classifier reconciliation: every request that got
                    # an HTTP response got exactly one outcome; only
                    # connections the kernel shed before accept
                    # (unreached) may go unclassified.
                    "outcomes_reconcile": (
                        classified == total if not unreached[0]
                        else responses <= classified <= total),
                    "goodput": goodput,
                    "router_overhead_p99": round(_p99(overhead_vals), 6)
                    if overhead_vals else None,
                    "loop_lag_p99_s": loop_pct["p99"],
                    "loop_lag_max_s": loop_pct["max"],
                    "loop_stall_s": round(loop_stall_s, 6),
                    "loop_stall_attributed_s": round(loop_attr_s, 6),
                    # Share of lag-measured stall time the watchdog
                    # pinned to named frames. mark_boundary() above
                    # stops cross-rung charge bleed, and the residual
                    # sub-tick skew (the lag ring only sees a stall once
                    # the next tick lands) is clamped, so the ratio is
                    # always in [0, 1]; None when the rung had no stalls
                    # to attribute.
                    "loop_stall_attribution": (
                        round(min(1.0, loop_attr_s / loop_stall_s), 4)
                        if loop_stall_s > 0 else None),
                    "top_blockers": blocker_deltas[:3],
                }
                rungs.append(rung)
                if rps is not None and (knee is None):
                    rps_ceiling = max(rps_ceiling, rps)
                if knee is None and goodput is not None \
                        and goodput < collapse_threshold:
                    knee = rung
    finally:
        await router_runner.cleanup()
        for runner in runners:
            await runner.cleanup()
        _reset_router_singletons()
        os.unlink(slo_file.name)

    goodput_5m = state.slo.goodput(300.0)
    return {
        "metric": "router_saturation",
        "unit": "rps_ceiling",
        "value": rps_ceiling or None,
        "replicas": replicas,
        "steps": list(steps),
        "requests_per_user": requests_per_user,
        "total_requests": total_requests,
        "collapse_threshold": collapse_threshold,
        "slo_config": SLO_CONFIG,
        "knee_users": knee["users"] if knee else None,
        "knee_goodput": knee["goodput"] if knee else None,
        "router_overhead_p99_at_knee":
            knee["router_overhead_p99"] if knee else None,
        "loop_lag_p99_at_knee": knee["loop_lag_p99_s"] if knee else None,
        "loop_stall_attribution_at_knee":
            knee["loop_stall_attribution"] if knee else None,
        "loop_top_blockers_at_knee":
            knee["top_blockers"] if knee else None,
        "loop_summary": state.loop_monitor.summary(),
        "goodput_5m_final": round(goodput_5m, 4)
        if goodput_5m is not None else None,
        "outcomes_total": state.slo.counts(),
        "outcomes_reconcile_all": all(r["outcomes_reconcile"]
                                      for r in rungs),
        "rungs": rungs,
        "engine_requests": [len(e.requests_seen) for e in engines],
    }


# ---------------------------------------------------------------------------
# Workers A/B: does SO_REUSEPORT alone move the rps ceiling?
# ---------------------------------------------------------------------------

#: Rung ladder for the A/B. Stops at the r13 single-loop ceiling
#: neighborhood (knee at 1000 users) plus headroom to see whether the
#: 4-worker leg pushes the knee out.
WORKERS_AB_STEPS = (100, 500, 1000, 2500, 5000)


async def _debug_workers(session, router_url: str,
                         lag_window_s: Optional[float] = None) -> dict:
    params = {}
    if lag_window_s is not None:
        params["lag_window_s"] = repr(float(lag_window_s))
    async with session.get(router_url + "/debug/workers",
                           params=params) as resp:
        resp.raise_for_status()
        return await resp.json()


def _outcomes_by_worker(workers_body: dict) -> dict:
    return {int(row["worker"]): dict(row.get("outcomes") or {})
            for row in workers_body["per_worker"]}


def _components_by_worker(workers_body: dict) -> dict:
    """Per-worker on-loop component seconds from ``/debug/workers``
    (the ``loop_components`` row the federation plane carries so the
    relay A/B can prove the byte copy left each worker's loop)."""
    return {int(row["worker"]): dict(row.get("loop_components") or {})
            for row in workers_body["per_worker"]}


def _component_seconds(components: dict, name: str) -> float:
    return float((components.get(name) or {}).get("seconds") or 0.0)


async def _scrape_relay_totals(session, router_url: str) -> dict:
    """Relay counters off the (merged) ``/metrics`` plane: total pumped
    bytes/chunks and handoff failures by reason. Flag-off legs must
    report zeros — the labeled series only exist once the pump runs."""
    import re

    async with session.get(router_url + "/metrics") as resp:
        resp.raise_for_status()
        text = await resp.text()
    bytes_total = 0.0
    chunks_total = 0.0
    handoff_failures: dict = {}
    for line in text.splitlines():
        if line.startswith("vllm_router:relay_bytes_total{"):
            bytes_total += float(line.rsplit(" ", 1)[1])
        elif line.startswith("vllm_router:relay_chunks_total{"):
            chunks_total += float(line.rsplit(" ", 1)[1])
        elif line.startswith("vllm_router:relay_handoff_failures_total{"):
            match = re.search(r'reason="([^"]*)"', line)
            reason = match.group(1) if match else "unknown"
            handoff_failures[reason] = (
                handoff_failures.get(reason, 0.0)
                + float(line.rsplit(" ", 1)[1]))
    return {
        "relay_bytes_total": bytes_total,
        "relay_chunks_total": chunks_total,
        "relay_handoff_failures": handoff_failures,
    }


async def _run_workers_leg(*, workers: int, steps, requests_per_user: int,
                           replicas: int, engine_ttft: float,
                           client_timeout_s: float,
                           collapse_threshold: float,
                           slo_config_path: str,
                           relay: bool = False,
                           relay_pump_threads: int = 2,
                           max_tokens: int = 4,
                           engine_tokens_per_sec: float = 0.0) -> dict:
    """One leg: the router as a REAL ``--router-workers N`` subprocess
    (the pre-fork path under test — in-process build_app cannot fork),
    FakeEngine replicas and the closed-loop clients in this process.
    Outcome deltas and per-worker loop lag come from ``/debug/workers``,
    so the leg exercises the federation plane it measures. With
    ``relay=True`` the subprocess also gets ``--relay-off-loop`` and the
    leg additionally harvests per-worker ``streaming_relay`` /
    ``relay_feed`` on-loop seconds per rung — the direct evidence that
    the per-chunk byte copy left (or stayed on) each worker's loop."""
    import signal
    import socket
    import subprocess
    import sys

    import aiohttp

    from production_stack_tpu.testing.fake_engine import FakeEngine

    engines = [FakeEngine(model=MODEL, ttft=engine_ttft,
                          tokens_per_sec=engine_tokens_per_sec,
                          max_tokens_default=4) for _ in range(replicas)]
    started = [await _start(e.make_app()) for e in engines]
    runners = [r for r, _ in started]
    urls = [u for _, u in started]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        router_port = s.getsockname()[1]
    router_url = f"http://127.0.0.1:{router_port}"
    trace_buffer = max(1024, max(steps) * requests_per_user)
    proc = subprocess.Popen([
        sys.executable, "-m", "production_stack_tpu.router.app",
        "--host", "127.0.0.1", "--port", str(router_port),
        "--router-workers", str(workers),
        "--static-backends", ",".join(urls),
        "--static-models", ",".join([MODEL] * replicas),
        "--routing-logic", "roundrobin",
        "--engine-stats-interval", "60",
        "--slo-config", slo_config_path,
        "--trace-buffer", str(trace_buffer),
        "--loop-monitor",
        *(["--relay-off-loop",
           "--relay-pump-threads", str(relay_pump_threads)]
          if relay else []),
        "--log-level", "warning",
        # init_logger gives each module its own level from this env var;
        # without it per-request INFO routing lines (20k+ at the top
        # rung) would tax the workers under measurement.
    ], env=dict(os.environ, TPU_STACK_LOG_LEVEL="warning"))

    rungs: List[dict] = []
    knee = None
    rps_ceiling = 0.0
    topology: List[dict] = []
    relay_totals: Optional[dict] = None
    try:
        async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
            timeout=aiohttp.ClientTimeout(total=60.0),
        ) as probe:
            deadline = time.monotonic() + 30.0
            up = False
            while time.monotonic() < deadline:
                try:
                    async with probe.get(router_url + "/health") as resp:
                        if resp.status == 200:
                            up = True
                            break
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.2)
            if not up:
                raise RuntimeError(
                    f"router ({workers} workers) never became healthy")

            async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0),
            ) as session:
                for users in steps:
                    body0 = await _debug_workers(probe, router_url)
                    before = _outcomes_by_worker(body0)
                    comp_before = _components_by_worker(body0)
                    latencies: List[float] = []
                    failed = [0]
                    unreached = [0]

                    async def user(n):
                        for _ in range(n):
                            kind, latency = await _one_request(
                                session, router_url, client_timeout_s,
                                max_tokens=max_tokens)
                            if kind == "done":
                                latencies.append(latency)
                            else:
                                failed[0] += 1
                                if kind == "none":
                                    unreached[0] += 1

                    t0 = time.perf_counter()
                    await asyncio.gather(
                        *[user(requests_per_user) for _ in range(users)])
                    elapsed = time.perf_counter() - t0

                    total = users * requests_per_user
                    expected = total - unreached[0]
                    prev_total = sum(sum(c.values())
                                     for c in before.values())
                    catchup_deadline = time.monotonic() + 10.0
                    body = None
                    while time.monotonic() < catchup_deadline:
                        body = await _debug_workers(
                            probe, router_url,
                            lag_window_s=time.perf_counter() - t0)
                        now_total = sum(
                            sum(c.values()) for c in
                            _outcomes_by_worker(body).values())
                        if now_total - prev_total >= expected:
                            break
                        await asyncio.sleep(0.1)
                    after = _outcomes_by_worker(body)
                    topology = [{"worker": row["worker"],
                                 "pid": row["pid"],
                                 "port": body.get("port", router_port)}
                                for row in body["per_worker"]]

                    outcomes_by_worker = {}
                    for wid in sorted(after):
                        prev = before.get(wid, {})
                        delta = {k: after[wid][k] - prev.get(k, 0)
                                 for k in after[wid]
                                 if after[wid][k] - prev.get(k, 0)}
                        if delta:
                            outcomes_by_worker[str(wid)] = delta
                    outcomes: dict = {}
                    for delta in outcomes_by_worker.values():
                        for k, v in delta.items():
                            outcomes[k] = outcomes.get(k, 0) + v
                    classified = sum(outcomes.values())
                    good = outcomes.get("ok", 0)
                    goodput = (round(good / classified, 4)
                               if classified else None)
                    lag_by_worker = {
                        str(row["worker"]):
                            (row.get("loop_lag_window") or {}).get("p99")
                        for row in body["per_worker"]}
                    # Relay evidence: per-worker deltas of the two
                    # streaming components. Flag-off rungs accrue
                    # streaming_relay (the on-loop write path);
                    # flag-on rungs accrue relay_feed (the loop-side
                    # handoff shim) while streaming_relay stays ~0.
                    comp_after = _components_by_worker(body)
                    relay_comp_by_worker = {}
                    for wid in sorted(comp_after):
                        prev = comp_before.get(wid, {})
                        relay_comp_by_worker[str(wid)] = {
                            name: round(max(0.0, _component_seconds(
                                comp_after[wid], name)
                                - _component_seconds(prev, name)), 6)
                            for name in ("streaming_relay",
                                         "relay_feed")}
                    streaming_relay_s = round(sum(
                        row["streaming_relay"]
                        for row in relay_comp_by_worker.values()), 6)
                    relay_feed_s = round(sum(
                        row["relay_feed"]
                        for row in relay_comp_by_worker.values()), 6)
                    completed = len(latencies)
                    responses = total - unreached[0]
                    rps = (round(completed / elapsed, 1)
                           if elapsed else None)
                    rung = {
                        "users": users,
                        "requests": total,
                        "completed": completed,
                        "failed": failed[0],
                        "responses": responses,
                        "unreached": unreached[0],
                        "elapsed_s": round(elapsed, 2),
                        "rps": rps,
                        "p50_latency_s": round(
                            sorted(latencies)[completed // 2], 4)
                        if latencies else None,
                        "p99_latency_s": round(_p99(latencies), 4)
                        if latencies else None,
                        "outcomes": outcomes,
                        "outcomes_by_worker": outcomes_by_worker,
                        "outcomes_classified": classified,
                        # Same invariant as r12/r13, now summed across
                        # workers: Σ per-worker classified outcomes ==
                        # responses (relaxed only on fd-shed rungs).
                        "outcomes_reconcile": (
                            classified == total if not unreached[0]
                            else responses <= classified <= total),
                        "goodput": goodput,
                        "loop_lag_p99_by_worker": lag_by_worker,
                        "loop_lag_p99_max_s": max(
                            (v for v in lag_by_worker.values()
                             if v is not None), default=None),
                        "streaming_relay_s": streaming_relay_s,
                        "relay_feed_s": relay_feed_s,
                        "relay_components_by_worker":
                            relay_comp_by_worker,
                    }
                    rungs.append(rung)
                    if rps is not None and knee is None:
                        rps_ceiling = max(rps_ceiling, rps)
                    if knee is None and goodput is not None \
                            and goodput < collapse_threshold:
                        knee = rung
                # Pump counters off the merged /metrics plane: non-zero
                # only when the relay actually moved bytes (flag-off
                # legs prove the zero).
                relay_totals = await _scrape_relay_totals(
                    probe, router_url)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)
        for runner in runners:
            await runner.cleanup()

    # Goodput-qualified ceiling: best rung rate with the SLO mix still
    # healthy (goodput >= collapse_threshold). The raw rps_ceiling can
    # peak ON the collapse rung — slow requests still complete — so the
    # qualified number is the honest "ceiling with objectives held".
    rps_ceiling_good = max(
        (r["rps"] for r in rungs
         if r["rps"] is not None and r["goodput"] is not None
         and r["goodput"] >= collapse_threshold), default=None)
    return {
        "workers": workers,
        "relay": relay,
        "relay_pump_threads": relay_pump_threads if relay else None,
        "rps_ceiling": rps_ceiling or None,
        "rps_ceiling_good": rps_ceiling_good,
        "knee_users": knee["users"] if knee else None,
        "knee_goodput": knee["goodput"] if knee else None,
        "loop_lag_p99_at_knee":
            knee["loop_lag_p99_max_s"] if knee else None,
        "worker_topology": topology,
        "outcomes_reconcile_all": all(r["outcomes_reconcile"]
                                      for r in rungs),
        # Leg totals of the two streaming components (summed across
        # rungs and workers): the off-vs-on comparison of
        # streaming_relay_s is the ">=90% off-loop" acceptance number.
        "streaming_relay_s": round(sum(
            r["streaming_relay_s"] for r in rungs), 6),
        "relay_feed_s": round(sum(
            r["relay_feed_s"] for r in rungs), 6),
        "relay_totals": relay_totals,
        "rungs": rungs,
        "engine_requests": [len(e.requests_seen) for e in engines],
    }


async def run_saturation_workers_ab(*, steps=WORKERS_AB_STEPS,
                                    requests_per_user: int = 2,
                                    replicas: int = 4,
                                    worker_legs=(1, 4),
                                    engine_ttft: float = 0.001,
                                    client_timeout_s: float = 300.0,
                                    collapse_threshold: float = 0.9,
                                    ) -> dict:
    """1-vs-N-worker saturation A/B over the same engine fleet: the
    answer to "does SO_REUSEPORT alone move the r13 672 rps ceiling
    before the relay-off-loop work lands?" (ROADMAP item 2). The value
    is the multi-worker ceiling as a ratio of the single-worker one."""
    from production_stack_tpu.utils.misc import set_ulimit

    # Engines + clients share this process's fd budget (the router is a
    # subprocess and raises its own rlimit in main()).
    set_ulimit(target_soft_limit=max(65535, 4 * max(steps) + 8192))

    slo_file = tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", prefix="slo-sat-ab-", delete=False)
    yaml.safe_dump(SLO_CONFIG, slo_file)
    slo_file.close()

    legs = []
    try:
        for workers in worker_legs:
            legs.append(await _run_workers_leg(
                workers=workers, steps=steps,
                requests_per_user=requests_per_user, replicas=replicas,
                engine_ttft=engine_ttft,
                client_timeout_s=client_timeout_s,
                collapse_threshold=collapse_threshold,
                slo_config_path=slo_file.name))
    finally:
        os.unlink(slo_file.name)

    baseline = next((l for l in legs if l["workers"] == 1), legs[0])
    multi = next((l for l in legs if l["workers"] != 1), legs[-1])
    ratio = None
    if baseline["rps_ceiling"] and multi["rps_ceiling"]:
        ratio = round(multi["rps_ceiling"] / baseline["rps_ceiling"], 3)
    return {
        "metric": "router_saturation_workers_ab",
        "unit": "rps_ceiling_ratio",
        "value": ratio,
        # The single number that decides how to read the ratio: workers
        # beyond the core count share CPU, so SO_REUSEPORT spreads loop
        # lag without raising the ceiling.
        "host_cpus": os.cpu_count(),
        "replicas": replicas,
        "steps": list(steps),
        "requests_per_user": requests_per_user,
        "worker_legs": [l["workers"] for l in legs],
        "collapse_threshold": collapse_threshold,
        "slo_config": SLO_CONFIG,
        "rps_ceiling_1w": baseline["rps_ceiling"],
        "rps_ceiling_multi": multi["rps_ceiling"],
        "knee_users_1w": baseline["knee_users"],
        "knee_users_multi": multi["knee_users"],
        "outcomes_reconcile_all": all(l["outcomes_reconcile_all"]
                                      for l in legs),
        "legs": legs,
    }


# ---------------------------------------------------------------------------
# Relay A/B: does taking the byte copy off the loop move the ceiling?
# ---------------------------------------------------------------------------

#: Streamed tokens per request in the relay A/B, and the engine-side
#: token pacing. The r13/r16 ladders used 4-token answers emitted with
#: no pacing — the whole upstream body lands in the first socket read,
#: so there is nothing left to relay after the commit point and the
#: rungs measure connection setup, not streaming (measured: ~0.2 pumped
#: chunks per request, relay_feed_s == streaming_relay_s, ratio 1.0).
#: The relay targets the per-chunk copy loop, so its A/B streams the
#: workload shape the tier exists for: real token cadence (paced
#: frames arrive as separate reads, like a decoding model's 10-50 ms
#: inter-token gap) and enough chunks per request that the streaming
#: path is the dominant on-loop cost being measured.
RELAY_AB_MAX_TOKENS = 32
RELAY_AB_ENGINE_TOKENS_PER_SEC = 200.0


#: Rung ladder for the relay A/B. Unlike the r13/r16 unpaced ladders
#: (which climb to 2500 users), this one tops out at the old 1000-user
#: knee: with paced 32-token streams the closed-loop harness itself
#: becomes the bottleneck past ~1000 users on a small host — Little's
#: law pins TTFT near users/rps for BOTH legs regardless of router
#: efficiency, so deeper rungs measure the harness, not the relay.
RELAY_AB_STEPS = (100, 250, 500, 1000)


async def run_saturation_relay_ab(*, steps=RELAY_AB_STEPS,
                                  requests_per_user: int = 3,
                                  replicas: int = 4,
                                  relay_pump_threads: int = 2,
                                  multi_workers: int = 4,
                                  max_tokens: int = RELAY_AB_MAX_TOKENS,
                                  engine_tokens_per_sec: float =
                                  RELAY_AB_ENGINE_TOKENS_PER_SEC,
                                  engine_ttft: float = 0.001,
                                  client_timeout_s: float = 300.0,
                                  collapse_threshold: float = 0.9,
                                  ) -> dict:
    """Relay-off vs relay-on saturation A/B over the same engine fleet
    and rung ladder, plus a ``--router-workers 4 + relay`` leg (the
    composition ISSUE 17 requires: pump metrics worker-stamped through
    the federation plane). Three legs, all real subprocesses:

    1. ``workers=1`` relay off — the r13/r16 baseline path, every chunk
       written on the event loop (``streaming_relay`` accrues).
    2. ``workers=1`` relay on — same ladder, byte copy handed to pump
       threads after the first chunk (``relay_feed`` accrues,
       ``streaming_relay`` collapses).
    3. ``workers=4`` relay on — relay composed with SO_REUSEPORT
       pre-fork; per-worker component seconds prove each worker's loop
       shed the copy, not just the aggregate.

    ``value`` is the relay-on single-worker ceiling as a ratio of the
    relay-off one, computed over the *goodput-qualified* ceilings
    (``rps_ceiling_good``: best rung that still held goodput >=
    ``collapse_threshold``) when both legs have one — the raw ceiling
    can peak ON the collapse rung, where throughput is high but the
    objectives are already gone, which understates the relay's win of
    holding goodput deeper into the ladder. ``streaming_relay_drop``
    is the fractional reduction in on-loop streaming seconds (the
    ">=90% off the loop" acceptance number)."""
    from production_stack_tpu.utils.misc import set_ulimit

    set_ulimit(target_soft_limit=max(65535, 4 * max(steps) + 8192))

    slo_file = tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", prefix="slo-sat-relay-", delete=False)
    yaml.safe_dump(SLO_CONFIG, slo_file)
    slo_file.close()

    leg_specs = (
        {"workers": 1, "relay": False},
        {"workers": 1, "relay": True},
        {"workers": multi_workers, "relay": True},
    )
    legs = []
    try:
        for spec in leg_specs:
            legs.append(await _run_workers_leg(
                workers=spec["workers"], steps=steps,
                requests_per_user=requests_per_user, replicas=replicas,
                engine_ttft=engine_ttft,
                client_timeout_s=client_timeout_s,
                collapse_threshold=collapse_threshold,
                slo_config_path=slo_file.name,
                relay=spec["relay"],
                relay_pump_threads=relay_pump_threads,
                max_tokens=max_tokens,
                engine_tokens_per_sec=engine_tokens_per_sec))
    finally:
        os.unlink(slo_file.name)

    off = next(l for l in legs if not l["relay"])
    on = next(l for l in legs if l["relay"] and l["workers"] == 1)
    multi_on = next(l for l in legs if l["relay"] and l["workers"] != 1)
    # Prefer the goodput-qualified ceilings: the honest "ceiling with
    # objectives held". Raw ceilings only when a leg never held goodput.
    ratio = None
    if off.get("rps_ceiling_good") and on.get("rps_ceiling_good"):
        ratio = round(on["rps_ceiling_good"] / off["rps_ceiling_good"], 3)
    elif off["rps_ceiling"] and on["rps_ceiling"]:
        ratio = round(on["rps_ceiling"] / off["rps_ceiling"], 3)
    # Per-rung on/off throughput so the artifact shows WHERE the relay
    # wins, not just the single ceiling number.
    rps_ratio_by_rung = {}
    off_by_users = {r["users"]: r for r in off["rungs"]}
    for r in on["rungs"]:
        o = off_by_users.get(r["users"])
        if o and o.get("rps") and r.get("rps"):
            rps_ratio_by_rung[str(r["users"])] = round(r["rps"] / o["rps"], 3)
    drop = None
    if off["streaming_relay_s"] > 0:
        drop = round(1.0 - (on["streaming_relay_s"]
                            / off["streaming_relay_s"]), 4)
    return {
        "metric": "router_saturation_relay_ab",
        "unit": "rps_ceiling_ratio",
        "value": ratio,
        # Same caveat as the workers A/B: pump threads beyond the core
        # count share CPU with the loop, so the win must come from
        # cheaper per-chunk loop work + syscall coalescing, not
        # parallelism. host_cpus says which regime this run measured.
        "host_cpus": os.cpu_count(),
        "replicas": replicas,
        "steps": list(steps),
        "requests_per_user": requests_per_user,
        "max_tokens": max_tokens,
        "engine_tokens_per_sec": engine_tokens_per_sec,
        "relay_pump_threads": relay_pump_threads,
        "collapse_threshold": collapse_threshold,
        "slo_config": SLO_CONFIG,
        "rps_ceiling_off": off["rps_ceiling"],
        "rps_ceiling_on": on["rps_ceiling"],
        "rps_ceiling_multi_on": multi_on["rps_ceiling"],
        "rps_ceiling_good_off": off.get("rps_ceiling_good"),
        "rps_ceiling_good_on": on.get("rps_ceiling_good"),
        "rps_ceiling_good_multi_on": multi_on.get("rps_ceiling_good"),
        "rps_ratio_by_rung": rps_ratio_by_rung,
        "knee_users_off": off["knee_users"],
        "knee_users_on": on["knee_users"],
        "knee_users_multi_on": multi_on["knee_users"],
        # On-loop streaming seconds, off leg vs on leg, and the
        # fractional drop (acceptance: >= 0.9).
        "streaming_relay_s_off": off["streaming_relay_s"],
        "streaming_relay_s_on": on["streaming_relay_s"],
        "streaming_relay_drop": drop,
        "relay_feed_s_on": on["relay_feed_s"],
        "relay_totals_off": off["relay_totals"],
        "relay_totals_on": on["relay_totals"],
        "outcomes_reconcile_all": all(l["outcomes_reconcile_all"]
                                      for l in legs),
        "legs": legs,
    }
