"""Hermetic router saturation harness: step offered load until goodput
collapses.

No TPU and no model: four :class:`FakeEngine` replicas answer short
streamed completions through the real router running with a real
``--slo-config``, while rungs of closed-loop users (each user issues its
requests back-to-back, so offered load is exactly the rung's user
count) climb from hundreds to 10k+ concurrent. The engines themselves
are nearly free, so what saturates is the thing this harness is about:
the router process — its event loop, proxy streaming, QoS/SLO
accounting, and socket handling.

Per rung the harness reports throughput (RPS), client-side latency
percentiles, the router's own SLO outcome deltas (the ``ok`` / ``slow``
/ ``shed`` / ``failed`` / ``client_abort`` classifier under test), the
goodput ratio, and ``router_overhead_p99`` from the in-process trace
ring. The **knee** is the first rung whose goodput falls below the
collapse threshold; the **RPS ceiling** is the best throughput seen at
or before it. The per-rung outcome deltas double as the classifier's
reconciliation proof: every request that obtained an HTTP response got
exactly one outcome. Past the process fd budget (everything — client,
router, and engine sockets — shares one rlimit, four fds per in-flight
request) the kernel sheds connections before the router can accept
them; those are reported per rung as ``unreached`` and are the only
requests allowed to go unclassified, so reconciliation tightens to
``responses <= classified <= offered`` on shedding rungs and stays
exact everywhere else.

The router runs with ``--loop-monitor`` on, so every rung also records
event-loop evidence: ``loop_lag_p99_s`` (scheduling-lag p99 over the
rung's own samples), ``loop_stall_s`` (lag-measured stall seconds),
``loop_stall_attributed_s`` / ``loop_stall_attribution`` (how much of
that stall time the blocking-call watchdog pinned to named
``file:line:func`` frames), and ``top_blockers`` (the rung's top-3
frames by stall seconds). This is the scale-out decision artifact
ROADMAP item 3 asks for: the knee rung names the code holding the loop,
not just the rung where goodput collapsed.

Used by ``bench.py`` (BENCH_SATURATION=1, artifact
``BENCH_SATURATION_r13.json``) and, at toy scale, by
``tests/test_slo.py``.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import time
from typing import List, Optional

import yaml

from production_stack_tpu.testing.qos_ab import (
    _p99,
    _reset_router_singletons,
)

MODEL = "sat-model"

#: Default rung ladder (concurrent closed-loop users). The top rung is
#: the 10k+ mark the harness exists for; earlier rungs locate the knee.
DEFAULT_STEPS = (100, 500, 1000, 2500, 5000, 10000)

#: Objectives served to the router for the run: under saturation the
#: queueing delay blows through the TTFT bound long before connections
#: fail, so goodput collapse is observable while requests still finish.
SLO_CONFIG = {
    "default": {
        "ttft_p99_s": 1.0,
        "inter_token_p99_s": 0.5,
        "availability": 0.999,
    },
}


async def _start(app, shutdown_timeout: float = 0.5):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0,
                       shutdown_timeout=shutdown_timeout, backlog=4096)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def _one_request(session, router_url: str,
                       client_timeout_s: float):
    """One streamed completion.

    Returns ``("done", latency)`` on a complete stream, ``("response",
    None)`` when the router answered with anything else (an error
    status, or a stream that broke after the status line — either way
    the router saw the request and must classify it), and ``("none",
    None)`` when the connection died before any HTTP status arrived —
    the request may never have reached the router at all (fd-exhaustion
    shedding at the socket layer)."""
    import aiohttp

    t0 = time.perf_counter()
    got_response = False
    try:
        async with session.post(
            router_url + "/v1/completions",
            json={"model": MODEL, "prompt": "ping", "max_tokens": 4,
                  "stream": True},
            timeout=aiohttp.ClientTimeout(total=client_timeout_s),
        ) as resp:
            got_response = True
            if resp.status != 200:
                return ("response", None)
            done = False
            async for line in resp.content:
                if line.strip() == b"data: [DONE]":
                    done = True
            if done:
                return ("done", time.perf_counter() - t0)
            return ("response", None)
    except (aiohttp.ClientError, asyncio.TimeoutError):
        return (("response" if got_response else "none"), None)


async def run_saturation(*, steps=DEFAULT_STEPS,
                         requests_per_user: int = 2,
                         replicas: int = 4,
                         engine_ttft: float = 0.001,
                         client_timeout_s: float = 300.0,
                         collapse_threshold: float = 0.9) -> dict:
    import aiohttp

    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import build_parser
    from production_stack_tpu.testing.fake_engine import FakeEngine
    from production_stack_tpu.utils.misc import set_ulimit

    # Client + router + engine sockets all live in this one process; the
    # top rung alone wants ~3x its user count in fds.
    set_ulimit(target_soft_limit=max(65535, 4 * max(steps) + 8192))

    _reset_router_singletons()
    engines = [FakeEngine(model=MODEL, ttft=engine_ttft,
                          max_tokens_default=4) for _ in range(replicas)]
    started = [await _start(e.make_app()) for e in engines]
    runners = [r for r, _ in started]
    urls = [u for _, u in started]

    total_requests = sum(s * requests_per_user for s in steps)

    slo_file = tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", prefix="slo-sat-", delete=False)
    yaml.safe_dump(SLO_CONFIG, slo_file)
    slo_file.close()

    args = build_parser().parse_args([])
    args.static_backends = ",".join(urls)
    args.static_models = ",".join([MODEL] * replicas)
    args.routing_logic = "roundrobin"
    args.engine_stats_interval = 60
    args.slo_config = slo_file.name
    # Ring must hold a whole rung so the per-rung overhead slice is the
    # full rung population, not whatever survived eviction.
    args.trace_buffer = max(1024, max(steps) * requests_per_user)
    # Event-loop introspection on: per-rung lag percentiles + the
    # blocking-call watchdog's frame attribution are the point of the
    # artifact.
    args.loop_monitor = True
    router_app = build_app(args)
    state = router_app["state"]
    # Swap in a monitor whose lag ring holds hours of ticks: per-rung
    # percentiles must cover the whole rung, not the last few minutes.
    # (Replaced before startup; on_startup starts whatever is attached.)
    from production_stack_tpu.obs.looplag import LoopMonitor

    state.loop_monitor = LoopMonitor(
        "tpu-stack-router",
        stall_threshold_s=state.loop_monitor.stall_threshold_s,
        capacity=1 << 18)
    router_runner, router_url = await _start(router_app)

    rungs: List[dict] = []
    knee = None
    rps_ceiling = 0.0
    try:
        async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
        ) as session:
            for users in steps:
                prev_counts = state.slo.counts()
                recorder = state.trace_recorder
                overhead_before = len(
                    recorder.root_attribute_values("overhead_s"))
                monitor = state.loop_monitor
                lag_seq0 = monitor.seq()
                stall_s0 = monitor.stall_s_sum
                attributed0 = monitor.detector.stall_s_attributed
                blockers0 = monitor.detector.blocker_snapshot()
                latencies: List[float] = []
                failed = [0]
                unreached = [0]

                async def user(n):
                    for _ in range(n):
                        kind, latency = await _one_request(
                            session, router_url, client_timeout_s)
                        if kind == "done":
                            latencies.append(latency)
                        else:
                            failed[0] += 1
                            if kind == "none":
                                unreached[0] += 1

                t0 = time.perf_counter()
                await asyncio.gather(
                    *[user(requests_per_user) for _ in range(users)])
                elapsed = time.perf_counter() - t0

                # An errored-out client returns before the router
                # handler notices the disconnect; give classification a
                # bounded window to catch up before reconciling. Only
                # requests shed before the router accepted them
                # (unreached) may legitimately never be counted.
                total = users * requests_per_user
                expected = total - unreached[0]
                prev_total = sum(prev_counts.values())
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if sum(state.slo.counts().values()) - prev_total \
                            >= expected:
                        break
                    await asyncio.sleep(0.05)

                counts = state.slo.counts()
                outcomes = {k: counts[k] - prev_counts.get(k, 0)
                            for k in counts
                            if counts[k] - prev_counts.get(k, 0)}
                classified = sum(outcomes.values())
                good = outcomes.get("ok", 0)
                goodput = round(good / classified, 4) if classified else None
                overhead_vals = recorder.root_attribute_values(
                    "overhead_s")[overhead_before:]
                # Event-loop evidence for this rung: lag percentiles
                # over the rung's own tick samples, stall seconds from
                # the lag ring (the measured quantity), and the
                # watchdog's frame attribution delta (the explanation).
                loop_pct = monitor.percentiles(since_seq=lag_seq0)
                loop_stall_s = monitor.stall_s_sum - stall_s0
                loop_attr_s = (monitor.detector.stall_s_attributed
                               - attributed0)
                blockers1 = monitor.detector.blocker_snapshot()
                blocker_deltas = []
                for key, rec in blockers1.items():
                    before = blockers0.get(key, {"stalls": 0,
                                                 "stall_s": 0.0})
                    delta_s = rec["stall_s"] - before["stall_s"]
                    if delta_s > 0:
                        blocker_deltas.append({
                            "frame": key,
                            "stalls": rec["stalls"] - before["stalls"],
                            "stall_s": round(delta_s, 6),
                        })
                blocker_deltas.sort(key=lambda b: b["stall_s"],
                                    reverse=True)
                completed = len(latencies)
                responses = total - unreached[0]
                rps = round(completed / elapsed, 1) if elapsed else None
                rung = {
                    "users": users,
                    "requests": total,
                    "completed": completed,
                    "failed": failed[0],
                    "responses": responses,
                    "unreached": unreached[0],
                    "elapsed_s": round(elapsed, 2),
                    "rps": rps,
                    "p50_latency_s": round(
                        sorted(latencies)[completed // 2], 4)
                    if latencies else None,
                    "p99_latency_s": round(_p99(latencies), 4)
                    if latencies else None,
                    "outcomes": outcomes,
                    "outcomes_classified": classified,
                    # Classifier reconciliation: every request that got
                    # an HTTP response got exactly one outcome; only
                    # connections the kernel shed before accept
                    # (unreached) may go unclassified.
                    "outcomes_reconcile": (
                        classified == total if not unreached[0]
                        else responses <= classified <= total),
                    "goodput": goodput,
                    "router_overhead_p99": round(_p99(overhead_vals), 6)
                    if overhead_vals else None,
                    "loop_lag_p99_s": loop_pct["p99"],
                    "loop_lag_max_s": loop_pct["max"],
                    "loop_stall_s": round(loop_stall_s, 6),
                    "loop_stall_attributed_s": round(loop_attr_s, 6),
                    # Share of lag-measured stall time the watchdog
                    # pinned to named frames. Sampling charges wall time
                    # between polls, so the ratio can slightly exceed 1
                    # (the lag ring only sees a stall once the next tick
                    # lands); None when the rung had no stalls to
                    # attribute.
                    "loop_stall_attribution": (
                        round(loop_attr_s / loop_stall_s, 4)
                        if loop_stall_s > 0 else None),
                    "top_blockers": blocker_deltas[:3],
                }
                rungs.append(rung)
                if rps is not None and (knee is None):
                    rps_ceiling = max(rps_ceiling, rps)
                if knee is None and goodput is not None \
                        and goodput < collapse_threshold:
                    knee = rung
    finally:
        await router_runner.cleanup()
        for runner in runners:
            await runner.cleanup()
        _reset_router_singletons()
        os.unlink(slo_file.name)

    goodput_5m = state.slo.goodput(300.0)
    return {
        "metric": "router_saturation",
        "unit": "rps_ceiling",
        "value": rps_ceiling or None,
        "replicas": replicas,
        "steps": list(steps),
        "requests_per_user": requests_per_user,
        "total_requests": total_requests,
        "collapse_threshold": collapse_threshold,
        "slo_config": SLO_CONFIG,
        "knee_users": knee["users"] if knee else None,
        "knee_goodput": knee["goodput"] if knee else None,
        "router_overhead_p99_at_knee":
            knee["router_overhead_p99"] if knee else None,
        "loop_lag_p99_at_knee": knee["loop_lag_p99_s"] if knee else None,
        "loop_stall_attribution_at_knee":
            knee["loop_stall_attribution"] if knee else None,
        "loop_top_blockers_at_knee":
            knee["top_blockers"] if knee else None,
        "loop_summary": state.loop_monitor.summary(),
        "goodput_5m_final": round(goodput_5m, 4)
        if goodput_5m is not None else None,
        "outcomes_total": state.slo.counts(),
        "outcomes_reconcile_all": all(r["outcomes_reconcile"]
                                      for r in rungs),
        "rungs": rungs,
        "engine_requests": [len(e.requests_seen) for e in engines],
    }


# ---------------------------------------------------------------------------
# Workers A/B: does SO_REUSEPORT alone move the rps ceiling?
# ---------------------------------------------------------------------------

#: Rung ladder for the A/B. Stops at the r13 single-loop ceiling
#: neighborhood (knee at 1000 users) plus headroom to see whether the
#: 4-worker leg pushes the knee out.
WORKERS_AB_STEPS = (100, 500, 1000, 2500, 5000)


async def _debug_workers(session, router_url: str,
                         lag_window_s: Optional[float] = None) -> dict:
    params = {}
    if lag_window_s is not None:
        params["lag_window_s"] = repr(float(lag_window_s))
    async with session.get(router_url + "/debug/workers",
                           params=params) as resp:
        resp.raise_for_status()
        return await resp.json()


def _outcomes_by_worker(workers_body: dict) -> dict:
    return {int(row["worker"]): dict(row.get("outcomes") or {})
            for row in workers_body["per_worker"]}


async def _run_workers_leg(*, workers: int, steps, requests_per_user: int,
                           replicas: int, engine_ttft: float,
                           client_timeout_s: float,
                           collapse_threshold: float,
                           slo_config_path: str) -> dict:
    """One leg: the router as a REAL ``--router-workers N`` subprocess
    (the pre-fork path under test — in-process build_app cannot fork),
    FakeEngine replicas and the closed-loop clients in this process.
    Outcome deltas and per-worker loop lag come from ``/debug/workers``,
    so the leg exercises the federation plane it measures."""
    import signal
    import socket
    import subprocess
    import sys

    import aiohttp

    from production_stack_tpu.testing.fake_engine import FakeEngine

    engines = [FakeEngine(model=MODEL, ttft=engine_ttft,
                          max_tokens_default=4) for _ in range(replicas)]
    started = [await _start(e.make_app()) for e in engines]
    runners = [r for r, _ in started]
    urls = [u for _, u in started]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        router_port = s.getsockname()[1]
    router_url = f"http://127.0.0.1:{router_port}"
    trace_buffer = max(1024, max(steps) * requests_per_user)
    proc = subprocess.Popen([
        sys.executable, "-m", "production_stack_tpu.router.app",
        "--host", "127.0.0.1", "--port", str(router_port),
        "--router-workers", str(workers),
        "--static-backends", ",".join(urls),
        "--static-models", ",".join([MODEL] * replicas),
        "--routing-logic", "roundrobin",
        "--engine-stats-interval", "60",
        "--slo-config", slo_config_path,
        "--trace-buffer", str(trace_buffer),
        "--loop-monitor",
        "--log-level", "warning",
        # init_logger gives each module its own level from this env var;
        # without it per-request INFO routing lines (20k+ at the top
        # rung) would tax the workers under measurement.
    ], env=dict(os.environ, TPU_STACK_LOG_LEVEL="warning"))

    rungs: List[dict] = []
    knee = None
    rps_ceiling = 0.0
    topology: List[dict] = []
    try:
        async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
            timeout=aiohttp.ClientTimeout(total=60.0),
        ) as probe:
            deadline = time.monotonic() + 30.0
            up = False
            while time.monotonic() < deadline:
                try:
                    async with probe.get(router_url + "/health") as resp:
                        if resp.status == 200:
                            up = True
                            break
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.2)
            if not up:
                raise RuntimeError(
                    f"router ({workers} workers) never became healthy")

            async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0),
            ) as session:
                for users in steps:
                    before = _outcomes_by_worker(
                        await _debug_workers(probe, router_url))
                    latencies: List[float] = []
                    failed = [0]
                    unreached = [0]

                    async def user(n):
                        for _ in range(n):
                            kind, latency = await _one_request(
                                session, router_url, client_timeout_s)
                            if kind == "done":
                                latencies.append(latency)
                            else:
                                failed[0] += 1
                                if kind == "none":
                                    unreached[0] += 1

                    t0 = time.perf_counter()
                    await asyncio.gather(
                        *[user(requests_per_user) for _ in range(users)])
                    elapsed = time.perf_counter() - t0

                    total = users * requests_per_user
                    expected = total - unreached[0]
                    prev_total = sum(sum(c.values())
                                     for c in before.values())
                    catchup_deadline = time.monotonic() + 10.0
                    body = None
                    while time.monotonic() < catchup_deadline:
                        body = await _debug_workers(
                            probe, router_url,
                            lag_window_s=time.perf_counter() - t0)
                        now_total = sum(
                            sum(c.values()) for c in
                            _outcomes_by_worker(body).values())
                        if now_total - prev_total >= expected:
                            break
                        await asyncio.sleep(0.1)
                    after = _outcomes_by_worker(body)
                    topology = [{"worker": row["worker"],
                                 "pid": row["pid"],
                                 "port": body.get("port", router_port)}
                                for row in body["per_worker"]]

                    outcomes_by_worker = {}
                    for wid in sorted(after):
                        prev = before.get(wid, {})
                        delta = {k: after[wid][k] - prev.get(k, 0)
                                 for k in after[wid]
                                 if after[wid][k] - prev.get(k, 0)}
                        if delta:
                            outcomes_by_worker[str(wid)] = delta
                    outcomes: dict = {}
                    for delta in outcomes_by_worker.values():
                        for k, v in delta.items():
                            outcomes[k] = outcomes.get(k, 0) + v
                    classified = sum(outcomes.values())
                    good = outcomes.get("ok", 0)
                    goodput = (round(good / classified, 4)
                               if classified else None)
                    lag_by_worker = {
                        str(row["worker"]):
                            (row.get("loop_lag_window") or {}).get("p99")
                        for row in body["per_worker"]}
                    completed = len(latencies)
                    responses = total - unreached[0]
                    rps = (round(completed / elapsed, 1)
                           if elapsed else None)
                    rung = {
                        "users": users,
                        "requests": total,
                        "completed": completed,
                        "failed": failed[0],
                        "responses": responses,
                        "unreached": unreached[0],
                        "elapsed_s": round(elapsed, 2),
                        "rps": rps,
                        "p50_latency_s": round(
                            sorted(latencies)[completed // 2], 4)
                        if latencies else None,
                        "p99_latency_s": round(_p99(latencies), 4)
                        if latencies else None,
                        "outcomes": outcomes,
                        "outcomes_by_worker": outcomes_by_worker,
                        "outcomes_classified": classified,
                        # Same invariant as r12/r13, now summed across
                        # workers: Σ per-worker classified outcomes ==
                        # responses (relaxed only on fd-shed rungs).
                        "outcomes_reconcile": (
                            classified == total if not unreached[0]
                            else responses <= classified <= total),
                        "goodput": goodput,
                        "loop_lag_p99_by_worker": lag_by_worker,
                        "loop_lag_p99_max_s": max(
                            (v for v in lag_by_worker.values()
                             if v is not None), default=None),
                    }
                    rungs.append(rung)
                    if rps is not None and knee is None:
                        rps_ceiling = max(rps_ceiling, rps)
                    if knee is None and goodput is not None \
                            and goodput < collapse_threshold:
                        knee = rung
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)
        for runner in runners:
            await runner.cleanup()

    return {
        "workers": workers,
        "rps_ceiling": rps_ceiling or None,
        "knee_users": knee["users"] if knee else None,
        "knee_goodput": knee["goodput"] if knee else None,
        "loop_lag_p99_at_knee":
            knee["loop_lag_p99_max_s"] if knee else None,
        "worker_topology": topology,
        "outcomes_reconcile_all": all(r["outcomes_reconcile"]
                                      for r in rungs),
        "rungs": rungs,
        "engine_requests": [len(e.requests_seen) for e in engines],
    }


async def run_saturation_workers_ab(*, steps=WORKERS_AB_STEPS,
                                    requests_per_user: int = 2,
                                    replicas: int = 4,
                                    worker_legs=(1, 4),
                                    engine_ttft: float = 0.001,
                                    client_timeout_s: float = 300.0,
                                    collapse_threshold: float = 0.9,
                                    ) -> dict:
    """1-vs-N-worker saturation A/B over the same engine fleet: the
    answer to "does SO_REUSEPORT alone move the r13 672 rps ceiling
    before the relay-off-loop work lands?" (ROADMAP item 2). The value
    is the multi-worker ceiling as a ratio of the single-worker one."""
    from production_stack_tpu.utils.misc import set_ulimit

    # Engines + clients share this process's fd budget (the router is a
    # subprocess and raises its own rlimit in main()).
    set_ulimit(target_soft_limit=max(65535, 4 * max(steps) + 8192))

    slo_file = tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", prefix="slo-sat-ab-", delete=False)
    yaml.safe_dump(SLO_CONFIG, slo_file)
    slo_file.close()

    legs = []
    try:
        for workers in worker_legs:
            legs.append(await _run_workers_leg(
                workers=workers, steps=steps,
                requests_per_user=requests_per_user, replicas=replicas,
                engine_ttft=engine_ttft,
                client_timeout_s=client_timeout_s,
                collapse_threshold=collapse_threshold,
                slo_config_path=slo_file.name))
    finally:
        os.unlink(slo_file.name)

    baseline = next((l for l in legs if l["workers"] == 1), legs[0])
    multi = next((l for l in legs if l["workers"] != 1), legs[-1])
    ratio = None
    if baseline["rps_ceiling"] and multi["rps_ceiling"]:
        ratio = round(multi["rps_ceiling"] / baseline["rps_ceiling"], 3)
    return {
        "metric": "router_saturation_workers_ab",
        "unit": "rps_ceiling_ratio",
        "value": ratio,
        # The single number that decides how to read the ratio: workers
        # beyond the core count share CPU, so SO_REUSEPORT spreads loop
        # lag without raising the ceiling.
        "host_cpus": os.cpu_count(),
        "replicas": replicas,
        "steps": list(steps),
        "requests_per_user": requests_per_user,
        "worker_legs": [l["workers"] for l in legs],
        "collapse_threshold": collapse_threshold,
        "slo_config": SLO_CONFIG,
        "rps_ceiling_1w": baseline["rps_ceiling"],
        "rps_ceiling_multi": multi["rps_ceiling"],
        "knee_users_1w": baseline["knee_users"],
        "knee_users_multi": multi["knee_users"],
        "outcomes_reconcile_all": all(l["outcomes_reconcile_all"]
                                      for l in legs),
        "legs": legs,
    }
