"""Fake OpenAI-compatible engine with controllable TTFT / token rate.

Mirrors the role of reference ``src/tests/perftest/fake-openai-server.py``:
lets the router's multi-backend behavior (routing, streaming, stats, metrics
scraping, sleep mode) be exercised hermetically with no TPU or cluster.

Serves: /v1/models, /v1/chat/completions, /v1/completions, /v1/embeddings,
/tokenize, /detokenize, /metrics (vllm:* exposition), /sleep, /wake_up,
/is_sleeping, /health, /v1/audio/transcriptions, /fault (fault injection),
/drain (graceful drain, mirroring the real engine server), and — with
``max_loras > 0`` — the LoRA residency surface (/v1/lora_adapters,
/v1/load_lora_adapter, /v1/unload_lora_adapter) with slot limits,
adapter-salted prefix-cache keys, and unknown-model 404s.

Fault injection (for the router fault-tolerance tests and BENCH_CHAOS):
POST /fault {"mode": ..., "after_chunks": N, "times": K} arms one of
``error_before_stream`` (500 before any body byte), ``hang_before_stream``
(accepts the request, never sends headers — the router's TTFT deadline
must fire), ``hang_mid_stream`` (streams ``after_chunks`` chunks then
stalls — the inter-chunk deadline must fire), ``crash_after_n_chunks``
(streams ``after_chunks`` chunks then drops the TCP connection).
``times`` bounds how many requests fault (-1 = until cleared); mode null
disarms. ``pull_error`` faults /kv/pull (500) instead of inference.
Connect-refuse is exercised by stopping the runner itself.

Fleet surface (hermetic mirror of the real engine's global-prefix-cache
integration): a simulated prefix cache keyed on the KV controller's chunk
hashes. A request whose leading chunks are cached skips that fraction of
its TTFT; completions admit their prompt's chunks and (after
``configure_kv``) report them to the router's /kv/admit. /kv/pull copies
matching chunks from an in-process peer (``run_fake_engine`` registry),
mirroring the real cross-replica transfer; /drain mirrors the real
server's controller deregistration.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid
from typing import Dict, List, Optional

from aiohttp import web

from production_stack_tpu.obs.trace import TraceRecorder
from production_stack_tpu.structured.api import (
    StructuredError, compile_char_dfa, parse_structured)


class FakeEngine:
    # url -> engine, for in-process /kv/pull peer copies (the fake analog
    # of the real server's _local_peers port registry).
    _peers: Dict[str, "FakeEngine"] = {}

    def __init__(
        self,
        model: str = "fake-model",
        ttft: float = 0.0,
        tokens_per_sec: float = 0.0,
        max_tokens_default: int = 16,
        models: Optional[List[str]] = None,
        simulate_contention: bool = False,
        enable_chunked_prefill: bool = False,
        prefill_chunks: int = 4,
        max_loras: int = 0,
    ):
        self.models = models or [model]
        self.ttft = ttft
        self.tokens_per_sec = tokens_per_sec
        self.max_tokens_default = max_tokens_default
        # Single-device contention model (default OFF — existing timing-
        # sensitive router tests rely on concurrent requests overlapping
        # freely): prefill work and decode token emission serialize on one
        # lock, like one TPU stepping one program at a time. An unchunked
        # prefill holds the lock for the full TTFT (so a concurrent
        # decode's inter-token gap can stall by up to that); chunked
        # prefill (``enable_chunked_prefill``) splits it into
        # ``prefill_chunks`` lock acquisitions, bounding any stall to
        # ttft / prefill_chunks.
        self.simulate_contention = simulate_contention
        self.enable_chunked_prefill = enable_chunked_prefill
        self.prefill_chunks = max(prefill_chunks, 1)
        self.prefill_chunks_total = 0
        # Speculative-decoding counters (static here: the fake engine does
        # no real drafting, it just exposes the tpu:spec_* scrape surface,
        # including the per-proposer source split and the draft-model
        # forward counter).
        self.spec_proposed_tokens_total = 0
        self.spec_accepted_tokens_total = 0
        self.spec_disabled_requests_total = 0
        self.spec_proposed_by_source = {"ngram": 0, "draft_model": 0}
        self.spec_accepted_by_source = {"ngram": 0, "draft_model": 0}
        self.spec_draft_forward_steps_total = 0
        # Structured output: compiled like the real engine (same
        # parse/compile path) but "generation" is the DFA's example
        # string, so router e2e conformance runs hermetically on CPU.
        self.structured_requests_total = 0
        self.structured_violations_total = 0
        self._engine_lock = asyncio.Lock()
        # QoS surface: the router's X-Priority / X-Tenant headers are
        # honored the way the real scheduler honors them — batch prefill
        # chunks defer while any interactive prefill is in flight — and
        # counted per tenant/priority for hermetic assertions.
        self._interactive_prefills = 0
        self._no_interactive = asyncio.Event()
        self._no_interactive.set()
        self.tenant_requests: Dict[str, int] = {}
        self.priority_requests: Dict[str, int] = {
            "interactive": 0, "batch": 0}
        self.sleeping = False
        # Fault injection state (see module docstring). ``fault_times``
        # counts down per faulted request; -1 means until disarmed.
        self.fault_mode: Optional[str] = None
        self.fault_after_chunks = 0
        self.fault_times = -1
        self.faults_injected = 0
        # Drain state mirroring the real engine server: /drain stops
        # admission (inference 503s), /health flips to 503, in-flight
        # requests finish.
        self.draining = False
        self.num_running = 0
        self.num_waiting = 0
        self.requests_seen: List[dict] = []
        self.kv_usage = 0.42
        # Fleet surface (see module docstring). ``self_url`` is stamped by
        # run_fake_engine once the real port is known; ``configure_kv``
        # registers with the router's KV controller.
        self.prefix_cache: "set[int]" = set()
        self.kv_controller_url: Optional[str] = None
        self.self_url: Optional[str] = None
        self.api_key: Optional[str] = None
        self.instance_id = f"fake-{uuid.uuid4().hex[:8]}"
        # Crash-consistency mirror of the real engine: a per-process
        # generation id (a restarted FakeEngine object is a new
        # incarnation), optional lease heartbeats, and the admitted
        # root-anchored chunk paths the anti-entropy resync reasserts.
        self.generation = uuid.uuid4().hex
        self.heartbeat_interval = 0.0
        self.admitted_paths: "set[tuple]" = set()
        self.crashed = False
        self._hb_task: Optional[asyncio.Task] = None
        self._runner: Optional[web.AppRunner] = None
        self._site: Optional[web.TCPSite] = None
        self.kv_pulls_received = 0
        self.kv_pulls_served = 0
        self.kv_pulls_rejected = 0
        # /kv/pull admission cap, mirroring the engine-side semaphore
        # (0 = unlimited, the historical fake behavior).
        self.kv_pull_max_concurrency = 0
        self._pull_inflight = 0
        self.pull_delay_s = 0.0
        # Parameterized transfer-latency model for the pull-economics
        # ledger and the crossover A/B: a served pull reports
        # ``transfer.bytes`` (chunks copied x bytes-per-chunk) and costs
        # ``pull_delay_s + bytes * pull_latency_s_per_byte`` of wall time.
        self.kv_pull_bytes_per_chunk = 4096
        self.pull_latency_s_per_byte = 0.0
        # Prompt-length-proportional prefill: TTFT grows by this much per
        # prompt character (0 keeps the historical fixed-TTFT behavior).
        # With it, recompute cost scales with prefix length the way a
        # real prefill does — the other half of the crossover physics.
        self.prefill_time_per_char_s = 0.0
        self.pull_requests: List[dict] = []
        self.prefix_cache_hits = 0
        self.prefix_cache_queries = 0
        self.hbm_headroom_bytes: float = -1.0  # >=0: scraped by autoscaler
        # LoRA surface (adapter-plane tests / BENCH_LORA), mirroring the
        # real engine's slot model: slot 0 is the base model, so a
        # max_loras of N holds N-1 resident adapters. 0 disables the
        # surface entirely — the historical fake accepts any model name,
        # and that stays true so timing tests keep their behavior; with
        # max_loras > 0 an unknown model 404s like the real server.
        self.max_loras = max_loras
        self.lora_adapters: Dict[str, float] = {}  # name -> load stamp
        # Simulated weight fetch: /v1/load_lora_adapter sleeps this long
        # before the adapter becomes resident (the cost the affinity-on
        # A/B leg avoids by pinning instead of thrashing slots).
        self.lora_load_delay_s = 0.0
        self.lora_loads = 0
        self.lora_unloads = 0
        self.lora_request_counts: Dict[str, int] = {}
        # Same trace surface as the real engine server: synthetic
        # queue/prefill/decode spans linked under the router's forwarded
        # traceparent, retrievable at /debug/traces/{request_id}.
        self.trace_recorder = TraceRecorder("fake-engine")

    # -- helpers -----------------------------------------------------------
    def _structured_content(self, body: dict):
        """(text, None) with a grammar-valid example string when the
        request carries a structured constraint, (None, 400 response)
        when the constraint doesn't compile, (None, None) otherwise.
        Uses the SAME parse/compile path as the real engine, so router
        e2e conformance tests exercise the production compiler."""
        try:
            spec = parse_structured(body)
            if spec is None:
                return None, None
            text = compile_char_dfa(spec).example()
        except StructuredError as exc:
            return None, web.json_response(
                {"error": {"message": str(exc),
                           "type": "BadRequestError"}}, status=400)
        self.structured_requests_total += 1
        return text, None

    def _take_fault(self) -> Optional[str]:
        """Claim the armed fault for this request (decrementing ``times``);
        returns the mode or None."""
        if self.fault_mode is None:
            return None
        if self.fault_times == 0:
            return None
        if self.fault_times > 0:
            self.fault_times -= 1
        self.faults_injected += 1
        return self.fault_mode

    def _token_delay(self) -> float:
        return 1.0 / self.tokens_per_sec if self.tokens_per_sec > 0 else 0.0

    def _count_request(self, request: web.Request) -> str:
        """Record the router's QoS headers; returns the priority class."""
        priority = (request.headers.get("X-Priority") or "interactive").lower()
        if priority not in ("interactive", "batch"):
            priority = "interactive"
        self.priority_requests[priority] = \
            self.priority_requests.get(priority, 0) + 1
        tenant = request.headers.get("X-Tenant")
        if tenant:
            self.tenant_requests[tenant] = \
                self.tenant_requests.get(tenant, 0) + 1
        return priority

    # -- fleet surface -----------------------------------------------------
    def _kv_headers(self) -> Dict[str, str]:
        if self.api_key:
            return {"Authorization": f"Bearer {self.api_key}"}
        return {}

    async def _kv_post(self, path: str, payload: dict) -> None:
        """Best-effort POST to the router's KV controller endpoints."""
        if self.kv_controller_url is None:
            return
        import aiohttp

        try:
            async with aiohttp.ClientSession() as sess:
                await sess.post(
                    f"{self.kv_controller_url}{path}", json=payload,
                    headers=self._kv_headers(),
                    timeout=aiohttp.ClientTimeout(total=5))
        except Exception:  # noqa: BLE001 - controller may be gone in tests
            pass

    async def configure_kv(self, controller_url: str,
                           api_key: Optional[str] = None,
                           heartbeat_interval: float = 0.0) -> None:
        """Register with the router's KV controller (call after
        run_fake_engine so ``self_url`` is stamped). A positive
        ``heartbeat_interval`` also starts the lease-heartbeat task,
        mirroring the real engine's --kv-heartbeat-interval."""
        self.kv_controller_url = controller_url.rstrip("/")
        self.api_key = api_key
        self.heartbeat_interval = float(heartbeat_interval)
        await self._kv_post("/kv/register", {
            "instance_id": self.instance_id, "url": self.self_url,
            "generation": self.generation,
            "heartbeat_interval": self.heartbeat_interval or None})
        if self.heartbeat_interval > 0 and self._hb_task is None:
            self._hb_task = asyncio.get_running_loop().create_task(
                self._heartbeat_loop())

    async def _heartbeat_loop(self) -> None:
        import aiohttp

        while True:
            await asyncio.sleep(self.heartbeat_interval)
            body: dict = {}
            try:
                async with aiohttp.ClientSession() as sess:
                    async with sess.post(
                        f"{self.kv_controller_url}/kv/heartbeat",
                        json={"instance_id": self.instance_id,
                              "generation": self.generation,
                              "heartbeat_interval": self.heartbeat_interval,
                              "url": self.self_url},
                        headers=self._kv_headers(),
                        timeout=aiohttp.ClientTimeout(total=5)) as resp:
                        if resp.status == 200:
                            body = await resp.json()
            except Exception:  # noqa: BLE001 - router may be gone in tests
                continue
            if not body.get("known"):
                await self._kv_post("/kv/register", {
                    "instance_id": self.instance_id, "url": self.self_url,
                    "generation": self.generation,
                    "heartbeat_interval": self.heartbeat_interval or None})
                await self.resync_now()
            elif body.get("revived"):
                await self.resync_now()

    async def resync_now(self) -> dict:
        """One anti-entropy round, same protocol as the real engine:
        digest check against the controller, full-state replace on
        mismatch. Public so tests can drive a cycle deterministically."""
        if self.kv_controller_url is None:
            return {"match": None}
        import aiohttp

        from production_stack_tpu.kv.controller import claim_digest, path_keys

        paths = [list(p) for p in sorted(self.admitted_paths)]
        keys: "set[int]" = set()
        for p in paths:
            keys.update(path_keys(p))
        count, xor = claim_digest(keys)
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.post(
                    f"{self.kv_controller_url}/kv/resync",
                    json={"instance_id": self.instance_id,
                          "count": count, "xor": xor},
                    headers=self._kv_headers(),
                    timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    check = await resp.json() if resp.status == 200 else {}
                if check.get("match"):
                    return {"match": True, "swept": 0}
                async with sess.post(
                    f"{self.kv_controller_url}/kv/resync_state",
                    json={"instance_id": self.instance_id, "paths": paths},
                    headers=self._kv_headers(),
                    timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    body = await resp.json() if resp.status == 200 else {}
            return {"match": False, **body}
        except Exception:  # noqa: BLE001 - controller may be gone in tests
            return {"match": None}

    def forget_prefix(self, prompt: str) -> None:
        """Drop a prompt's chunks locally WITHOUT reporting /kv/evict —
        the timeout-swallowed-evict drift the anti-entropy resync is
        built to detect and heal."""
        from production_stack_tpu.kv.controller import chunk_hashes

        self.admitted_paths.discard(tuple(chunk_hashes(prompt)))
        self.prefix_cache = set()
        for p in self.admitted_paths:
            self.prefix_cache.update(p)

    async def crash(self) -> None:
        """kill -9 simulation: heartbeats stop and the listening socket
        closes abruptly; in-flight connections are aborted. NO drain, NO
        /kv/deregister — the controller can only learn through missed
        lease beats, which is exactly what the chaos leg asserts."""
        self.crashed = True
        if self._hb_task is not None:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._hb_task = None
        if self.self_url and FakeEngine._peers.get(self.self_url) is self:
            del FakeEngine._peers[self.self_url]
        if self._runner is not None and self._runner.server is not None:
            for conn in list(self._runner.server.connections):
                transport = getattr(conn, "transport", None)
                if transport is not None:
                    transport.abort()
        if self._site is not None:
            await self._site.stop()
            self._site = None

    def _lora_check(self, body: dict):
        """(adapter_or_None, 404_response_or_None) for the request's
        model. With the LoRA surface on, an unknown model is a clean
        404 — same contract as the real server's _check_model, never a
        silent base-model fallback. Resident adapters are counted."""
        if self.max_loras <= 0:
            return None, None
        model = body.get("model")
        if model is None or model in self.models:
            return None, None
        if model not in self.lora_adapters:
            return None, web.json_response(
                {"error": {"message": f"model {model!r} not found",
                           "type": "NotFoundError"}}, status=404)
        self.lora_request_counts[model] = \
            self.lora_request_counts.get(model, 0) + 1
        return model, None

    def _prefix_hashes(self, body: dict) -> "List[int]":
        # The simulated prefix cache only exists once the engine is
        # wired to a KV controller (configure_kv) — otherwise repeat
        # prompts would skip their TTFT and break every timing-based
        # fake-engine test that reuses a prompt.
        if not self.kv_controller_url:
            return []
        from production_stack_tpu.kv.controller import chunk_hashes
        from production_stack_tpu.router.routing_logic import _extract_prompt

        prompt = _extract_prompt(body)
        if not prompt:
            return []
        # Adapter-salted keys, mirroring the real engine's admission
        # report: an adapter-addressed request's chunks can never match
        # a base-model (or other-adapter) prefix.
        model = body.get("model")
        salt = model if (model and model in self.lora_adapters) else None
        return chunk_hashes(prompt, salt=salt)

    def _cached_fraction(self, hashes: "List[int]") -> float:
        """Leading fraction of the prompt's chunks already held — that
        fraction of the TTFT is skipped, like real prefix-cache reuse."""
        if not hashes:
            return 0.0
        cached = 0
        for h in hashes:
            if h not in self.prefix_cache:
                break
            cached += 1
        self.prefix_cache_hits += cached
        self.prefix_cache_queries += len(hashes)
        return cached / len(hashes)

    async def _admit_prefix(self, hashes: "List[int]") -> None:
        if not hashes:
            return
        self.prefix_cache.update(hashes)
        self.admitted_paths.add(tuple(int(h) for h in hashes))
        if self.kv_controller_url:
            await self._kv_post("/kv/admit", {
                "instance_id": self.instance_id, "hashes": hashes})

    def _prompt_chars(self, body: dict) -> int:
        from production_stack_tpu.router.routing_logic import _extract_prompt

        return len(_extract_prompt(body) or "")

    async def _prefill_sleep(self, priority: str = "interactive",
                             cached_frac: float = 0.0,
                             prompt_chars: int = 0) -> int:
        """TTFT wait; under the contention model it holds the engine lock
        in 1 (unchunked) or ``prefill_chunks`` (chunked) slices. Returns
        the chunk count.

        Batch-class prefills defer between chunks while any interactive
        prefill is in flight — the fake-device analog of the real
        scheduler's priority admission + preemption, so the noisy-neighbor
        A/B observes the same TTFT protection hermetically."""
        base_ttft = (self.ttft
                     + prompt_chars * self.prefill_time_per_char_s)
        effective_ttft = base_ttft * (1.0 - cached_frac)
        if not self.simulate_contention:
            if effective_ttft > 0:
                await asyncio.sleep(effective_ttft)
            return 1
        chunks = self.prefill_chunks if self.enable_chunked_prefill else 1
        interactive = priority != "batch"
        if interactive:
            self._interactive_prefills += 1
            self._no_interactive.clear()
        try:
            for _ in range(chunks):
                if not interactive:
                    await self._no_interactive.wait()
                async with self._engine_lock:
                    if effective_ttft > 0:
                        await asyncio.sleep(effective_ttft / chunks)
        finally:
            if interactive:
                self._interactive_prefills -= 1
                if self._interactive_prefills == 0:
                    self._no_interactive.set()
        self.prefill_chunks_total += chunks
        return chunks

    async def _decode_step(self) -> None:
        """Per-token wait; under the contention model the emission also
        waits for the engine lock (a prefill in progress stalls it)."""
        await asyncio.sleep(self._token_delay())
        if self.simulate_contention:
            async with self._engine_lock:
                pass

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/v1/models", self.handle_models)
        app.router.add_post("/v1/chat/completions", self.handle_chat)
        app.router.add_post("/v1/completions", self.handle_completion)
        app.router.add_post("/v1/embeddings", self.handle_embeddings)
        app.router.add_post("/tokenize", self.handle_tokenize)
        app.router.add_post("/detokenize", self.handle_detokenize)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_post("/sleep", self.handle_sleep)
        app.router.add_post("/wake_up", self.handle_wake)
        app.router.add_get("/is_sleeping", self.handle_is_sleeping)
        app.router.add_get("/health", self.handle_health)
        app.router.add_post("/fault", self.handle_fault)
        app.router.add_post("/drain", self.handle_drain)
        app.router.add_post("/kv/pull", self.handle_kv_pull)
        app.router.add_get("/v1/lora_adapters", self.handle_list_lora)
        app.router.add_post("/v1/load_lora_adapter", self.handle_load_lora)
        app.router.add_post("/v1/unload_lora_adapter", self.handle_unload_lora)
        app.router.add_post("/v1/audio/transcriptions", self.handle_transcription)
        from production_stack_tpu.obs.debug import add_debug_routes

        add_debug_routes(app.router, self.trace_recorder)
        return app

    def _record_trace(self, request: web.Request, rid: str, model: str,
                      t_arrival: float, t_prefill_end: Optional[float],
                      n_tokens: int) -> None:
        """Engine-side stage timeline matching the real server's span
        names: queue (instant here), prefill (the TTFT sleep), decode
        (the token loop)."""
        now = time.time()
        trace = self.trace_recorder.begin(
            rid, request.headers.get("traceparent"))
        root = trace.start_span("engine.request", start=t_arrival,
                                model=model)
        trace.add_span("engine.queue", t_arrival, t_arrival, parent=root)
        prefill_end = t_prefill_end if t_prefill_end is not None else now
        chunks = (self.prefill_chunks if self.enable_chunked_prefill else 1) \
            if self.simulate_contention else 1
        trace.add_span("engine.prefill", t_arrival, prefill_end, parent=root,
                       prompt_tokens=5, cached_tokens=0, uncached_tokens=5,
                       prefill_chunks=chunks)
        trace.add_span("engine.decode", prefill_end, now, parent=root,
                       tokens=n_tokens, steps=n_tokens)
        root.finish(end=now, tokens=n_tokens)
        self.trace_recorder.record(trace)

    async def handle_models(self, request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [
                {"id": m, "object": "model", "created": int(time.time()),
                 "owned_by": "fake"} for m in self.models
            ],
        })

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        if self.draining:
            return web.json_response(
                {"error": {"message": "engine is draining",
                           "type": "ServiceUnavailable"}},
                status=503, headers={"Retry-After": "1"})
        # pull_error targets /kv/pull only — don't let inference claim it.
        fault = None if self.fault_mode == "pull_error" else self._take_fault()
        body = await request.json()
        self.requests_seen.append(body)
        _, not_found = self._lora_check(body)
        if not_found is not None:
            return not_found
        structured_text, bad = self._structured_content(body)
        if bad is not None:
            return bad
        prefix = self._prefix_hashes(body)
        cached_frac = self._cached_fraction(prefix)
        n_tokens = int(
            body.get("max_tokens")
            or body.get("max_completion_tokens")
            or self.max_tokens_default
        )
        pieces = ([structured_text] if structured_text is not None
                  else ["Hello "] * n_tokens)
        finish = "stop" if structured_text is not None else "length"
        stream = bool(body.get("stream", False))
        rid = (request.headers.get("X-Request-Id")
               or f"chatcmpl-{uuid.uuid4().hex[:12]}")
        model = body.get("model", self.models[0])
        t_arrival = time.time()
        t_prefill_end: Optional[float] = None
        priority = self._count_request(request)
        self.num_running += 1
        try:
            if fault == "error_before_stream":
                return web.json_response(
                    {"error": {"message": "injected upstream failure",
                               "type": "InternalServerError"}},
                    status=500)
            if fault == "hang_before_stream":
                # Accept but never answer: the router's TTFT deadline is
                # the only way out. Bounded so an un-deadlined client
                # (FT off) eventually errors instead of wedging the test.
                await asyncio.sleep(300)
                return web.json_response(
                    {"error": {"message": "injected hang elapsed",
                               "type": "InternalServerError"}},
                    status=500)
            await self._prefill_sleep(priority, cached_frac,
                                      self._prompt_chars(body))
            t_prefill_end = time.time()
            if not stream:
                for _ in range(len(pieces)):
                    await self._decode_step()
                return web.json_response({
                    "id": rid, "object": "chat.completion", "model": model,
                    "created": int(time.time()),
                    "choices": [{
                        "index": 0,
                        "message": {"role": "assistant",
                                    "content": "".join(pieces)},
                        "finish_reason": finish,
                    }],
                    "usage": {"prompt_tokens": 5,
                              "completion_tokens": len(pieces),
                              "total_tokens": 5 + len(pieces)},
                })
            resp = web.StreamResponse()
            resp.content_type = "text/event-stream"
            await resp.prepare(request)
            for i, piece in enumerate(pieces):
                if fault and i == self.fault_after_chunks:
                    if fault == "hang_mid_stream":
                        # Stall after N chunks: the router's inter-chunk
                        # deadline must fire. Bounded for FT-off tests.
                        await asyncio.sleep(300)
                    if fault == "crash_after_n_chunks":
                        # Drop the TCP connection mid-stream, as a
                        # crashing replica would.
                        if request.transport is not None:
                            request.transport.close()
                        return resp
                chunk = {
                    "id": rid, "object": "chat.completion.chunk",
                    "created": int(time.time()), "model": model,
                    "choices": [{
                        "index": 0,
                        "delta": ({"role": "assistant", "content": piece}
                                  if i == 0 else {"content": piece}),
                        "finish_reason": None,
                    }],
                }
                await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
                await self._decode_step()
            final = {
                "id": rid, "object": "chat.completion.chunk",
                "created": int(time.time()), "model": model,
                "choices": [{"index": 0, "delta": {}, "finish_reason": finish}],
            }
            await resp.write(f"data: {json.dumps(final)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        finally:
            self._record_trace(request, rid, model, t_arrival,
                               t_prefill_end, n_tokens)
            self.num_running -= 1
            await self._admit_prefix(prefix)

    async def handle_completion(self, request: web.Request) -> web.StreamResponse:
        if self.draining:
            return web.json_response(
                {"error": {"message": "engine is draining",
                           "type": "ServiceUnavailable"}},
                status=503, headers={"Retry-After": "1"})
        body = await request.json()
        self.requests_seen.append(body)
        _, not_found = self._lora_check(body)
        if not_found is not None:
            return not_found
        structured_text, bad = self._structured_content(body)
        if bad is not None:
            return bad
        n_tokens = int(body.get("max_tokens") or self.max_tokens_default)
        pieces = ([structured_text] if structured_text is not None
                  else ["Hello "] * n_tokens)
        finish = "stop" if structured_text is not None else "length"
        stream = bool(body.get("stream", False))
        rid = (request.headers.get("X-Request-Id")
               or f"cmpl-{uuid.uuid4().hex[:12]}")
        model = body.get("model", self.models[0])
        t_arrival = time.time()
        priority = self._count_request(request)
        prefix = self._prefix_hashes(body)
        await self._prefill_sleep(priority, self._cached_fraction(prefix),
                                  self._prompt_chars(body))
        await self._admit_prefix(prefix)
        t_prefill_end = time.time()
        if not stream:
            self._record_trace(request, rid, model, t_arrival,
                               t_prefill_end, n_tokens)
            return web.json_response({
                "id": rid, "object": "text_completion", "model": model,
                "created": int(time.time()),
                "choices": [{"index": 0, "text": "".join(pieces),
                             "finish_reason": finish}],
                "usage": {"prompt_tokens": 5,
                          "completion_tokens": len(pieces),
                          "total_tokens": 5 + len(pieces)},
            })
        resp = web.StreamResponse()
        resp.content_type = "text/event-stream"
        await resp.prepare(request)
        for piece in pieces:
            chunk = {
                "id": rid, "object": "text_completion",
                "created": int(time.time()), "model": model,
                "choices": [{"index": 0, "text": piece,
                             "finish_reason": None}],
            }
            await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
            await asyncio.sleep(self._token_delay())
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        self._record_trace(request, rid, model, t_arrival,
                           t_prefill_end, n_tokens)
        return resp

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        body = await request.json()
        inputs = body.get("input")
        if isinstance(inputs, str):
            inputs = [inputs]
        return web.json_response({
            "object": "list", "model": body.get("model", self.models[0]),
            "data": [{"object": "embedding", "index": i, "embedding": [0.0] * 8}
                     for i in range(len(inputs or []))],
            "usage": {"prompt_tokens": 0, "total_tokens": 0},
        })

    async def handle_tokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        text = body.get("prompt") or ""
        tokens = list(range(len(text.split())))
        return web.json_response({"tokens": tokens, "count": len(tokens)})

    async def handle_detokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        return web.json_response({"prompt": " ".join(map(str, body.get("tokens", [])))})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        text = (
            "# TYPE vllm:num_requests_running gauge\n"
            f"vllm:num_requests_running {self.num_running}\n"
            "# TYPE vllm:num_requests_waiting gauge\n"
            f"vllm:num_requests_waiting {self.num_waiting}\n"
            "# TYPE vllm:gpu_cache_usage_perc gauge\n"
            f"vllm:gpu_cache_usage_perc {self.kv_usage}\n"
            "# TYPE vllm:gpu_prefix_cache_hits counter\n"
            "vllm:gpu_prefix_cache_hits_total 30\n"
            "# TYPE vllm:gpu_prefix_cache_queries counter\n"
            "vllm:gpu_prefix_cache_queries_total 100\n"
            "# TYPE tpu:prefill_chunks counter\n"
            f"tpu:prefill_chunks_total {self.prefill_chunks_total}\n"
            "# TYPE tpu:spec_proposed_tokens counter\n"
            f'tpu:spec_proposed_tokens_total{{source="ngram"}} '
            f"{self.spec_proposed_by_source['ngram']}\n"
            f'tpu:spec_proposed_tokens_total{{source="draft_model"}} '
            f"{self.spec_proposed_by_source['draft_model']}\n"
            "# TYPE tpu:spec_accepted_tokens counter\n"
            f'tpu:spec_accepted_tokens_total{{source="ngram"}} '
            f"{self.spec_accepted_by_source['ngram']}\n"
            f'tpu:spec_accepted_tokens_total{{source="draft_model"}} '
            f"{self.spec_accepted_by_source['draft_model']}\n"
            "# TYPE tpu:spec_acceptance_rate gauge\n"
            f"tpu:spec_acceptance_rate "
            f"{(self.spec_accepted_tokens_total / self.spec_proposed_tokens_total) if self.spec_proposed_tokens_total else 0.0}\n"
            "# TYPE tpu:spec_disabled_requests counter\n"
            f"tpu:spec_disabled_requests_total {self.spec_disabled_requests_total}\n"
            "# TYPE tpu:spec_draft_forward_steps counter\n"
            f"tpu:spec_draft_forward_steps_total "
            f"{self.spec_draft_forward_steps_total}\n"
            "# TYPE tpu:structured_requests counter\n"
            f"tpu:structured_requests_total {self.structured_requests_total}\n"
            "# TYPE tpu:structured_violations counter\n"
            f"tpu:structured_violations_total {self.structured_violations_total}\n"
        )
        if self.hbm_headroom_bytes >= 0:
            text += (
                "# TYPE tpu:hbm_headroom_bytes gauge\n"
                f"tpu:hbm_headroom_bytes {self.hbm_headroom_bytes}\n"
            )
        if self.lora_request_counts:
            text += "# TYPE tpu:lora_requests counter\n"
            for name in sorted(self.lora_request_counts):
                text += (f'tpu:lora_requests_total{{adapter="{name}"}} '
                         f"{self.lora_request_counts[name]}\n")
        return web.Response(text=text, content_type="text/plain")

    async def handle_sleep(self, request: web.Request) -> web.Response:
        self.sleeping = True
        return web.json_response({"status": "sleeping"})

    async def handle_wake(self, request: web.Request) -> web.Response:
        self.sleeping = False
        return web.json_response({"status": "awake"})

    async def handle_is_sleeping(self, request: web.Request) -> web.Response:
        return web.json_response({"is_sleeping": self.sleeping})

    async def handle_health(self, request: web.Request) -> web.Response:
        if self.draining:
            return web.json_response(
                {"status": "draining", "in_flight": self.num_running},
                status=503, headers={"Retry-After": "1"})
        return web.json_response({"status": "ok"})

    async def handle_fault(self, request: web.Request) -> web.Response:
        """Arm/disarm fault injection (see module docstring)."""
        body = await request.json()
        mode = body.get("mode")
        valid = (None, "error_before_stream", "hang_before_stream",
                 "hang_mid_stream", "crash_after_n_chunks", "pull_error",
                 "crash")
        if mode not in valid:
            return web.json_response(
                {"error": f"unknown fault mode {mode!r}"}, status=400)
        if mode == "crash":
            # Immediate, not per-request: the whole process "dies" (see
            # crash()). Scheduled so this response can still be written.
            self.faults_injected += 1
            asyncio.get_running_loop().create_task(self.crash())
            return web.json_response({"mode": "crash", "status": "dying"})
        self.fault_mode = mode
        self.fault_after_chunks = int(body.get("after_chunks", 0))
        self.fault_times = int(body.get("times", -1))
        return web.json_response({
            "mode": self.fault_mode,
            "after_chunks": self.fault_after_chunks,
            "times": self.fault_times,
            "faults_injected": self.faults_injected,
        })

    async def handle_drain(self, request: web.Request) -> web.Response:
        """Mirror of the real engine server's /drain: stop admission,
        wait for in-flight requests, report drained/draining."""
        try:
            timeout_s = float(request.query.get("timeout_s", "30"))
        except ValueError:
            return web.json_response({"error": "bad timeout_s"}, status=400)
        first_drain = not self.draining
        self.draining = True
        if first_drain and self.kv_controller_url:
            # Mirror the real server: a draining replica's cache is about
            # to disappear — stop advertising it to the controller.
            await self._kv_post("/kv/deregister",
                                {"instance_id": self.instance_id})
        deadline = time.monotonic() + timeout_s
        while self.num_running > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        drained = self.num_running == 0
        return web.json_response(
            {"status": "drained" if drained else "draining",
             "in_flight": self.num_running},
            status=200 if drained else 202)

    async def handle_kv_pull(self, request: web.Request) -> web.Response:
        """Cross-replica KV pull, same contract as the real engine server:
        body {"source_url", "request"}; copies the source peer's matching
        leading chunks into this engine's cache so the imminent inference
        request sees them as cached (the TTFT win the router measures)."""
        body = await request.json()
        self.pull_requests.append(body)
        if (self.kv_pull_max_concurrency > 0
                and self._pull_inflight >= self.kv_pull_max_concurrency):
            # Engine-side stampede control mirror: admission full.
            self.kv_pulls_rejected += 1
            return web.json_response(
                {"status": "rejected", "error": "pull admission full"},
                status=503, headers={"Retry-After": "1"})
        if self.fault_mode == "pull_error" and self.fault_times != 0:
            if self.fault_times > 0:
                self.fault_times -= 1
            self.faults_injected += 1
            return web.json_response(
                {"error": "injected pull failure"}, status=500)
        source_url = str(body.get("source_url") or "").rstrip("/")
        hashes = self._prefix_hashes(body.get("request") or {})
        peer = FakeEngine._peers.get(source_url)
        self._pull_inflight += 1
        t0 = time.monotonic()
        try:
            if self.pull_delay_s > 0:
                # Simulated per-pull overhead (control round-trip), so
                # stampede tests can observe real overlap at the
                # admission gate.
                await asyncio.sleep(self.pull_delay_s)
            if peer is None or not hashes:
                return web.json_response(
                    {"status": "miss", "injected_blocks": 0})
            matched = []
            for h in hashes:
                if h not in peer.prefix_cache:
                    break
                matched.append(h)
            if not matched:
                return web.json_response(
                    {"status": "miss", "injected_blocks": 0})
            bytes_moved = len(matched) * self.kv_pull_bytes_per_chunk
            if self.pull_latency_s_per_byte > 0:
                # Size-proportional transfer time: the measurable half of
                # the pull-economics model.
                await asyncio.sleep(bytes_moved * self.pull_latency_s_per_byte)
            self.prefix_cache.update(matched)
            peer.kv_pulls_served += 1
            self.kv_pulls_received += 1
            return web.json_response({
                "status": "ok", "injected_blocks": len(matched),
                "num_tokens": len(matched),
                "transfer": {"path": "fake-peer", "bytes": bytes_moved,
                             "total_seconds": round(
                                 time.monotonic() - t0, 6)}})
        finally:
            self._pull_inflight -= 1

    async def handle_transcription(self, request: web.Request) -> web.Response:
        await request.post()
        return web.json_response({"text": "fake transcription"})

    # -- LoRA surface ------------------------------------------------------
    async def handle_list_lora(self, request: web.Request) -> web.Response:
        """Residency scrape surface, same shape as the real server's
        enriched /v1/lora_adapters (what AdapterRegistry parses)."""
        return web.json_response({
            "adapters": [{"lora_name": name}
                         for name in sorted(self.lora_adapters)],
            "max_loras": self.max_loras,
            "capacity": max(self.max_loras - 1, 0),
            "base_model": self.models[0],
        })

    async def handle_load_lora(self, request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        if not name:
            return web.json_response(
                {"error": {"message": "lora_name required",
                           "type": "BadRequestError"}}, status=400)
        if name in self.lora_adapters:
            return web.json_response(
                {"status": "ok", "lora_name": name, "already_resident": True})
        if len(self.lora_adapters) >= max(self.max_loras - 1, 0):
            # Same 400 the real engine returns on a full slot table —
            # the registry's cue to LRU-evict and retry.
            return web.json_response(
                {"error": {"message": (
                    f"could not load adapter {name!r} "
                    "(no free slots or LoRA disabled)"),
                    "type": "BadRequestError"}}, status=400)
        if self.lora_load_delay_s > 0:
            # Simulated weight fetch / swap-in.
            await asyncio.sleep(self.lora_load_delay_s)
        self.lora_adapters[name] = time.monotonic()
        self.lora_loads += 1
        return web.json_response({"status": "ok", "lora_name": name})

    async def handle_unload_lora(self, request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        if name not in self.lora_adapters:
            return web.json_response(
                {"error": {"message": f"adapter {name!r} not loaded",
                           "type": "NotFoundError"}}, status=404)
        del self.lora_adapters[name]
        self.lora_unloads += 1
        return web.json_response({"status": "ok", "lora_name": name})


async def run_fake_engine(engine: FakeEngine, host: str, port: int) -> web.AppRunner:
    app = engine.make_app()
    bound: "List[str]" = []

    async def _unregister(app):
        # Drop the peer registration so a recycled port can't resolve to a
        # stopped engine's cache (same guard as the real server).
        if engine._hb_task is not None:
            engine._hb_task.cancel()
            try:
                await engine._hb_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            engine._hb_task = None
        if bound and FakeEngine._peers.get(bound[0]) is engine:
            del FakeEngine._peers[bound[0]]

    app.on_cleanup.append(_unregister)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    real_port = site._server.sockets[0].getsockname()[1]
    url = f"http://{host}:{real_port}"
    bound.append(url)
    FakeEngine._peers[url] = engine
    engine.self_url = url
    engine._runner = runner
    engine._site = site
    return runner


def main() -> None:
    parser = argparse.ArgumentParser(description="Fake OpenAI engine")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--model", default="fake-model")
    parser.add_argument("--ttft", type=float, default=0.0)
    parser.add_argument("--tokens-per-sec", type=float, default=0.0)
    parser.add_argument("--simulate-contention", action="store_true",
                        default=False,
                        help="serialize prefill/decode on one lock (one "
                             "fake device) so arrival storms stall decode")
    parser.add_argument("--enable-chunked-prefill", action="store_true",
                        default=False,
                        help="with --simulate-contention: prefills yield "
                             "the device between chunks")
    parser.add_argument("--prefill-chunks", type=int, default=4)
    args = parser.parse_args()

    async def _run():
        engine = FakeEngine(
            args.model, args.ttft, args.tokens_per_sec,
            simulate_contention=args.simulate_contention,
            enable_chunked_prefill=args.enable_chunked_prefill,
            prefill_chunks=args.prefill_chunks)
        await run_fake_engine(engine, args.host, args.port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_run())


if __name__ == "__main__":
    main()
