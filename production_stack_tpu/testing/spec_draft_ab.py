"""Draft-model speculative decoding A/B harness.

Hermetic (real :class:`EngineCore` on CPU, tiny zoo models, one device),
two measurements that bracket what ``--speculative-draft-model`` buys:

- **Non-repetitive text** (``run_nonrepetitive_ab``): prompts with no
  repeated n-grams, where prompt lookup has nothing to propose — its
  tokens-per-forward pins to ~1.0 — while a draft model proposes on any
  text. The drafter here is the TARGET model itself (tiny-llama
  drafting tiny-llama: identical weights, so greedy drafts are always
  right), measuring the plumbing's ceiling on this workload rather than
  a particular big/small model pairing.

- **Structured JSON traffic** (``run_structured_composition``): the
  SAME grammar-constrained traffic decoded three ways. Without
  speculation a structured row is scheduled one step per burst (the
  host must observe each token before shipping the next mask), so
  ``structured_alone`` sets the floor. ``drafter_alone`` runs the
  drafter with FSM-threading ablated
  (``speculative_draft_constrain=False``): the drafter proposes
  unconstrained tokens, verify rejects at the first out-of-grammar
  position, and the adaptive fallback latches drafting off — the
  drafter alone buys little on constrained traffic.
  ``structured_drafter`` threads the token FSM into the drafter (the
  creative-twist composition): masked drafts stay inside the grammar,
  acceptance recovers, and constrained rows get multi-token bursts —
  beating both ablations on the same traffic.

Tokens-per-forward is ``generation_tokens_total /
decode_forward_steps_total`` — TARGET forwards only; drafter forwards
are reported separately (``spec_draft_forward_steps_total``) exactly as
the metrics surface splits them.

Used by ``bench.py`` (``BENCH_SPEC_DRAFT=1`` ->
``BENCH_SPEC_DRAFT_r20.json``) and ``tests/test_benchmark_harness.py``
(artifact schema).
"""

from __future__ import annotations

import queue
import time
from typing import List, Optional, Tuple

# JSON-ish value grammar: every structural char is a forced (single
# allowed token) FSM state; only the 16 [ab] payload positions leave
# the drafter a real choice.
JSON_REGEX = '\\{"k": "[ab]{16}"\\}'

#: Prompt token streams with no repeated trigram (prompt lookup finds
#: no earlier occurrence of any current n-gram, so it drafts nothing).
NONREP_PROMPTS = (
    [31, 7, 2, 19, 44, 3, 28, 11],
    [13, 41, 5, 23, 37, 8, 29, 17, 47, 2],
    [6, 43, 12, 30, 9, 25, 40, 15],
)


def _make_engine(**over):
    import jax

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.core import EngineCore

    kwargs = dict(
        model="tiny-llama", max_model_len=256, max_num_seqs=4,
        block_size=8, num_blocks=128, min_prefill_bucket=16, max_loras=0,
    )
    kwargs.update(over)
    eng = EngineCore(EngineConfig(**kwargs), devices=jax.devices()[:1])
    eng.start()
    return eng


def _run_leg(eng, requests: List[Tuple[str, list, object]],
             timeout_s: float = 600.0) -> dict:
    """Submit all requests, drain to completion, snapshot the spec
    accounting. ``failed`` counts requests that finished with an error
    (or never finished — that raises instead)."""
    done: "queue.Queue" = queue.Queue()
    finishes = {}
    counts = {}

    def make_cb(rid):
        def on_token(token, finish):
            if token is not None:
                counts[rid] = counts.get(rid, 0) + 1
            if finish is not None:
                finishes[rid] = finish
                done.put(rid)
        return on_token

    t0 = time.perf_counter()
    for rid, prompt_ids, sampling in requests:
        eng.add_request(rid, list(prompt_ids), sampling, make_cb(rid))
    remaining = len(requests)
    deadline = time.time() + timeout_s
    while remaining > 0 and time.time() < deadline:
        try:
            done.get(timeout=1.0)
            remaining -= 1
        except queue.Empty:
            continue
    wall = time.perf_counter() - t0
    if remaining:
        raise RuntimeError(f"{remaining} bench requests never finished")
    failed = sum(1 for f in finishes.values()
                 if f not in ("length", "stop"))
    return {
        "requests": len(requests),
        "failed_requests": failed,
        "generated_tokens": int(eng.generation_tokens_total),
        "decode_forwards": int(eng.decode_forward_steps_total),
        "tokens_per_forward": round(
            eng.generation_tokens_total
            / max(eng.decode_forward_steps_total, 1), 4),
        "wall_s": round(wall, 3),
        "spec_proposed_by_source": dict(eng.spec_proposed_by_source),
        "spec_accepted_by_source": dict(eng.spec_accepted_by_source),
        "spec_draft_forward_steps": int(eng.spec_draft_forward_steps_total),
        "spec_disabled_requests": int(eng.spec_disabled_requests_total),
    }


def _greedy_reqs(prefix: str, max_tokens: int,
                 guided_regex: Optional[str] = None,
                 n: int = 3) -> List[Tuple[str, list, object]]:
    from production_stack_tpu.engine.sampling import SamplingParams

    reqs = []
    for i in range(n):
        body = {"temperature": 0, "max_tokens": max_tokens,
                "ignore_eos": guided_regex is None}
        if guided_regex is not None:
            body["guided_regex"] = guided_regex
        reqs.append((f"{prefix}{i}", NONREP_PROMPTS[i % len(NONREP_PROMPTS)],
                     SamplingParams.from_request(body)))
    return reqs


def run_nonrepetitive_ab(*, max_tokens: int = 32, spec_tokens: int = 4) -> dict:
    """Prompt lookup vs draft model on text with no internal repeats."""
    ngram = _make_engine(speculative_num_tokens=spec_tokens)
    try:
        leg_ngram = _run_leg(ngram, _greedy_reqs("ng", max_tokens))
    finally:
        ngram.stop()
    draft = _make_engine(speculative_num_tokens=spec_tokens,
                         speculative_draft_model="tiny-llama")
    try:
        leg_draft = _run_leg(draft, _greedy_reqs("dm", max_tokens))
    finally:
        draft.stop()
    ratio = (leg_draft["tokens_per_forward"]
             / max(leg_ngram["tokens_per_forward"], 1e-9))
    return {
        "max_tokens": max_tokens,
        "speculative_num_tokens": spec_tokens,
        "prompt_lookup": leg_ngram,
        "draft_model": leg_draft,
        "tokens_per_forward_ratio": round(ratio, 4),
    }


def run_structured_composition(*, spec_tokens: int = 4,
                               draft_model: str = "tiny-llama") -> dict:
    """structured+drafter vs structured-alone vs drafter-alone, all on
    the same grammar-constrained traffic."""
    # max_tokens generously past the grammar's length: the regex
    # finishes the request itself, so every leg emits the full value.
    max_tokens = 32

    alone = _make_engine()
    try:
        leg_structured = _run_leg(
            alone, _greedy_reqs("sa", max_tokens, guided_regex=JSON_REGEX))
    finally:
        alone.stop()

    # FSM-threading ablated: the drafter alone, blind to the grammar.
    unconstrained = _make_engine(speculative_num_tokens=spec_tokens,
                                 speculative_draft_model=draft_model,
                                 speculative_draft_constrain=False)
    try:
        leg_drafter = _run_leg(
            unconstrained,
            _greedy_reqs("da", max_tokens, guided_regex=JSON_REGEX))
    finally:
        unconstrained.stop()

    both = _make_engine(speculative_num_tokens=spec_tokens,
                        speculative_draft_model=draft_model)
    try:
        leg_both = _run_leg(
            both, _greedy_reqs("sd", max_tokens, guided_regex=JSON_REGEX))
        violations = int(both.stats()["structured_violations_total"])
    finally:
        both.stop()

    return {
        "guided_regex": JSON_REGEX,
        "speculative_num_tokens": spec_tokens,
        "draft_model": draft_model,
        "structured_alone": leg_structured,
        "drafter_alone": leg_drafter,
        "structured_drafter": leg_both,
        "structured_violations": violations,
        "beats_structured_alone": (
            leg_both["tokens_per_forward"]
            > leg_structured["tokens_per_forward"]),
        "beats_drafter_alone": (
            leg_both["tokens_per_forward"]
            > leg_drafter["tokens_per_forward"]),
    }


def run_spec_draft_ab(*, max_tokens: int = 32, spec_tokens: int = 4) -> dict:
    nonrep = run_nonrepetitive_ab(max_tokens=max_tokens,
                                  spec_tokens=spec_tokens)
    structured = run_structured_composition(spec_tokens=spec_tokens)
    failed = (nonrep["prompt_lookup"]["failed_requests"]
              + nonrep["draft_model"]["failed_requests"]
              + structured["structured_alone"]["failed_requests"]
              + structured["drafter_alone"]["failed_requests"]
              + structured["structured_drafter"]["failed_requests"])
    return {
        "metric": "spec_draft_ab",
        "unit": "tokens_per_forward_ratio",
        "value": nonrep["tokens_per_forward_ratio"],
        "nonrepetitive": nonrep,
        "structured_json": structured,
        "failed_requests": failed,
    }
