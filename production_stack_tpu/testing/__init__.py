"""Test fixtures: fake engine backend and load generator.

The reference's load-bearing fixture is a fake vLLM backend with controllable
token rate and TTFT (``src/tests/perftest/fake-openai-server.py:31-80``);
this package provides the same for the TPU stack, importable from unit tests
and runnable standalone for router perf testing.
"""

from production_stack_tpu.testing.fake_engine import FakeEngine, run_fake_engine

__all__ = ["FakeEngine", "run_fake_engine"]
