"""Hermetic two-tenant noisy-neighbor A/B: QoS on vs off vs unloaded.

The physics, with no TPU and no model: a :class:`FakeEngine` in
contention mode serializes prefill chunks on one lock (one device). A
batch tenant floods it with concurrent prefills while an interactive
tenant sends one request at a time and measures TTFT.

- **unloaded** leg: interactive requests alone — the TTFT floor.
- **qos_on** leg: the router runs with a tenants file.  The batch
  tenant's requests carry ``X-Priority: batch`` (assigned by the router
  from tenant config — the flood clients never set the header
  themselves), the fair queue caps how many reach the engine at once,
  and the engine defers batch prefill chunks while an interactive
  prefill is in flight.  Interactive TTFT stays near the floor.
- **qos_off** leg: same traffic, no tenants file.  Every request is
  equal, the flood serializes the device, and interactive TTFT degrades
  by roughly the number of concurrent prefills.

Used by ``bench.py`` (BENCH_QOS=1) and
``tests/test_qos_noisy_neighbor.py``.
"""

from __future__ import annotations

import asyncio
import json
import statistics
import time
from typing import List, Optional

MODEL = "qos-model"
INTERACTIVE_KEY = "sk-qos-interactive"
BATCH_KEY = "sk-qos-batch"


def write_tenants_file(path: str, *, max_concurrency: int = 2,
                       shed_queue_depth: int = 256) -> str:
    """Two-tenant config: a weighted interactive tenant and a batch
    tenant whose requests are classed batch without any client header."""
    config = {
        "max_concurrency": max_concurrency,
        "shed_queue_depth": shed_queue_depth,
        "tenants": [
            {"name": "interactive-tenant",
             "api_keys": [INTERACTIVE_KEY],
             "weight": 4,
             "priority": "interactive"},
            {"name": "batch-tenant",
             "api_keys": [BATCH_KEY],
             "weight": 1,
             "priority": "batch"},
        ],
    }
    with open(path, "w") as f:
        json.dump(config, f)
    return path


def _reset_router_singletons() -> None:
    from production_stack_tpu.router import routing_logic as rl
    from production_stack_tpu.router.engine_stats import EngineStatsScraper
    from production_stack_tpu.router.request_stats import RequestStatsMonitor
    from production_stack_tpu.utils.misc import SingletonABCMeta, SingletonMeta

    for cls in (
        rl.RoundRobinRouter, rl.SessionRouter, rl.PrefixAwareRouter,
        rl.KvawareRouter, rl.DisaggregatedPrefillRouter,
    ):
        SingletonABCMeta._reset_instance(cls)
    SingletonMeta._reset_instance(RequestStatsMonitor)
    SingletonMeta._reset_instance(EngineStatsScraper)


async def _start(app):
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


def _p99(values: List[float]) -> Optional[float]:
    if not values:
        return None
    return sorted(values)[
        min(len(values) - 1, max(0, -(-99 * len(values) // 100) - 1))]


async def _interactive_ttft(session, router_url: str) -> float:
    """One streamed interactive request; returns TTFT (first content
    chunk). Raises on any non-200."""
    import aiohttp

    t0 = time.perf_counter()
    ttft = None
    async with session.post(
        router_url + "/v1/chat/completions",
        json={"model": MODEL, "max_tokens": 2, "stream": True,
              "messages": [{"role": "user", "content": "quick question"}]},
        headers={"Authorization": f"Bearer {INTERACTIVE_KEY}"},
        timeout=aiohttp.ClientTimeout(total=300),
    ) as resp:
        if resp.status != 200:
            raise RuntimeError(
                f"interactive request failed: {resp.status}")
        async for line in resp.content:
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            chunk = json.loads(line[len("data: "):])
            if ttft is None and \
                    chunk["choices"][0].get("delta", {}).get("content"):
                ttft = time.perf_counter() - t0
    if ttft is None:
        raise RuntimeError("stream produced no content")
    return ttft


async def _run_leg(*, qos_on: bool, tenants_file: Optional[str],
                   flood: int, interactive_requests: int, ttft_s: float,
                   prefill_chunks: int) -> dict:
    import aiohttp

    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import build_parser
    from production_stack_tpu.testing.fake_engine import FakeEngine

    _reset_router_singletons()
    engine = FakeEngine(
        model=MODEL, ttft=ttft_s, tokens_per_sec=0.0,
        max_tokens_default=2, simulate_contention=True,
        enable_chunked_prefill=True, prefill_chunks=prefill_chunks)
    engine_runner, engine_url = await _start(engine.make_app())
    args = build_parser().parse_args([])
    args.static_backends = engine_url
    args.static_models = MODEL
    args.routing_logic = "roundrobin"
    args.engine_stats_interval = 60
    if qos_on:
        args.qos_tenants_file = tenants_file
    router_app = build_app(args)
    router_runner, router_url = await _start(router_app)

    stop = asyncio.Event()
    flood_stats = {"completed": 0, "failed": 0}

    async def one_flood(session):
        # Continuous batch pressure: each client re-fires as soon as its
        # previous request finishes, for the whole interactive phase.
        # No X-Priority header — with QoS on the router classes these
        # batch from tenant config; with QoS off they are plain traffic.
        while not stop.is_set():
            try:
                async with session.post(
                    router_url + "/v1/chat/completions",
                    json={"model": MODEL, "max_tokens": 2,
                          "messages": [{"role": "user",
                                        "content": "offline batch job " * 4}]},
                    headers={"Authorization": f"Bearer {BATCH_KEY}"},
                    timeout=aiohttp.ClientTimeout(total=300),
                ) as resp:
                    await resp.read()
                    key = "completed" if resp.status == 200 else "failed"
                    flood_stats[key] += 1
            except (aiohttp.ClientError, asyncio.TimeoutError):
                flood_stats["failed"] += 1

    ttfts: List[float] = []
    errors = 0
    try:
        async with aiohttp.ClientSession() as session:
            # Warm connections / compile-free first hop before timing.
            await _interactive_ttft(session, router_url)
            flood_tasks = [asyncio.ensure_future(one_flood(session))
                           for _ in range(flood)]
            if flood:
                await asyncio.sleep(ttft_s)  # let the flood saturate
            try:
                for _ in range(interactive_requests):
                    try:
                        ttfts.append(
                            await _interactive_ttft(session, router_url))
                    except RuntimeError:
                        errors += 1
            finally:
                stop.set()
                # Drain in-flight flood requests (cancelling mid-stream
                # just litters the log with closed-transport errors);
                # cancellation is only the hang backstop.
                if flood_tasks:
                    _, pending = await asyncio.wait(
                        flood_tasks, timeout=ttft_s * flood + 10)
                    for t in pending:
                        t.cancel()
                    await asyncio.gather(
                        *flood_tasks, return_exceptions=True)
    finally:
        await router_runner.cleanup()
        await engine_runner.cleanup()
        _reset_router_singletons()

    return {
        "qos_on": qos_on,
        "flood": flood,
        "requests": len(ttfts),
        "errors": errors,
        "p50_ttft_s": round(statistics.median(ttfts), 4) if ttfts else None,
        "p99_ttft_s": round(_p99(ttfts), 4) if ttfts else None,
        "flood_completed": flood_stats["completed"],
        "flood_failed": flood_stats["failed"],
        "engine_priority_requests": dict(engine.priority_requests),
        "engine_tenant_requests": dict(engine.tenant_requests),
    }


async def run_qos_ab(tenants_file: str, *, flood: int = 16,
                     interactive_requests: int = 6, ttft_s: float = 0.3,
                     prefill_chunks: int = 8) -> dict:
    """Run the three legs back to back; returns the A/B result dict.

    ``tenants_file`` must already exist (see :func:`write_tenants_file`).
    """
    unloaded = await _run_leg(
        qos_on=False, tenants_file=None, flood=0,
        interactive_requests=interactive_requests, ttft_s=ttft_s,
        prefill_chunks=prefill_chunks)
    qos_on = await _run_leg(
        qos_on=True, tenants_file=tenants_file, flood=flood,
        interactive_requests=interactive_requests, ttft_s=ttft_s,
        prefill_chunks=prefill_chunks)
    qos_off = await _run_leg(
        qos_on=False, tenants_file=None, flood=flood,
        interactive_requests=interactive_requests, ttft_s=ttft_s,
        prefill_chunks=prefill_chunks)
    base = unloaded["p99_ttft_s"] or 1e-9
    return {
        "metric": "qos_noisy_neighbor_ab",
        "unit": "p99_ttft_ratio_vs_unloaded",
        "value": round(qos_on["p99_ttft_s"] / base, 3)
        if qos_on["p99_ttft_s"] else None,
        "qos_off_ratio": round(qos_off["p99_ttft_s"] / base, 3)
        if qos_off["p99_ttft_s"] else None,
        "ttft_s": ttft_s,
        "prefill_chunks": prefill_chunks,
        "batch_flood": flood,
        "interactive_requests": interactive_requests,
        "unloaded": unloaded,
        "qos_on": qos_on,
        "qos_off": qos_off,
    }
